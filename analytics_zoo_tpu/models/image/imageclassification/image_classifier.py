"""ImageClassifier (reference
`Z/models/image/imageclassification/ImageClassifier.scala:55` + config
registry): a ZooModel dispatching to named architectures."""

from __future__ import annotations

from typing import Optional, Tuple

from analytics_zoo_tpu.models.common import ZooModel


def _fused_resnet() -> bool:
    """ZOO_TPU_FUSED_RESNET: "1"/"0" pin the fused Pallas conv+BN
    bottlenecks (`ops/conv_bn.py`) on/off; "auto" (the default) routes
    fused on a TPU backend once `conv_bn.fused_profitable()` reports a
    measured on-chip win — the same policy shape as attention's
    flash "auto" (`ops/attention.py:33-61`)."""
    import os
    mode = os.environ.get("ZOO_TPU_FUSED_RESNET", "auto")
    if mode == "auto":
        from analytics_zoo_tpu.ops.conv_bn import fused_profitable
        return fused_profitable()
    return mode == "1"


def _build_resnet(depth, s, c, fused=False):
    from analytics_zoo_tpu.models.image.imageclassification.resnet \
        import ResNet
    return ResNet(depth).build(s, c, fused=fused)


def _builders():
    """Single name→builder registry; ARCHS derives from its keys so the
    validation tuple and the dispatch can never drift. ResNet builders
    accept ``fused=`` (the rest are fixed-layout)."""
    import functools

    from analytics_zoo_tpu.models.image.imageclassification import archs
    from analytics_zoo_tpu.models.image.imageclassification.lenet import \
        lenet5
    reg = {
        "lenet-5": lenet5,
        "vgg-16": archs.vgg16,
        "vgg-19": archs.vgg19,
        "inception-v1": archs.inception_v1,
        "mobilenet": archs.mobilenet,
        "mobilenet-v2": archs.mobilenet_v2,
        "densenet-121": archs.densenet121,
        "squeezenet": archs.squeezenet,
    }
    for d in (50, 101, 152):
        reg[f"resnet-{d}"] = functools.partial(_build_resnet, d)
    return reg


class ImageClassifier(ZooModel):
    """``ImageClassifier(model_name="resnet-50")`` — named-architecture
    image classification (the pretrained-weight registry of the reference
    maps to `load_model` files here)."""

    class _ArchList:
        """Class-level descriptor so both ``ImageClassifier.ARCHS`` and
        ``instance.ARCHS`` yield the architecture-name tuple."""

        def __get__(self, obj, objtype=None):
            return tuple(_builders())

    ARCHS = _ArchList()

    def __init__(self, model_name: str = "resnet-50",
                 input_shape: Tuple[int, int, int] = (224, 224, 3),
                 classes: int = 1000,
                 fused: Optional[bool] = None):
        """``fused``: ResNets only — build with the fused Pallas
        conv+BN bottlenecks. None resolves the ``ZOO_TPU_FUSED_RESNET``
        env default AT CONSTRUCTION and the resolved value persists in
        ``hyper_parameters`` (a checkpoint reloads the architecture it
        was saved with, regardless of the loading process's env)."""
        super().__init__()
        name = model_name.lower()
        if name not in _builders():
            raise ValueError(f"unknown architecture '{model_name}'; "
                             f"known: {tuple(_builders())}")
        self.model_name = name
        self.input_shape = tuple(input_shape)
        self.classes = int(classes)
        if fused is None:
            fused = name.startswith("resnet-") and _fused_resnet()
        self.fused = bool(fused)
        if self.fused and not name.startswith("resnet-"):
            raise ValueError(f"fused=True is ResNet-only, not {name}")

    def load_weights(self, path: str):
        """Load a ``save_weights`` ``.npz``; for ResNets a checkpoint
        saved in a DIFFERENT layout (unfused ↔ per-block fused ↔
        stage) is converted on the fly via `convert_resnet_params` —
        the checkpoint-portability leg of the fused "auto" default:
        existing unfused checkpoints load into the fused TPU runtime
        without user action."""
        try:
            return super().load_weights(path)
        except KeyError:
            if not self.model_name.startswith("resnet-"):
                raise
        import jax
        import numpy as np

        from analytics_zoo_tpu.models.image.imageclassification \
            .resnet import convert_resnet_params
        est = self.model.estimator
        if est.params is None:
            est._ensure_initialized()
        nested: dict = {}
        with np.load(path) as data:
            for key in data.files:
                parts = key.split("/")
                d = nested
                for p in parts[:-1]:
                    d = d.setdefault(p, {})
                d[parts[-1]] = data[key]
        target = jax.device_get(est.params)
        converted = convert_resnet_params(nested, target)
        for (kp1, l1), (kp2, l2) in zip(
                jax.tree_util.tree_leaves_with_path(converted),
                jax.tree_util.tree_leaves_with_path(target)):
            if tuple(np.shape(l1)) != tuple(np.shape(l2)):
                raise ValueError(
                    f"shape mismatch at {kp2}: saved "
                    f"{np.shape(l1)} vs model {np.shape(l2)}")
        est.params = jax.device_put(converted)
        est._train_step = None
        return self

    def hyper_parameters(self):
        return {"model_name": self.model_name,
                "input_shape": self.input_shape,
                "classes": self.classes,
                "fused": self.fused}

    def build_model(self):
        builder = _builders()[self.model_name]
        if self.model_name.startswith("resnet-"):
            return builder(self.input_shape, self.classes,
                           fused=self.fused)
        return builder(self.input_shape, self.classes)

    @classmethod
    def load_model(cls, path_or_name: str, weights_path=None,
                   input_shape=(224, 224, 3), classes: int = 1000,
                   allow_random: bool = False):
        """Registry-aware load (reference
        `ImageClassifier.loadModel` by published name): a known
        architecture name (e.g. ``"resnet-50"``) builds it and loads
        shape-validated weights from ``weights_path`` /
        ``$ZOO_TPU_PRETRAINED_DIR`` (raising when no artifact is
        found unless ``allow_random=True``); anything else is a
        ``save_model`` file path."""
        from analytics_zoo_tpu.models.config import (
            ImageClassificationConfig, _resolve_weights,
            _strip_published_name)
        arch = _strip_published_name(path_or_name).lower()
        # registry route: known arch, OR an artifact for this published
        # name sits in $ZOO_TPU_PRETRAINED_DIR (e.g. a .model whose
        # arch has no built-in builder)
        if arch in _builders() or _resolve_weights(
                path_or_name, arch, None) is not None:
            return ImageClassificationConfig.create(
                path_or_name, input_shape=input_shape, classes=classes,
                weights_path=weights_path, allow_random=allow_random)
        return super().load_model(path_or_name)
