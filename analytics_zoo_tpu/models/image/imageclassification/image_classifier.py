"""ImageClassifier (reference
`Z/models/image/imageclassification/ImageClassifier.scala:55` + config
registry): a ZooModel dispatching to named architectures."""

from __future__ import annotations

from typing import Optional, Tuple

from analytics_zoo_tpu.models.common import ZooModel


class ImageClassifier(ZooModel):
    """``ImageClassifier(model_name="resnet-50")`` — named-architecture
    image classification (the pretrained-weight registry of the reference
    maps to `load_model` files here)."""

    ARCHS = ("lenet-5", "resnet-50", "resnet-101", "resnet-152")

    def __init__(self, model_name: str = "resnet-50",
                 input_shape: Tuple[int, int, int] = (224, 224, 3),
                 classes: int = 1000):
        super().__init__()
        name = model_name.lower()
        if name not in self.ARCHS:
            raise ValueError(f"unknown architecture '{model_name}'; "
                             f"known: {self.ARCHS}")
        self.model_name = name
        self.input_shape = tuple(input_shape)
        self.classes = int(classes)

    def hyper_parameters(self):
        return {"model_name": self.model_name,
                "input_shape": self.input_shape,
                "classes": self.classes}

    def build_model(self):
        if self.model_name == "lenet-5":
            from analytics_zoo_tpu.models.image.imageclassification \
                .lenet import lenet5
            return lenet5(self.input_shape, self.classes)
        from analytics_zoo_tpu.models.image.imageclassification.resnet \
            import ResNet
        depth = int(self.model_name.split("-")[1])
        return ResNet(depth).build(self.input_shape, self.classes)
