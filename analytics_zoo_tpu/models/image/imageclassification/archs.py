"""Named classification architectures for `ImageClassifier`.

The reference's pretrained zoo covers VGG / Inception / ResNet / MobileNet
/ DenseNet / SqueezeNet (`Z/models/image/imageclassification/
ImageClassificationConfig.scala:31` name registry). ResNet/LeNet live in
their own modules; this file provides the rest, built on the functional
Keras API so every arch lowers to one XLA program.

TPU-first choices shared by all archs:
- NHWC end-to-end, channels in multiples of 16/64 where the original
  design allows (MXU tiling).
- BatchNorm everywhere the modern variants use it; global-batch stats
  under pjit.
- No local response normalization in Inception (the original GoogLeNet
  LRN is replaced by BN, the standard modern recipe) — LRN is
  bandwidth-bound and hostile to fusion.
"""

from __future__ import annotations

from analytics_zoo_tpu.models.image.imageclassification.resnet import (
    conv_bn as _cbr)
from analytics_zoo_tpu.pipeline.api.keras.engine import Input
from analytics_zoo_tpu.pipeline.api.keras.models import Model, Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Activation, AveragePooling2D, BatchNormalization, Concatenate,
    Convolution2D, Dense, DepthwiseConvolution2D, Dropout, Flatten,
    GlobalAveragePooling2D, MaxPooling2D, Add)


# ---------------------------------------------------------------------------
# VGG (reference `ImageClassificationConfig` names vgg-16 / vgg-19)
# ---------------------------------------------------------------------------

_VGG_BLOCKS = {16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}


def vgg(depth: int = 16, input_shape=(224, 224, 3), classes: int = 1000
        ) -> Model:
    if depth not in _VGG_BLOCKS:
        raise ValueError(f"vgg depth must be one of {sorted(_VGG_BLOCKS)}")
    model = Sequential(name=f"vgg{depth}")
    filters = 64
    first = True
    for n_convs in _VGG_BLOCKS[depth]:
        for i in range(n_convs):
            kw = {"input_shape": input_shape} if first else {}
            first = False
            model.add(Convolution2D(min(filters, 512), 3, 3,
                                    border_mode="same", activation="relu",
                                    **kw))
        model.add(MaxPooling2D(pool_size=2, strides=2))
        filters *= 2
    model.add(Flatten())
    model.add(Dense(4096, activation="relu"))
    model.add(Dropout(0.5))
    model.add(Dense(4096, activation="relu"))
    model.add(Dropout(0.5))
    model.add(Dense(classes))
    return model


def vgg16(input_shape=(224, 224, 3), classes=1000) -> Model:
    return vgg(16, input_shape, classes)


def vgg19(input_shape=(224, 224, 3), classes=1000) -> Model:
    return vgg(19, input_shape, classes)


# ---------------------------------------------------------------------------
# Inception-v1 / GoogLeNet (reference training recipe
# `examples/inception/Train.scala:70-107` — the ImageNet headline example)
# ---------------------------------------------------------------------------



def _inception_module(x, f1, f3r, f3, f5r, f5, fp, name):
    b1 = _cbr(x, f1, 1, name=name + "_1x1")
    b3 = _cbr(x, f3r, 1, name=name + "_3x3r")
    b3 = _cbr(b3, f3, 3, name=name + "_3x3")
    b5 = _cbr(x, f5r, 1, name=name + "_5x5r")
    b5 = _cbr(b5, f5, 5, name=name + "_5x5")
    bp = MaxPooling2D(pool_size=3, strides=1, border_mode="same")(x)
    bp = _cbr(bp, fp, 1, name=name + "_pool")
    return Concatenate(axis=-1)([b1, b3, b5, bp])


def inception_v1(input_shape=(224, 224, 3), classes: int = 1000) -> Model:
    inp = Input(input_shape, name="image")
    x = _cbr(inp, 64, 7, stride=2, name="stem1")
    x = MaxPooling2D(pool_size=3, strides=2, border_mode="same")(x)
    x = _cbr(x, 64, 1, name="stem2r")
    x = _cbr(x, 192, 3, name="stem2")
    x = MaxPooling2D(pool_size=3, strides=2, border_mode="same")(x)
    x = _inception_module(x, 64, 96, 128, 16, 32, 32, "i3a")
    x = _inception_module(x, 128, 128, 192, 32, 96, 64, "i3b")
    x = MaxPooling2D(pool_size=3, strides=2, border_mode="same")(x)
    x = _inception_module(x, 192, 96, 208, 16, 48, 64, "i4a")
    x = _inception_module(x, 160, 112, 224, 24, 64, 64, "i4b")
    x = _inception_module(x, 128, 128, 256, 24, 64, 64, "i4c")
    x = _inception_module(x, 112, 144, 288, 32, 64, 64, "i4d")
    x = _inception_module(x, 256, 160, 320, 32, 128, 128, "i4e")
    x = MaxPooling2D(pool_size=3, strides=2, border_mode="same")(x)
    x = _inception_module(x, 256, 160, 320, 32, 128, 128, "i5a")
    x = _inception_module(x, 384, 192, 384, 48, 128, 128, "i5b")
    x = GlobalAveragePooling2D()(x)
    x = Dropout(0.4)(x)
    out = Dense(classes, name="fc")(x)
    return Model(inp, out, name="inception_v1")


# ---------------------------------------------------------------------------
# MobileNet v1 / v2
# ---------------------------------------------------------------------------

def _dw_block(x, filters, stride, name, alpha=1.0):
    """MobileNet v1 block: 3x3 depthwise + BN/relu, 1x1 pointwise +
    BN/relu."""
    x = DepthwiseConvolution2D(3, 3, subsample=stride, border_mode="same",
                               bias=False, name=name + "_dw")(x)
    x = BatchNormalization(name=name + "_dw_bn")(x)
    x = Activation("relu")(x)
    x = Convolution2D(int(filters * alpha), 1, 1, border_mode="same",
                      bias=False, name=name + "_pw")(x)
    x = BatchNormalization(name=name + "_pw_bn")(x)
    return Activation("relu")(x)


def mobilenet(input_shape=(224, 224, 3), classes: int = 1000,
              alpha: float = 1.0) -> Model:
    inp = Input(input_shape, name="image")
    x = Convolution2D(int(32 * alpha), 3, 3, subsample=2,
                      border_mode="same", bias=False, name="stem")(inp)
    x = BatchNormalization(name="stem_bn")(x)
    x = Activation("relu")(x)
    cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
           (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
           (1024, 1)]
    for i, (f, s) in enumerate(cfg):
        x = _dw_block(x, f, s, f"b{i}", alpha=alpha)
    x = GlobalAveragePooling2D()(x)
    out = Dense(classes, name="fc")(x)
    return Model(inp, out, name="mobilenet")


def _inverted_residual(x, in_ch, filters, stride, expansion, name):
    """MobileNet v2 inverted residual with linear bottleneck."""
    hidden = in_ch * expansion
    y = x
    if expansion != 1:
        y = Convolution2D(hidden, 1, 1, border_mode="same", bias=False,
                          name=name + "_exp")(y)
        y = BatchNormalization(name=name + "_exp_bn")(y)
        y = Activation("relu6")(y)
    y = DepthwiseConvolution2D(3, 3, subsample=stride, border_mode="same",
                               bias=False, name=name + "_dw")(y)
    y = BatchNormalization(name=name + "_dw_bn")(y)
    y = Activation("relu6")(y)
    y = Convolution2D(filters, 1, 1, border_mode="same", bias=False,
                      name=name + "_proj")(y)
    y = BatchNormalization(name=name + "_proj_bn")(y)
    if stride == 1 and in_ch == filters:
        y = Add()([y, x])
    return y


def mobilenet_v2(input_shape=(224, 224, 3), classes: int = 1000) -> Model:
    inp = Input(input_shape, name="image")
    x = Convolution2D(32, 3, 3, subsample=2, border_mode="same",
                      bias=False, name="stem")(inp)
    x = BatchNormalization(name="stem_bn")(x)
    x = Activation("relu6")(x)
    # (expansion, out_channels, repeats, first_stride)
    cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
           (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
    in_ch = 32
    bi = 0
    for t, c, n, s in cfg:
        for i in range(n):
            x = _inverted_residual(x, in_ch, c, s if i == 0 else 1, t,
                                   f"b{bi}")
            in_ch = c
            bi += 1
    x = Convolution2D(1280, 1, 1, border_mode="same", bias=False,
                      name="head")(x)
    x = BatchNormalization(name="head_bn")(x)
    x = Activation("relu6")(x)
    x = GlobalAveragePooling2D()(x)
    out = Dense(classes, name="fc")(x)
    return Model(inp, out, name="mobilenet_v2")


# ---------------------------------------------------------------------------
# DenseNet-121
# ---------------------------------------------------------------------------

def _dense_layer(x, growth, name):
    y = BatchNormalization(name=name + "_bn1")(x)
    y = Activation("relu")(y)
    y = Convolution2D(4 * growth, 1, 1, border_mode="same", bias=False,
                      name=name + "_c1")(y)
    y = BatchNormalization(name=name + "_bn2")(y)
    y = Activation("relu")(y)
    y = Convolution2D(growth, 3, 3, border_mode="same", bias=False,
                      name=name + "_c2")(y)
    return Concatenate(axis=-1)([x, y])


def densenet121(input_shape=(224, 224, 3), classes: int = 1000,
                growth: int = 32) -> Model:
    inp = Input(input_shape, name="image")
    x = Convolution2D(64, 7, 7, subsample=2, border_mode="same",
                      bias=False, name="stem")(inp)
    x = BatchNormalization(name="stem_bn")(x)
    x = Activation("relu")(x)
    x = MaxPooling2D(pool_size=3, strides=2, border_mode="same")(x)
    ch = 64
    for bi, n_layers in enumerate((6, 12, 24, 16)):
        for li in range(n_layers):
            x = _dense_layer(x, growth, f"d{bi}l{li}")
            ch += growth
        if bi < 3:  # transition
            ch //= 2
            x = BatchNormalization(name=f"t{bi}_bn")(x)
            x = Activation("relu")(x)
            x = Convolution2D(ch, 1, 1, border_mode="same", bias=False,
                              name=f"t{bi}_c")(x)
            x = AveragePooling2D(pool_size=2, strides=2)(x)
    x = BatchNormalization(name="final_bn")(x)
    x = Activation("relu")(x)
    x = GlobalAveragePooling2D()(x)
    out = Dense(classes, name="fc")(x)
    return Model(inp, out, name="densenet121")


# ---------------------------------------------------------------------------
# SqueezeNet v1.1
# ---------------------------------------------------------------------------

def _fire(x, squeeze, expand, name):
    s = Convolution2D(squeeze, 1, 1, border_mode="same",
                      activation="relu", name=name + "_sq")(x)
    e1 = Convolution2D(expand, 1, 1, border_mode="same",
                       activation="relu", name=name + "_e1")(s)
    e3 = Convolution2D(expand, 3, 3, border_mode="same",
                       activation="relu", name=name + "_e3")(s)
    return Concatenate(axis=-1)([e1, e3])


def squeezenet(input_shape=(224, 224, 3), classes: int = 1000) -> Model:
    inp = Input(input_shape, name="image")
    x = Convolution2D(64, 3, 3, subsample=2, border_mode="same",
                      activation="relu", name="stem")(inp)
    x = MaxPooling2D(pool_size=3, strides=2, border_mode="same")(x)
    x = _fire(x, 16, 64, "f2")
    x = _fire(x, 16, 64, "f3")
    x = MaxPooling2D(pool_size=3, strides=2, border_mode="same")(x)
    x = _fire(x, 32, 128, "f4")
    x = _fire(x, 32, 128, "f5")
    x = MaxPooling2D(pool_size=3, strides=2, border_mode="same")(x)
    x = _fire(x, 48, 192, "f6")
    x = _fire(x, 48, 192, "f7")
    x = _fire(x, 64, 256, "f8")
    x = _fire(x, 64, 256, "f9")
    x = Dropout(0.5)(x)
    x = Convolution2D(classes, 1, 1, border_mode="same",
                      activation="relu", name="conv10")(x)
    out = GlobalAveragePooling2D()(x)
    return Model(inp, out, name="squeezenet")
