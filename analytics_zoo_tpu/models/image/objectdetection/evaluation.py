"""Detection evaluation: mean average precision.

Reference: `Z/models/image/objectdetection/common/evaluation/
MeanAveragePrecision.scala:31` and `PascalVocEvaluator.scala:33`
(VOC-style AP: 11-point interpolation or continuous area).
"""

from __future__ import annotations


import numpy as np

from analytics_zoo_tpu.models.image.objectdetection.bbox_util import (
    iou_matrix)
from analytics_zoo_tpu.models.image.objectdetection.detection import (
    Detection)


class MeanAveragePrecision:
    def __init__(self, n_classes: int, iou_threshold: float = 0.5,
                 use_07_metric: bool = False):
        self.n_classes = int(n_classes)
        self.iou_threshold = float(iou_threshold)
        self.use_07_metric = use_07_metric

    def _ap(self, recall: np.ndarray, precision: np.ndarray) -> float:
        if self.use_07_metric:  # VOC2007 11-point
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                p = precision[recall >= t].max() if \
                    (recall >= t).any() else 0.0
                ap += p / 11.0
            return float(ap)
        # continuous area under monotone precision envelope
        mrec = np.concatenate([[0.0], recall, [1.0]])
        mpre = np.concatenate([[0.0], precision, [0.0]])
        mpre = np.maximum.accumulate(mpre[::-1])[::-1]
        idx = np.flatnonzero(mrec[1:] != mrec[:-1])
        return float(np.sum((mrec[idx + 1] - mrec[idx]) *
                            mpre[idx + 1]))

    def evaluate(self,
                 detections: "list[list[Detection]]",
                 gt_boxes: "list[np.ndarray]",
                 gt_labels: "list[np.ndarray]"
                 ) -> "tuple[float, dict[int, float]]":
        """→ (mAP, per-class AP). gt label ids use the detection class
        ids (background excluded)."""
        aps: "dict[int, float]" = {}
        for c in range(1, self.n_classes):
            records: "list[tuple[float, bool]]" = []
            n_gt = 0
            for dets, boxes, labels in zip(detections, gt_boxes,
                                           gt_labels):
                cls_gt = np.asarray(boxes)[np.asarray(labels) == c] \
                    if len(boxes) else np.zeros((0, 4))
                n_gt += len(cls_gt)
                cls_dets = [d for d in dets if d.class_id == c]
                cls_dets.sort(key=lambda d: -d.score)
                taken = np.zeros(len(cls_gt), bool)
                for d in cls_dets:
                    if len(cls_gt) == 0:
                        records.append((d.score, False))
                        continue
                    ious = np.asarray(iou_matrix(
                        d.box[None], cls_gt))[0]
                    j = int(np.argmax(ious))
                    if ious[j] >= self.iou_threshold and not taken[j]:
                        taken[j] = True
                        records.append((d.score, True))
                    else:
                        records.append((d.score, False))
            if n_gt == 0:
                continue
            if not records:
                aps[c] = 0.0
                continue
            records.sort(key=lambda r: -r[0])
            tp = np.cumsum([r[1] for r in records])
            fp = np.cumsum([not r[1] for r in records])
            recall = tp / n_gt
            precision = tp / np.maximum(tp + fp, 1e-12)
            aps[c] = self._ap(recall, precision)
        mean_ap = float(np.mean(list(aps.values()))) if aps else 0.0
        return mean_ap, aps
