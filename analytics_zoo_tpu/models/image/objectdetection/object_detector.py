"""ObjectDetector ZooModel + config registry + datasets.

Reference: `Z/models/image/objectdetection/ObjectDetector.scala:53`
(pretrained-model loading by name, image-set prediction),
`ObjectDetectionConfig.scala:31` (name → preprocessing/postprocessing
config registry), PascalVOC/COCO dataset readers
(`common/dataset/`).
"""

from __future__ import annotations

import json
import os
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.models.image.objectdetection.detection import (
    DetectionOutput,
)
from analytics_zoo_tpu.models.image.objectdetection.multibox_loss \
    import MultiBoxLoss
from analytics_zoo_tpu.models.image.objectdetection.ssd import SSDVGG

VOC_CLASSES = (
    "background", "aeroplane", "bicycle", "bird", "boat", "bottle",
    "bus", "car", "cat", "chair", "cow", "diningtable", "dog", "horse",
    "motorbike", "person", "pottedplant", "sheep", "sofa", "train",
    "tvmonitor")


@dataclass
class ObjectDetectionConfig:
    """(reference `ObjectDetectionConfig.scala:31`)"""

    arch: str = "ssd-vgg16"
    img_size: int = 300
    n_classes: int = 21
    class_names: Sequence[str] = VOC_CLASSES
    mean: "tuple" = (123.0, 117.0, 104.0)
    conf_threshold: float = 0.01
    nms_threshold: float = 0.45


CONFIGS: "dict[str, ObjectDetectionConfig]" = {
    "ssd-vgg16-300x300": ObjectDetectionConfig(),
    "ssd-vgg16-300x300-voc": ObjectDetectionConfig(),
}


class ObjectDetector(ZooModel):
    """SSD object detection as a ZooModel (reference
    `ObjectDetector.scala:53`)."""

    def __init__(self, model_name: str = "ssd-vgg16-300x300",
                 n_classes: Optional[int] = None,
                 img_size: Optional[int] = None):
        super().__init__()
        if model_name not in CONFIGS:
            raise ValueError(f"unknown detection model '{model_name}'; "
                             f"known: {sorted(CONFIGS)}")
        cfg = CONFIGS[model_name]
        self.model_name = model_name
        self.config = cfg
        self.n_classes = int(n_classes or cfg.n_classes)
        self.img_size = int(img_size or cfg.img_size)
        self._builder = SSDVGG(self.n_classes, self.img_size)
        self.priors = self._builder.priors

    def hyper_parameters(self):
        return {"model_name": self.model_name,
                "n_classes": self.n_classes,
                "img_size": self.img_size}

    def build_model(self):
        return self._builder.build()

    @classmethod
    def load_model(cls, path_or_name: str, weights_path=None,
                   n_classes=None, img_size=None,
                   allow_random: bool = False):
        """Registry-aware load (reference
        `ObjectDetector.load(name)` via `ObjectDetectionConfig`):
        known variant names build + load local weights (raising when
        no artifact is found unless ``allow_random=True``); other
        strings are ``save_model`` file paths."""
        from analytics_zoo_tpu.models.config import (
            ObjectDetectionConfig, _resolve_weights,
            _strip_published_name)
        arch = _strip_published_name(path_or_name).lower()
        if arch in CONFIGS or _resolve_weights(
                path_or_name, arch, None) is not None:
            return ObjectDetectionConfig.create(
                path_or_name, n_classes=n_classes, img_size=img_size,
                weights_path=weights_path, allow_random=allow_random)
        return super().load_model(path_or_name)

    # -- training -----------------------------------------------------------
    def compile_detection(self, optimizer="sgd",
                          iou_threshold: float = 0.5,
                          neg_pos_ratio: float = 3.0):
        _ = self.model  # building refreshes the builder's prior layout
        self.priors = np.asarray(self._builder.priors)
        loss = MultiBoxLoss(self.n_classes, iou_threshold,
                            neg_pos_ratio).as_keras_loss(
            np.asarray(self.priors))
        self.compile(optimizer=optimizer, loss=loss)
        return self

    @staticmethod
    def pack_targets(gt_boxes: "list[np.ndarray]",
                     gt_labels: "list[np.ndarray]",
                     max_gt: int = 32) -> np.ndarray:
        """Pad per-image GT into the fixed-size y_true layout the
        MultiBox keras-loss consumes (label -1 = padding)."""
        b = len(gt_boxes)
        boxes = np.zeros((b, max_gt, 4), np.float32)
        labels = np.full((b, max_gt), -1.0, np.float32)
        for i, (bx, lb) in enumerate(zip(gt_boxes, gt_labels)):
            n = min(len(lb), max_gt)
            if n:
                boxes[i, :n] = np.asarray(bx)[:n]
                labels[i, :n] = np.asarray(lb)[:n]
        return np.concatenate(
            [boxes.reshape(b, -1), labels], axis=1)

    # -- inference ----------------------------------------------------------
    def detect(self, images: np.ndarray, batch_size: int = 8,
               conf_threshold: Optional[float] = None
               ) -> "list[list[Detection]]":
        """images: (B, H, W, 3) float (already mean-subtracted/resized;
        use `feature.image` transforms)."""
        _ = self.model
        self.priors = np.asarray(self._builder.priors)
        flat = self.predict(images, batch_size=batch_size)
        post = DetectionOutput(
            self.n_classes,
            conf_threshold=(conf_threshold if conf_threshold is not None
                            else self.config.conf_threshold),
            nms_threshold=self.config.nms_threshold)
        return post.from_flat(np.asarray(flat), np.asarray(self.priors))


# -- datasets (reference `common/dataset/`) ---------------------------------

class PascalVocDataset:
    """Reads a VOCdevkit layout: Annotations/*.xml + JPEGImages/*."""

    def __init__(self, root: str,
                 class_names: Sequence[str] = VOC_CLASSES):
        self.root = root
        self.class_to_id = {c: i for i, c in enumerate(class_names)}

    def read_annotations(self) -> "list[dict]":
        ann_dir = os.path.join(self.root, "Annotations")
        out = []
        for fname in sorted(os.listdir(ann_dir)):
            if not fname.endswith(".xml"):
                continue
            tree = ET.parse(os.path.join(ann_dir, fname))
            size = tree.find("size")
            w = float(size.find("width").text)
            h = float(size.find("height").text)
            boxes, labels = [], []
            for obj in tree.iter("object"):
                name = obj.find("name").text
                if name not in self.class_to_id:
                    continue
                bb = obj.find("bndbox")
                boxes.append([
                    float(bb.find("xmin").text) / w,
                    float(bb.find("ymin").text) / h,
                    float(bb.find("xmax").text) / w,
                    float(bb.find("ymax").text) / h])
                labels.append(self.class_to_id[name])
            img = tree.find("filename").text
            out.append({
                "image": os.path.join(self.root, "JPEGImages", img),
                "boxes": np.asarray(boxes, np.float32),
                "labels": np.asarray(labels, np.int32)})
        return out


class CocoDataset:
    """Reads a COCO instances json (boxes normalized to corners)."""

    def __init__(self, annotation_json: str, image_root: str = ""):
        self.annotation_json = annotation_json
        self.image_root = image_root

    def read_annotations(self) -> "list[dict]":
        with open(self.annotation_json) as f:
            coco = json.load(f)
        images = {im["id"]: im for im in coco["images"]}
        cat_ids = sorted(c["id"] for c in coco["categories"])
        cat_to_label = {cid: i + 1 for i, cid in enumerate(cat_ids)}
        per_image: "dict[int, dict]" = {}
        for ann in coco["annotations"]:
            im = images[ann["image_id"]]
            w, h = float(im["width"]), float(im["height"])
            x, y, bw, bh = ann["bbox"]
            entry = per_image.setdefault(ann["image_id"], {
                "image": os.path.join(self.image_root,
                                      im["file_name"]),
                "boxes": [], "labels": []})
            entry["boxes"].append([x / w, y / h, (x + bw) / w,
                                   (y + bh) / h])
            entry["labels"].append(cat_to_label[ann["category_id"]])
        return [{"image": v["image"],
                 "boxes": np.asarray(v["boxes"], np.float32),
                 "labels": np.asarray(v["labels"], np.int32)}
                for v in per_image.values()]
