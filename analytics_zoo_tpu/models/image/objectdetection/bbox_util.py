"""Bounding-box geometry: IoU, SSD codec, NMS, clipping.

Reference: `Z/models/image/objectdetection/common/BboxUtil.scala` (1033
LoC of loop-heavy geometry — SURVEY.md §2.6). Re-designed as fully
vectorized jnp ops: everything here traces under jit with static shapes
(NMS is a fixed-iteration suppression loop, not data-dependent control
flow), so the whole detection head runs on-device.

Box format: (x_min, y_min, x_max, y_max), normalized [0, 1].
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# SSD/Caffe variance defaults (BboxUtil encode/decode variances)
DEFAULT_VARIANCES = (0.1, 0.1, 0.2, 0.2)


def iou_matrix(boxes_a: jnp.ndarray, boxes_b: jnp.ndarray) -> jnp.ndarray:
    """(N, 4) × (M, 4) → (N, M) pairwise IoU (reference
    `BboxUtil.jaccardOverlap`)."""
    a = boxes_a[:, None, :]  # (N, 1, 4)
    b = boxes_b[None, :, :]  # (1, M, 4)
    inter_min = jnp.maximum(a[..., :2], b[..., :2])
    inter_max = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.maximum(inter_max - inter_min, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(boxes_a[:, 2] - boxes_a[:, 0], 0.0) * \
        jnp.maximum(boxes_a[:, 3] - boxes_a[:, 1], 0.0)
    area_b = jnp.maximum(boxes_b[:, 2] - boxes_b[:, 0], 0.0) * \
        jnp.maximum(boxes_b[:, 3] - boxes_b[:, 1], 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)


def _to_center(boxes):
    wh = boxes[..., 2:] - boxes[..., :2]
    c = (boxes[..., :2] + boxes[..., 2:]) * 0.5
    return c, wh


def encode_boxes(gt_boxes: jnp.ndarray, priors: jnp.ndarray,
                 variances=DEFAULT_VARIANCES) -> jnp.ndarray:
    """GT corner boxes → SSD regression targets wrt priors (reference
    `BboxUtil.encodeBBox`)."""
    v = jnp.asarray(variances)
    g_c, g_wh = _to_center(gt_boxes)
    p_c, p_wh = _to_center(priors)
    p_wh = jnp.maximum(p_wh, 1e-8)
    g_wh = jnp.maximum(g_wh, 1e-8)
    d_xy = (g_c - p_c) / (p_wh * v[:2])
    d_wh = jnp.log(g_wh / p_wh) / v[2:]
    return jnp.concatenate([d_xy, d_wh], axis=-1)


def decode_boxes(loc: jnp.ndarray, priors: jnp.ndarray,
                 variances=DEFAULT_VARIANCES) -> jnp.ndarray:
    """Regression outputs → corner boxes (reference
    `BboxUtil.decodeBBox`)."""
    v = jnp.asarray(variances)
    p_c, p_wh = _to_center(priors)
    c = loc[..., :2] * v[:2] * p_wh + p_c
    wh = jnp.exp(loc[..., 2:] * v[2:]) * p_wh
    return jnp.concatenate([c - wh * 0.5, c + wh * 0.5], axis=-1)


def clip_boxes(boxes: jnp.ndarray) -> jnp.ndarray:
    return jnp.clip(boxes, 0.0, 1.0)


def nms(boxes: jnp.ndarray, scores: jnp.ndarray,
        iou_threshold: float = 0.45,
        max_output: int = 100,
        score_threshold: float = 0.0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Non-maximum suppression, jit-friendly fixed-size output.

    Returns (indices (max_output,), valid mask (max_output,)); invalid
    slots hold index 0. (reference `BboxUtil.nms` / `Nms.scala`.)
    """
    n = boxes.shape[0]
    max_output = min(max_output, n)
    iou = iou_matrix(boxes, boxes)
    order_scores = jnp.where(scores > score_threshold, scores, -jnp.inf)

    def body(state, _):
        remaining, = state
        masked = jnp.where(remaining, order_scores, -jnp.inf)
        idx = jnp.argmax(masked)
        valid = masked[idx] > -jnp.inf
        # suppress overlaps with the selected box
        suppress = iou[idx] > iou_threshold
        remaining = remaining & ~suppress & \
            (jnp.arange(n) != idx)
        return (remaining,), (idx, valid)

    init = (jnp.ones((n,), jnp.bool_),)
    _, (idxs, valids) = jax.lax.scan(body, init, None,
                                     length=max_output)
    return idxs, valids


def bipartite_and_per_prediction_match(
        iou: jnp.ndarray, threshold: float = 0.5
        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """SSD prior↔GT matching (reference `BboxUtil.matchBbox`):

    1. bipartite: each GT claims its best prior (guaranteed match);
    2. per-prediction: remaining priors match their best GT if
       IoU > threshold.

    iou: (num_gt, num_priors). Returns (match_idx (num_priors,) int —
    GT index or -1, matched mask (num_priors,)).
    """
    num_gt, num_priors = iou.shape
    best_gt = jnp.argmax(iou, axis=0)           # per prior
    best_gt_iou = jnp.max(iou, axis=0)
    matched = best_gt_iou > threshold
    match_idx = jnp.where(matched, best_gt, -1)

    # bipartite pass: each GT's best prior is forced to that GT
    best_prior = jnp.argmax(iou, axis=1)        # (num_gt,)
    gt_has_box = jnp.max(iou, axis=1) > 0.0
    match_idx = match_idx.at[best_prior].set(
        jnp.where(gt_has_box, jnp.arange(num_gt), match_idx[best_prior]))
    matched = match_idx >= 0
    return match_idx, matched
