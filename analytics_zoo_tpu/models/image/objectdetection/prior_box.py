"""SSD prior (anchor) box generation (reference `PriorBox` usage in
`Z/models/image/objectdetection/ssd/SSDVGG.scala` / SSDGraph; Caffe
PriorBox semantics: per feature-map cell, boxes for min_size, sqrt(min*
max) size, and aspect ratios ±flip)."""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence

import numpy as np


@dataclass
class PriorBoxSpec:
    feature_size: int             # feature map is feature_size²
    min_size: float               # in input-image pixels
    max_size: float
    aspect_ratios: "tuple" = (2.0,)
    flip: bool = True
    clip: bool = False
    step: float = 0.0             # pixels per cell; 0 → image/feature


def _cell_priors(spec: PriorBoxSpec, img_size: float) -> np.ndarray:
    """Prior (w, h) list for one cell, normalized."""
    sizes = []
    s_min = spec.min_size / img_size
    sizes.append((s_min, s_min))
    s_prime = math.sqrt(spec.min_size * spec.max_size) / img_size
    sizes.append((s_prime, s_prime))
    for ar in spec.aspect_ratios:
        w = s_min * math.sqrt(ar)
        h = s_min / math.sqrt(ar)
        sizes.append((w, h))
        if spec.flip:
            sizes.append((h, w))
    return np.asarray(sizes, np.float32)


def generate_ssd_priors(specs: Sequence[PriorBoxSpec],
                        img_size: float = 300.0) -> np.ndarray:
    """→ (num_priors, 4) corner-format normalized priors."""
    all_boxes = []
    for spec in specs:
        f = spec.feature_size
        step = (spec.step / img_size) if spec.step else (1.0 / f)
        whs = _cell_priors(spec, img_size)       # (K, 2)
        ys, xs = np.meshgrid(np.arange(f), np.arange(f), indexing="ij")
        centers = np.stack([(xs + 0.5) * step, (ys + 0.5) * step],
                           axis=-1).reshape(-1, 1, 2)   # (F², 1, 2)
        wh = whs.reshape(1, -1, 2)                       # (1, K, 2)
        boxes = np.concatenate(
            [centers - wh / 2, centers + wh / 2],
            axis=-1).reshape(-1, 4)                      # (F²·K, 4)
        if spec.clip:
            boxes = np.clip(boxes, 0.0, 1.0)
        all_boxes.append(boxes.astype(np.float32))
    return np.concatenate(all_boxes, axis=0)


def num_priors_per_cell(spec: PriorBoxSpec) -> int:
    return 2 + len(spec.aspect_ratios) * (2 if spec.flip else 1)


# canonical SSD300 config (VGG variant, reference SSDVGG)
SSD300_SPECS = [
    PriorBoxSpec(38, 30.0, 60.0, (2.0,)),
    PriorBoxSpec(19, 60.0, 111.0, (2.0, 3.0)),
    PriorBoxSpec(10, 111.0, 162.0, (2.0, 3.0)),
    PriorBoxSpec(5, 162.0, 213.0, (2.0, 3.0)),
    PriorBoxSpec(3, 213.0, 264.0, (2.0,)),
    PriorBoxSpec(1, 264.0, 315.0, (2.0,)),
]
