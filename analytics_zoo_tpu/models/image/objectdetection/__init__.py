from analytics_zoo_tpu.models.image.objectdetection import bbox_util
from analytics_zoo_tpu.models.image.objectdetection.bbox_util import (
    iou_matrix, encode_boxes, decode_boxes, nms, clip_boxes)
from analytics_zoo_tpu.models.image.objectdetection.prior_box import (
    PriorBoxSpec, generate_ssd_priors)
from analytics_zoo_tpu.models.image.objectdetection.multibox_loss import (
    MultiBoxLoss, match_priors)
from analytics_zoo_tpu.models.image.objectdetection.detection import (
    DetectionOutput, Visualizer)
from analytics_zoo_tpu.models.image.objectdetection.evaluation import (
    MeanAveragePrecision)
from analytics_zoo_tpu.models.image.objectdetection.ssd import (
    SSDVGG, ssd300_vgg16)
from analytics_zoo_tpu.models.image.objectdetection.object_detector \
    import ObjectDetector

__all__ = [
    "bbox_util", "iou_matrix", "encode_boxes", "decode_boxes", "nms",
    "clip_boxes", "PriorBoxSpec", "generate_ssd_priors", "MultiBoxLoss",
    "match_priors", "DetectionOutput", "Visualizer",
    "MeanAveragePrecision", "SSDVGG", "ssd300_vgg16", "ObjectDetector",
]
