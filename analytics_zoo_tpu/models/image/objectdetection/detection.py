"""Detection post-processing + visualization.

Reference: DetectionOutput semantics inside
`Z/models/image/objectdetection/` (decode → per-class NMS → keep top-k)
and `Visualizer.scala:29` (draw labeled boxes on images).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from analytics_zoo_tpu.models.image.objectdetection.bbox_util import (
    clip_boxes, decode_boxes, iou_matrix)


@dataclass
class Detection:
    class_id: int
    score: float
    box: np.ndarray  # (4,) normalized corners


def _nms_numpy(boxes: np.ndarray, scores: np.ndarray,
               iou_threshold: float) -> "list[int]":
    order = np.argsort(-scores)
    keep: "list[int]" = []
    iou = np.asarray(iou_matrix(boxes, boxes))
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(int(i))
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True
    return keep


class DetectionOutput:
    """(loc (B, P, 4), conf (B, P, C) logits-or-probs, priors) →
    per-image Detection lists."""

    def __init__(self, n_classes: int, conf_threshold: float = 0.01,
                 nms_threshold: float = 0.45, top_k: int = 200,
                 conf_is_logits: bool = True):
        self.n_classes = int(n_classes)
        self.conf_threshold = float(conf_threshold)
        self.nms_threshold = float(nms_threshold)
        self.top_k = int(top_k)
        self.conf_is_logits = conf_is_logits

    def __call__(self, loc: np.ndarray, conf: np.ndarray,
                 priors: np.ndarray) -> "list[list[Detection]]":
        loc = np.asarray(loc)
        conf = np.asarray(conf, np.float64)
        if self.conf_is_logits:
            conf = conf - conf.max(-1, keepdims=True)
            e = np.exp(conf)
            conf = e / e.sum(-1, keepdims=True)
        out = []
        for b in range(loc.shape[0]):
            boxes = np.asarray(clip_boxes(
                decode_boxes(loc[b], priors)))
            dets: "list[Detection]" = []
            for c in range(1, self.n_classes):  # skip background 0
                scores = conf[b, :, c]
                mask = scores > self.conf_threshold
                if not mask.any():
                    continue
                cb, cs = boxes[mask], scores[mask]
                for i in _nms_numpy(cb, cs, self.nms_threshold):
                    dets.append(Detection(c, float(cs[i]), cb[i]))
            dets.sort(key=lambda d: -d.score)
            out.append(dets[:self.top_k])
        return out

    def from_flat(self, flat: np.ndarray, priors: np.ndarray
                  ) -> "list[list[Detection]]":
        """Accepts the SSD model's flattened output."""
        p = priors.shape[0]
        b = flat.shape[0]
        loc = flat[:, :p * 4].reshape(b, p, 4)
        conf = flat[:, p * 4:].reshape(b, p, self.n_classes)
        return self(loc, conf, priors)


class Visualizer:
    """Draw detections on an image (reference `Visualizer.scala:29`)."""

    def __init__(self, class_names: Sequence[str],
                 score_threshold: float = 0.3):
        self.class_names = list(class_names)
        self.score_threshold = float(score_threshold)

    def draw(self, image: np.ndarray,
             detections: "list[Detection]") -> np.ndarray:
        from PIL import Image, ImageDraw
        img = Image.fromarray(np.asarray(image, np.uint8))
        draw = ImageDraw.Draw(img)
        w, h = img.size
        for det in detections:
            if det.score < self.score_threshold:
                continue
            x1, y1, x2, y2 = det.box
            box = (x1 * w, y1 * h, x2 * w, y2 * h)
            draw.rectangle(box, outline=(255, 0, 0), width=2)
            label = (self.class_names[det.class_id]
                     if det.class_id < len(self.class_names)
                     else str(det.class_id))
            draw.text((box[0] + 2, box[1] + 2),
                      f"{label} {det.score:.2f}", fill=(255, 0, 0))
        return np.asarray(img)
