"""MultiBoxLoss (reference
`Z/models/image/objectdetection/common/loss/MultiBoxLoss.scala:39`,
622 LoC): SSD training loss = SmoothL1 localization on matched priors +
softmax confidence with 3:1 hard-negative mining, normalized by the
match count.

TPU-first: the whole loss — matching included — is vectorized and jit-
compiled per batch element via vmap; hard-negative mining uses a sort
(top-k) rather than the reference's per-image mutable heaps. Ground
truth arrives as fixed-size padded arrays (label -1 = padding), keeping
shapes static for XLA.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.models.image.objectdetection.bbox_util import (
    bipartite_and_per_prediction_match, encode_boxes, iou_matrix)


def match_priors(gt_boxes: jnp.ndarray, gt_labels: jnp.ndarray,
                 priors: jnp.ndarray, iou_threshold: float = 0.5
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Single image: (max_gt, 4) padded GT + (max_gt,) labels (-1 pad)
    → (loc_targets (P, 4), cls_targets (P,) int [0 = background],
    matched mask (P,))."""
    valid = gt_labels >= 0
    iou = iou_matrix(gt_boxes, priors)            # (max_gt, P)
    iou = jnp.where(valid[:, None], iou, 0.0)
    match_idx, matched = bipartite_and_per_prediction_match(
        iou, iou_threshold)
    safe_idx = jnp.maximum(match_idx, 0)
    matched_boxes = gt_boxes[safe_idx]
    loc_targets = encode_boxes(matched_boxes, priors)
    # class targets: gt label + 1 (0 reserved for background)
    cls_targets = jnp.where(matched, gt_labels[safe_idx] + 1, 0)
    return loc_targets, cls_targets, matched


def smooth_l1(x: jnp.ndarray) -> jnp.ndarray:
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


class MultiBoxLoss:
    """Callable loss: ((loc_pred, conf_pred), (gt_boxes, gt_labels)) →
    scalar. Shapes: loc_pred (B, P, 4); conf_pred (B, P, C) logits
    (C includes background class 0); gt padded (B, max_gt, 4)/(B,
    max_gt) with label -1 padding."""

    def __init__(self, n_classes: int, iou_threshold: float = 0.5,
                 neg_pos_ratio: float = 3.0, loc_weight: float = 1.0):
        self.n_classes = int(n_classes)
        self.iou_threshold = float(iou_threshold)
        self.neg_pos_ratio = float(neg_pos_ratio)
        self.loc_weight = float(loc_weight)

    def __call__(self, priors: jnp.ndarray, loc_pred: jnp.ndarray,
                 conf_pred: jnp.ndarray, gt_boxes: jnp.ndarray,
                 gt_labels: jnp.ndarray) -> jnp.ndarray:
        loc_t, cls_t, matched = jax.vmap(
            lambda b, l: match_priors(b, l, priors,
                                      self.iou_threshold))(
            gt_boxes, gt_labels)
        num_pos = jnp.sum(matched, axis=1)               # (B,)

        # localization: SmoothL1 over matched priors
        loc_loss = jnp.sum(
            smooth_l1(loc_pred - loc_t) * matched[..., None], axis=(1, 2))

        # confidence: softmax CE; hard negative mining 3:1 by loss rank
        logp = jax.nn.log_softmax(conf_pred.astype(jnp.float32), axis=-1)
        ce = -jnp.take_along_axis(logp, cls_t[..., None],
                                  axis=-1)[..., 0]        # (B, P)
        neg_ce = jnp.where(matched, -jnp.inf, ce)         # only negatives
        n_neg = jnp.minimum(
            (num_pos * self.neg_pos_ratio).astype(jnp.int32),
            jnp.asarray(ce.shape[1] - 1, jnp.int32))
        # rank negatives by loss; keep top n_neg per image
        sorted_neg = jnp.sort(neg_ce, axis=1)[:, ::-1]    # desc
        kth = jnp.take_along_axis(
            sorted_neg, jnp.maximum(n_neg - 1, 0)[:, None], axis=1)
        keep_neg = (neg_ce >= kth) & (n_neg[:, None] > 0) & \
            jnp.isfinite(neg_ce)
        conf_loss = jnp.sum(ce * (matched | keep_neg), axis=1)

        norm = jnp.maximum(num_pos.astype(jnp.float32), 1.0)
        total = (self.loc_weight * loc_loss + conf_loss) / norm
        return jnp.mean(total)

    def as_keras_loss(self, priors: jnp.ndarray):
        """Adapt to the Estimator's (y_true, y_pred) contract:
        y_pred = concat[loc (P·4), conf (P·C)] flattened per image;
        y_true = concat[gt_boxes (max_gt·4), gt_labels (max_gt)]."""
        p = priors.shape[0]
        c = self.n_classes

        def loss_fn(y_true, y_pred):
            b = y_pred.shape[0]
            loc = y_pred[:, :p * 4].reshape(b, p, 4)
            conf = y_pred[:, p * 4:].reshape(b, p, c)
            max_gt = (y_true.shape[1]) // 5
            gt_boxes = y_true[:, :max_gt * 4].reshape(b, max_gt, 4)
            gt_labels = y_true[:, max_gt * 4:].reshape(b, max_gt) \
                .astype(jnp.int32)
            return self(priors, loc, conf, gt_boxes, gt_labels)

        return loss_fn
