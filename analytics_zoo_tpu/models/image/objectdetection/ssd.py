"""SSD detection graphs (reference `Z/models/image/objectdetection/ssd/`
— SSDVGG, SSD minibatch/augmentation; SURVEY.md §2.6).

SSD300-VGG16: VGG base (pool5 3×3/s1, dilated fc6, 1×1 fc7) + extra
feature layers + per-scale loc/conf heads; conv4_3 passes through a
learnable-scale L2Norm (the classic SSD trick). NHWC throughout; heads
reshape to (B, P, 4)/(B, P, C) and concatenate into one flat output so
the Estimator's single-output loss contract applies
(`MultiBoxLoss.as_keras_loss`).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api import autograd as A
from analytics_zoo_tpu.pipeline.api.keras.engine import (
    Input, KerasLayer, Shape)
from analytics_zoo_tpu.pipeline.api.keras.models import Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Concatenate, Convolution2D, MaxPooling2D,
)
from analytics_zoo_tpu.models.image.objectdetection.prior_box import (
    SSD300_SPECS, generate_ssd_priors, num_priors_per_cell)


class L2NormScale(KerasLayer):
    """Channel-wise L2 normalization with learnable per-channel scale
    (reference SSD `NormalizeScale` on conv4_3; init scale 20)."""

    def __init__(self, scale_init: float = 20.0, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.scale_init = float(scale_init)

    def build(self, rng, input_shape: Shape) -> dict:
        return {"scale": jnp.full((input_shape[-1],), self.scale_init,
                                  jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True) +
                        1e-10)
        return x / norm * params["scale"].astype(x.dtype)


def _conv(x, filters, k, stride=1, pad="same", dilation=1, act="relu",
          name=None):
    return Convolution2D(filters, k, k, subsample=stride,
                         border_mode=pad, dilation=dilation,
                         activation=act, name=name)(x)


class SSDVGG:
    """SSD300-VGG16 builder (reference `SSDVGG.scala`)."""

    def __init__(self, n_classes: int, img_size: int = 300,
                 specs=None):
        self.n_classes = int(n_classes)  # includes background class 0
        self.img_size = int(img_size)
        self.specs = specs or SSD300_SPECS
        self.priors = generate_ssd_priors(self.specs, float(img_size))

    @property
    def num_priors(self) -> int:
        return self.priors.shape[0]

    def _backbone(self, x):
        # VGG16 through conv4_3 / fc7 (SSD-modified)
        for i, f in enumerate((64, 64)):
            x = _conv(x, f, 3, name=f"conv1_{i+1}")
        x = MaxPooling2D(border_mode="same")(x)
        for i, f in enumerate((128, 128)):
            x = _conv(x, f, 3, name=f"conv2_{i+1}")
        x = MaxPooling2D(border_mode="same")(x)
        for i, f in enumerate((256, 256, 256)):
            x = _conv(x, f, 3, name=f"conv3_{i+1}")
        x = MaxPooling2D(border_mode="same")(x)
        for i, f in enumerate((512, 512, 512)):
            x = _conv(x, f, 3, name=f"conv4_{i+1}")
        conv4_3 = x
        x = MaxPooling2D(border_mode="same")(x)
        for i, f in enumerate((512, 512, 512)):
            x = _conv(x, f, 3, name=f"conv5_{i+1}")
        x = MaxPooling2D(pool_size=3, strides=1, border_mode="same")(x)
        x = _conv(x, 1024, 3, dilation=6, name="fc6")   # dilated fc6
        fc7 = _conv(x, 1024, 1, name="fc7")
        return conv4_3, fc7

    def _extras(self, x):
        feats = []
        x = _conv(x, 256, 1, name="conv6_1")
        x = _conv(x, 512, 3, stride=2, name="conv6_2")
        feats.append(x)
        if x.shape[0] > 1:
            x = _conv(x, 128, 1, name="conv7_1")
            x = _conv(x, 256, 3, stride=2, name="conv7_2")
            feats.append(x)
        # VALID 3×3 stages only while spatially possible (small inputs
        # collapse the pyramid early)
        for i in (8, 9):
            if x.shape[0] < 3:
                break
            x = _conv(x, 128, 1, name=f"conv{i}_1")
            x = _conv(x, 256, 3, pad="valid", name=f"conv{i}_2")
            feats.append(x)
        return feats

    def build(self) -> Model:
        inp = Input((self.img_size, self.img_size, 3), name="image")
        conv4_3, fc7 = self._backbone(inp)
        feats = [L2NormScale(name="conv4_3_norm")(conv4_3), fc7] + \
            self._extras(fc7)
        # anchor layout follows the graph: take sizes from the actual
        # feature maps (input sizes other than 300 reshape the pyramid)
        import dataclasses
        specs = []
        for feat, spec in zip(feats, self.specs):
            specs.append(dataclasses.replace(
                spec, feature_size=int(feat.shape[0])))
        self.specs = specs
        self.priors = generate_ssd_priors(self.specs,
                                          float(self.img_size))
        locs, confs = [], []
        for i, (feat, spec) in enumerate(zip(feats, self.specs)):
            k = num_priors_per_cell(spec)
            f = spec.feature_size
            n_cell_priors = f * f * k
            loc = Convolution2D(k * 4, 3, 3, border_mode="same",
                                name=f"head{i}_loc")(feat)
            conf = Convolution2D(k * self.n_classes, 3, 3,
                                 border_mode="same",
                                 name=f"head{i}_conf")(feat)
            locs.append(A.Lambda(
                lambda t: t.reshape(t.shape[0], -1, 4),
                output_shape=(n_cell_priors, 4),
                name=f"head{i}_loc_r")(loc))
            confs.append(A.Lambda(
                lambda t, c=self.n_classes:
                    t.reshape(t.shape[0], -1, c),
                output_shape=(n_cell_priors, self.n_classes),
                name=f"head{i}_conf_r")(conf))
        loc_all = Concatenate(axis=1)(locs)     # (B, P, 4)
        conf_all = Concatenate(axis=1)(confs)   # (B, P, C)
        # flatten into the single-output training contract
        p = self.num_priors
        flat = A.Lambda(
            lambda ts: jnp.concatenate(
                [ts[0].reshape(ts[0].shape[0], -1),
                 ts[1].reshape(ts[1].shape[0], -1)], axis=-1),
            output_shape=(p * 4 + p * self.n_classes,),
            name="ssd_flat")
        out = _MultiInLambda(flat)([loc_all, conf_all])
        return Model(inp, out, name="ssd300_vgg16")


class _MultiInLambda(KerasLayer):
    """Adapter: run an autograd Lambda over a list input."""

    def __init__(self, lam):
        super().__init__(name=lam.name + "_multi")
        self.lam = lam

    def call(self, params, inputs, *, training=False, rng=None):
        return self.lam.fn(inputs)

    def compute_output_shape(self, input_shape):
        return self.lam.shape_fn(input_shape)


def ssd300_vgg16(n_classes: int = 21) -> Tuple[Model, np.ndarray]:
    """→ (model, priors). `n_classes` includes background (VOC: 21)."""
    builder = SSDVGG(n_classes)
    return builder.build(), builder.priors
