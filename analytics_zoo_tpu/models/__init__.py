from analytics_zoo_tpu.models.common import Ranker, ZooModel

__all__ = ["ZooModel", "Ranker"]
