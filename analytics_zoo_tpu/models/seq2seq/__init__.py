from analytics_zoo_tpu.models.seq2seq.seq2seq import (
    Seq2seq, RNNEncoder, RNNDecoder, Bridge)

__all__ = ["Seq2seq", "RNNEncoder", "RNNDecoder", "Bridge"]
