"""Seq2seq (reference `Z/models/seq2seq/Seq2seq.scala:50-302`,
`RNNEncoder`/`RNNDecoder`, `Bridge`): generic RNN encoder-decoder with a
state bridge, teacher-forcing training on `[encoder_input,
decoder_input]`, and a greedy `infer` loop feeding back the last
timestep (same contract as the reference's `infer:114-150`).

The encoder/decoder stacks reuse the framework's `lax.scan` RNN layers;
state handoff uses `call_with_state` rather than BigDL's SelectTable
node plumbing.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape
from analytics_zoo_tpu.pipeline.api.keras.models import KerasNet
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, GRU, LSTM


def _make_rnn(rnn_type: str, hidden: int, name: str):
    t = rnn_type.lower()
    if t == "lstm":
        return LSTM(hidden, return_sequences=True, name=name)
    if t == "gru":
        return GRU(hidden, return_sequences=True, name=name)
    raise ValueError(f"unsupported rnn type {rnn_type}")


class RNNEncoder:
    """(reference `RNNEncoder.scala`) — a stack of recurrent layers whose
    final carries are exposed to the decoder."""

    def __init__(self, rnn_type: str = "lstm", num_layers: int = 1,
                 hidden_size: int = 128):
        self.rnn_type = rnn_type
        self.num_layers = int(num_layers)
        self.hidden_size = int(hidden_size)
        self.rnns = [_make_rnn(rnn_type, hidden_size, f"enc_rnn_{i}")
                     for i in range(self.num_layers)]


class RNNDecoder:
    """(reference `RNNDecoder.scala`)"""

    def __init__(self, rnn_type: str = "lstm", num_layers: int = 1,
                 hidden_size: int = 128):
        self.rnn_type = rnn_type
        self.num_layers = int(num_layers)
        self.hidden_size = int(hidden_size)
        self.rnns = [_make_rnn(rnn_type, hidden_size, f"dec_rnn_{i}")
                     for i in range(self.num_layers)]


class Bridge:
    """(reference `Bridge.scala`): adapts encoder final states into
    decoder initial states — "dense" (linear), "densenonlinear" (tanh),
    or "passthrough"."""

    def __init__(self, bridge_type: str = "passthrough"):
        if bridge_type not in ("passthrough", "dense", "densenonlinear"):
            raise ValueError(f"unsupported bridge type {bridge_type}")
        self.bridge_type = bridge_type
        self.denses: "list[Dense]" = []

    def make_layers(self, num_states: int, hidden: int) -> "list[Dense]":
        if self.bridge_type == "passthrough":
            self.denses = []
        else:
            act = None if self.bridge_type == "dense" else "tanh"
            self.denses = [Dense(hidden, activation=act,
                                 name=f"bridge_{i}")
                           for i in range(num_states)]
        return self.denses


class _Seq2seqNet(KerasNet):
    """The compiled container: inputs [enc_seq, dec_seq]."""

    def __init__(self, encoder: RNNEncoder, decoder: RNNDecoder,
                 bridge: Bridge, generator: Optional[KerasLayer],
                 input_shape: Shape, output_shape: Shape):
        super().__init__(name="seq2seq")
        self.encoder = encoder
        self.decoder = decoder
        self.bridge = bridge
        self.generator = generator
        self._enc_shape = tuple(input_shape)
        self._dec_shape = tuple(output_shape)
        self._given_input_shape = [self._enc_shape, self._dec_shape]
        states_per_layer = 2 if encoder.rnn_type.lower() == "lstm" else 1
        self._n_states = decoder.num_layers * states_per_layer
        self.bridge.make_layers(self._n_states, decoder.hidden_size)

    @property
    def layers(self):
        out = list(self.encoder.rnns) + list(self.decoder.rnns) + \
            list(self.bridge.denses)
        if self.generator is not None:
            out.append(self.generator)
        return out

    def build(self, rng, input_shape) -> dict:
        params = {}
        keys = jax.random.split(rng, len(self.layers))
        ki = 0
        shape = self._enc_shape
        for r in self.encoder.rnns:
            params[r.name] = r.init(keys[ki], shape)
            ki += 1
            shape = (shape[0], r.output_dim)
        shape = self._dec_shape
        for r in self.decoder.rnns:
            params[r.name] = r.init(keys[ki], shape)
            ki += 1
            shape = (shape[0], r.output_dim)
        for d in self.bridge.denses:
            params[d.name] = d.init(
                keys[ki], (self.encoder.hidden_size,))
            ki += 1
        if self.generator is not None:
            params[self.generator.name] = self.generator.init(
                keys[ki], shape)
        return params

    def _flatten_states(self, carries):
        flat = []
        for c in carries:
            if isinstance(c, tuple):
                flat.extend(c)
            else:
                flat.append(c)
        return flat

    def _unflatten_states(self, flat):
        lstm = self.decoder.rnn_type.lower() == "lstm"
        out = []
        i = 0
        for _ in range(self.decoder.num_layers):
            if lstm:
                out.append((flat[i], flat[i + 1]))
                i += 2
            else:
                out.append(flat[i])
                i += 1
        return out

    def apply(self, params, inputs, *, training=False, rng=None):
        enc_in, dec_in = inputs
        x = enc_in
        carries = []
        for r in self.encoder.rnns:
            x, carry = r.call_with_state(params[r.name], x,
                                         training=training, rng=rng)
            carries.append(carry)
        flat = self._flatten_states(carries)
        if self.bridge.denses:
            flat = [d.call(params[d.name], s)
                    for d, s in zip(self.bridge.denses, flat)]
        init_states = self._unflatten_states(flat)
        y = dec_in
        for r, state in zip(self.decoder.rnns, init_states):
            y, _ = r.call_with_state(params[r.name], y,
                                     initial_carry=state,
                                     training=training, rng=rng)
        if self.generator is not None:
            y = self.generator.call(params[self.generator.name], y,
                                    training=training, rng=rng)
        return y, {}

    def call(self, params, inputs, *, training=False, rng=None):
        out, _ = self.apply(params, inputs, training=training, rng=rng)
        return out

    # -- decode fast path ---------------------------------------------------
    # An RNN's "KV cache" is its carry: one (B, H) state pair per
    # decoder layer replaces the transformer's paged pool. `encode`
    # runs the encoder + bridge once; `decode_step` advances every
    # decoder layer ONE timestep via the layers' own `step` (the same
    # primitive `call_with_state`'s scan uses, so stepping is
    # numerically the full forward); `generate`/`generate_tokens`
    # close the loop as a shape-static `lax.while_loop` — O(T) decode
    # instead of `infer`'s O(T²) re-forward, and one compile total.

    def encode(self, params, enc_in):
        """Encoder + bridge once → the decoder's initial carries."""
        x = enc_in
        carries = []
        for r in self.encoder.rnns:
            x, carry = r.call_with_state(params[r.name], x)
            carries.append(carry)
        flat = self._flatten_states(carries)
        if self.bridge.denses:
            flat = [d.call(params[d.name], s)
                    for d, s in zip(self.bridge.denses, flat)]
        return self._unflatten_states(flat)

    def decode_step(self, params, carries, x):
        """One decoder timestep: x (B, F) → (new_carries, y (B, F'))
        with the generator applied. Identical math to one scan step of
        `apply` (input projection + `layer.step` per layer)."""
        y = x
        new_carries = []
        for r, c in zip(self.decoder.rnns, carries):
            p = params[r.name]
            z = y @ p["kernel"].astype(y.dtype) + \
                p["bias"].astype(y.dtype)
            c2, y = r.step(p, c, z)
            new_carries.append(c2)
        if self.generator is not None:
            y = self.generator.call(params[self.generator.name], y)
        return new_carries, y

    def generate(self, params, enc_in, start, max_new: int,
                 stop_sign=None, atol: float = 1e-8,
                 rtol: float = 1e-5):
        """Compiled greedy continuous-vector generation — the
        while_loop twin of `Seq2seq.infer`'s host loop, same
        semantics: outputs[:, 0] is `start` (B, F), each step appends
        the decoder output, and a slot stops (its stop vector NOT
        appended, like the host loop's break-before-concat) when the
        output matches `stop_sign` within allclose(atol, rtol).
        Returns (outputs (B, 1 + max_new, F), counts (B,))."""
        b = enc_in.shape[0]
        start = jnp.broadcast_to(jnp.asarray(start, enc_in.dtype),
                                 (b,) + jnp.asarray(start).shape[-1:])
        carries = self.encode(params, enc_in)
        f = start.shape[-1]
        max_new = int(max_new)
        buf = jnp.zeros((b, 1 + max_new, f), enc_in.dtype)
        buf = buf.at[:, 0].set(start)
        stop = (None if stop_sign is None
                else jnp.asarray(stop_sign, enc_in.dtype))

        def cond(st):
            _, _, _, done, _, i = st
            return jnp.logical_and(i < max_new,
                                   jnp.logical_not(jnp.all(done)))

        def body(st):
            carries, buf, last, done, n, i = st
            carries, y = self.decode_step(params, carries, last)
            if stop is None:
                hit = jnp.zeros((b,), jnp.bool_)
            else:
                hit = jnp.all(jnp.abs(y - stop) <=
                              atol + rtol * jnp.abs(stop), axis=-1)
            write = jnp.logical_and(jnp.logical_not(done),
                                    jnp.logical_not(hit))
            pos = jnp.clip(n, 0, max_new)
            cur = buf[jnp.arange(b), pos]
            buf = buf.at[jnp.arange(b), pos].set(
                jnp.where(write[:, None], y, cur))
            n = n + write.astype(jnp.int32)
            last = jnp.where(write[:, None], y, last)
            done = jnp.logical_or(done, hit)
            return (carries, buf, last, done, n, i + 1)

        st = (carries, buf, start, jnp.zeros((b,), jnp.bool_),
              jnp.ones((b,), jnp.int32), jnp.asarray(0, jnp.int32))
        _, buf, _, _, n, _ = jax.lax.while_loop(cond, body, st)
        return buf, n

    def generate_tokens(self, params, enc_in, start_token: int,
                        max_new: int, *, temperature=0.0,
                        top_k: int = 0, eos_id=None, rng=None):
        """Compiled categorical generation over a vocab-softmax
        generator (the chatbot configuration): token ids feed back as
        one-hot rows, sampling is greedy/temperature/top-k like the
        transformer path. Returns (ids (B, 1 + max_new), counts) with
        ids[:, 0] = start_token; an emitted `eos_id` IS appended."""
        from analytics_zoo_tpu.ops.sampling import sample_tokens
        if self.generator is None:
            raise ValueError("generate_tokens needs a categorical "
                             "generator (vocab-sized softmax)")
        b = enc_in.shape[0]
        vocab = int(self._dec_shape[-1])
        if rng is None:
            rng = jax.random.key(0)
        max_new = int(max_new)
        temp = jnp.broadcast_to(
            jnp.asarray(temperature, jnp.float32), (b,))
        carries = self.encode(params, enc_in)
        buf = jnp.full((b, 1 + max_new), int(start_token), jnp.int32)

        def cond(st):
            _, _, _, done, _, i = st
            return jnp.logical_and(i < max_new,
                                   jnp.logical_not(jnp.all(done)))

        def body(st):
            carries, buf, last, done, n, i = st
            x = jax.nn.one_hot(last, vocab, dtype=enc_in.dtype)
            carries, y = self.decode_step(params, carries, x)
            logits = jnp.log(jnp.clip(y.astype(jnp.float32), 1e-20,
                                      1.0))
            nxt = sample_tokens(jax.random.fold_in(rng, i), logits,
                                temp, top_k)
            active = jnp.logical_not(done)
            pos = jnp.clip(n, 0, max_new)
            cur = buf[jnp.arange(b), pos]
            buf = buf.at[jnp.arange(b), pos].set(
                jnp.where(active, nxt, cur))
            n = n + active.astype(jnp.int32)
            if eos_id is not None:
                done = jnp.logical_or(
                    done, jnp.logical_and(active, nxt == eos_id))
            last = jnp.where(active, nxt, last)
            return (carries, buf, last, done, n, i + 1)

        st = (carries, buf,
              jnp.full((b,), int(start_token), jnp.int32),
              jnp.zeros((b,), jnp.bool_), jnp.ones((b,), jnp.int32),
              jnp.asarray(0, jnp.int32))
        _, buf, _, _, n, _ = jax.lax.while_loop(cond, body, st)
        return buf, n

    def compute_output_shape(self, input_shape):
        shape = (self._dec_shape[0], self.decoder.hidden_size)
        if self.generator is not None:
            shape = tuple(self.generator.compute_output_shape(shape))
        return shape


class Seq2seq(ZooModel):
    def __init__(self, encoder: "RNNEncoder | None" = None,
                 decoder: "RNNDecoder | None" = None,
                 input_shape: Sequence[int] = (10, 32),
                 output_shape: Sequence[int] = (10, 32),
                 bridge: "Bridge | str | None" = None,
                 generator: Optional[KerasLayer] = None):
        super().__init__()
        self.encoder = encoder or RNNEncoder()
        self.decoder = decoder or RNNDecoder(
            rnn_type=self.encoder.rnn_type,
            num_layers=self.encoder.num_layers,
            hidden_size=self.encoder.hidden_size)
        if self.encoder.rnn_type.lower() != \
                self.decoder.rnn_type.lower():
            raise ValueError("encoder/decoder rnn types must match")
        if isinstance(bridge, str):
            bridge = Bridge(bridge)
        self.bridge = bridge or Bridge("passthrough")
        self.generator = generator
        self.input_shape = tuple(input_shape)
        self.output_shape = tuple(output_shape)

    def hyper_parameters(self):
        # encoder/decoder/bridge/generator are rebuilt from these
        return {"encoder": None, "decoder": None,
                "input_shape": self.input_shape,
                "output_shape": self.output_shape}

    def build_model(self) -> _Seq2seqNet:
        return _Seq2seqNet(self.encoder, self.decoder, self.bridge,
                           self.generator, self.input_shape,
                           self.output_shape)

    def _jitted(self, key, make):
        """Per-instance cache of jitted decode closures, so repeated
        `infer`/`infer_beam` calls at the same shapes reuse ONE
        compiled program (the compile-count contract the serving soak
        test asserts)."""
        cache = self.__dict__.setdefault("_jit_cache", {})
        if key not in cache:
            cache[key] = make()
        return cache[key]

    def infer(self, input_seq: np.ndarray, start_sign: np.ndarray,
              max_seq_len: int = 30,
              stop_sign: Optional[np.ndarray] = None) -> np.ndarray:
        """Greedy generation (reference `infer:114-150`): start from
        `start_sign`, append the last-timestep output each step; stop
        at `stop_sign` or `max_seq_len`. Same contract and outputs as
        the reference's host loop, but the loop is now the compiled
        `_Seq2seqNet.generate` while_loop — the encoder runs once and
        each token costs one decoder step instead of a full re-forward
        of the growing sequence, with zero per-token dispatches."""
        est = self.model.estimator
        est._ensure_initialized()
        params = est.params
        if input_seq.ndim == 2:
            input_seq = input_seq[None]
        input_seq = np.asarray(input_seq, np.float32)
        start = np.asarray(start_sign, np.float32).reshape(
            (1,) + np.asarray(start_sign).shape[-1:])
        has_stop = stop_sign is not None
        key = ("infer", input_seq.shape, start.shape,
               int(max_seq_len), has_stop)
        fn = self._jitted(key, lambda: jax.jit(
            lambda p, enc, st, stop: self.model.generate(
                p, enc, st, int(max_seq_len),
                stop_sign=stop, atol=1e-8)
            if has_stop else self.model.generate(
                p, enc, st, int(max_seq_len))))
        stop = (jnp.asarray(np.asarray(stop_sign, np.float32))
                if has_stop else jnp.zeros((), jnp.float32))
        out, counts = fn(params, jnp.asarray(input_seq),
                         jnp.asarray(start), stop)
        n = int(np.max(np.asarray(counts)))
        return np.asarray(out)[:, :n]

    def infer_beam(self, input_seq: np.ndarray, start_token: int,
                   beam_size: int = 4, max_seq_len: int = 30,
                   stop_token: Optional[int] = None,
                   length_penalty: float = 0.6
                   ) -> "tuple[list[int], float]":
        """Beam-search decoding over a CATEGORICAL generator (the
        decoder must end in a vocab-sized softmax, e.g. the chatbot's
        ``generator=Dense(V, activation="softmax")``); tokens feed
        back as one-hot rows. Beyond the reference (its `infer` is
        greedy only). Returns ``(token_ids, score)`` for the best
        finished hypothesis — ids exclude the start token — with
        GNMT-style length normalization ``logp / ((5+L)/6)**alpha``.
        """
        est = self.model.estimator
        est._ensure_initialized()
        params = est.params
        if input_seq.ndim == 2:
            input_seq = input_seq[None]
        vocab = self.output_shape[-1]

        def norm(logp, length):
            return logp / (((5.0 + length) / 6.0) ** length_penalty)

        # ONE jitted step reused across the whole beam loop: the old
        # loop fed a (n_beams, t, V) decoder input whose t GREW and
        # whose n_beams varied every token — a fresh trace/compile per
        # step. Shapes are now pinned at (beam_size, max_seq_len, ·)
        # and the timestep is a traced index; RNN causality makes
        # out[:, t] independent of the zero rows past t, so results
        # are unchanged while the compile count drops to one.
        input_seq = np.asarray(input_seq, np.float32)
        enc_rep = jnp.asarray(np.repeat(input_seq, beam_size, axis=0))
        key = ("beam", tuple(enc_rep.shape), int(max_seq_len), vocab)
        step = self._jitted(key, lambda: jax.jit(
            lambda p, enc, dec, t: self.model.forward(
                p, [enc, dec])[:, t, :]))
        dec_buf = np.zeros((beam_size, max_seq_len, vocab),
                           np.float32)

        beams = [([start_token], 0.0)]          # (ids incl. start, logp)
        finished: "list[tuple[list[int], float]]" = []
        for t in range(max_seq_len):
            if not beams:
                break
            # one batched step for all live hypotheses (dead rows
            # compute garbage that is sliced away)
            dec_buf[:] = 0.0
            for row, (ids, _) in enumerate(beams):
                dec_buf[row, np.arange(len(ids)), ids] = 1.0
            out = np.asarray(step(params, enc_rep,
                                  jnp.asarray(dec_buf),
                                  jnp.asarray(t, jnp.int32)))
            out = out[:len(beams)]
            logp_next = np.log(np.clip(out, 1e-20, 1.0))
            cand = []
            for (ids, lp), row in zip(beams, logp_next):
                for tok in np.argsort(row)[-beam_size:]:
                    cand.append((ids + [int(tok)], lp + row[tok]))
            cand.sort(key=lambda c: c[1], reverse=True)
            beams = []
            for ids, lp in cand[: beam_size * 2]:
                if stop_token is not None and ids[-1] == stop_token:
                    finished.append((ids[1:-1], norm(lp, len(ids) - 1)))
                elif len(beams) < beam_size:
                    beams.append((ids, lp))
            if len(finished) >= beam_size:
                break
        # unfinished sweeps score over their SCORED tokens only
        # (len(ids)-1 excludes the start token, same count the
        # stop-finished branch uses) — else junk that ran out the
        # clock out-scores an equally likely eos-terminated reply
        finished.extend((ids[1:], norm(lp, len(ids) - 1))
                        for ids, lp in beams)
        if not finished:
            return [], float("-inf")
        best = max(finished, key=lambda c: c[1])
        return list(best[0]), float(best[1])
