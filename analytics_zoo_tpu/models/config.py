"""Pretrained-model registries: published name → architecture +
weights artifact.

Reference: `ObjectDetectionConfig.scala:31-108` and
`ImageClassificationConfig` map published model names (e.g.
``"analytics-zoo_ssd-vgg16-300x300_PASCAL_0.1.0"``) to downloadable
``.model`` artifacts, and `ZooModel.loadModel`
(`models/common/ZooModel.scala:39-154`) materialises the model from
the artifact. The TPU registry keeps the name→architecture mapping
and resolves weights from LOCAL artifacts — TPU VMs have no implicit
download path, and weight provenance stays explicit. Resolution order:

1. an explicit ``weights_path=`` argument (``.npz`` weight file or a
   reference-format BigDL/zoo ``.model``);
2. ``$ZOO_TPU_PRETRAINED_DIR/<published name or arch>.{npz,model}``
   when the env var is set;
3. nothing found → ``FileNotFoundError`` unless ``allow_random=True``
   (architecture only, random init, with a log line) — a silently
   untrained "pretrained" model is a correctness trap (VERDICT r2).

``.npz`` weights are shape-validated against the built architecture
(`ZooModel.load_weights`); a ``.model`` artifact defines the model the
way the reference's `loadModel` does — it is imported with
`Net.load_bigdl` and returned as-is (the artifact's own architecture,
reference `Net.scala:91`).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from analytics_zoo_tpu.common.nncontext import logger


def _resolve_weights(name: str, arch: str,
                     weights_path: Optional[str]) -> Optional[str]:
    """Find a weights artifact for `name` (full published name) /
    `arch` (bare architecture): explicit path first, then
    ``$ZOO_TPU_PRETRAINED_DIR`` under both names, .npz before
    .model."""
    if weights_path is not None:
        if not os.path.exists(weights_path):
            raise FileNotFoundError(weights_path)
        return weights_path
    root = os.environ.get("ZOO_TPU_PRETRAINED_DIR")
    if root:
        # .npz (shape-validated into the built arch) under either stem
        # beats any .model (artifact-defined arch)
        for ext in (".npz", ".model"):
            for stem in dict.fromkeys((name, arch)):    # ordered, deduped
                cand = os.path.join(root, stem + ext)
                if os.path.exists(cand):
                    return cand
    return None


def _missing_weights_error(kind: str, name: str) -> FileNotFoundError:
    return FileNotFoundError(
        f"{kind}: no pretrained weights found for {name!r} — pass "
        f"weights_path= (.npz or reference .model), or place "
        f"<name>.npz/.model under $ZOO_TPU_PRETRAINED_DIR, or pass "
        f"allow_random=True for an untrained architecture")


def _load_bigdl_artifact(kind: str, arch: str, path: str,
                         ignored_args: dict, wrapper=None):
    """A reference ``.model`` artifact defines the model
    (`ZooModel.loadModel`): import it whole via the BigDL codec. The
    imported net is adopted into `wrapper` (an
    ImageClassifier/ObjectDetector built for a known arch, keeping
    detect()/save_weights/the full ZooModel surface) or, for archs
    outside the wrapper registries, returned as an
    `ImportedZooModel`."""
    from analytics_zoo_tpu.pipeline.api.net_load import Net
    dropped = {k: v for k, v in ignored_args.items() if v is not None}
    if dropped:
        logger.warning(
            "%s: %s resolves to a .model artifact whose saved "
            "architecture takes precedence — ignoring %s", kind, arch,
            dropped)
    logger.info("%s: %s loaded from reference artifact %s",
                kind, arch, path)
    net = Net.load_bigdl(path)
    if wrapper is not None:
        wrapper._model = net
        return wrapper
    from analytics_zoo_tpu.models.common import ImportedZooModel
    return ImportedZooModel(path, model_name=arch, net=net)


def _strip_published_name(name: str) -> str:
    """Accept the reference's full published names
    (``analytics-zoo_<arch>_<dataset>_<version>``) as well as bare
    architecture names."""
    parts = name.split("_")
    if len(parts) >= 2 and parts[0] in ("analytics-zoo", "zoo"):
        return parts[1]
    return name


class ImageClassificationConfig:
    """(reference `ImageClassificationConfig`): published
    classification models."""

    @staticmethod
    def names() -> Tuple[str, ...]:
        from analytics_zoo_tpu.models.image.imageclassification import \
            ImageClassifier
        return tuple(ImageClassifier.ARCHS)

    @staticmethod
    def create(name: str, input_shape=(224, 224, 3), classes: int = 1000,
               weights_path: Optional[str] = None,
               allow_random: bool = False):
        from analytics_zoo_tpu.models.image.imageclassification import \
            ImageClassifier
        arch = _strip_published_name(name).lower()
        wp = _resolve_weights(name, arch, weights_path)
        if wp is None and not allow_random:
            raise _missing_weights_error("ImageClassificationConfig",
                                         name)
        if wp is not None and wp.endswith(".model"):
            wrapper = None
            if arch in ImageClassifier.ARCHS:
                wrapper = ImageClassifier(model_name=arch,
                                          input_shape=input_shape,
                                          classes=classes)
            return _load_bigdl_artifact(
                "ImageClassificationConfig", arch, wp,
                {"input_shape": (None if input_shape == (224, 224, 3)
                                 else input_shape),
                 "classes": None if classes == 1000 else classes},
                wrapper=wrapper)
        model = ImageClassifier(model_name=arch,
                                input_shape=input_shape,
                                classes=classes)
        model.compile()
        if wp is not None:
            model.load_weights(wp)
            logger.info("ImageClassificationConfig: %s weights from %s",
                        arch, wp)
        else:
            logger.info("ImageClassificationConfig: %s randomly "
                        "initialized (allow_random=True)", arch)
        return model


class ObjectDetectionConfig:
    """(reference `ObjectDetectionConfig.scala:31`): published
    detection models."""

    @staticmethod
    def names() -> Tuple[str, ...]:
        from analytics_zoo_tpu.models.image.objectdetection \
            .object_detector import CONFIGS
        return tuple(sorted(CONFIGS))

    @staticmethod
    def create(name: str, n_classes: Optional[int] = None,
               img_size: Optional[int] = None,
               weights_path: Optional[str] = None,
               allow_random: bool = False):
        from analytics_zoo_tpu.models.image.objectdetection import \
            ObjectDetector
        arch = _strip_published_name(name).lower()
        wp = _resolve_weights(name, arch, weights_path)
        if wp is None and not allow_random:
            raise _missing_weights_error("ObjectDetectionConfig", name)
        if wp is not None and wp.endswith(".model"):
            wrapper = None
            if arch in ObjectDetectionConfig.names():
                wrapper = ObjectDetector(model_name=arch,
                                         n_classes=n_classes,
                                         img_size=img_size)
            return _load_bigdl_artifact(
                "ObjectDetectionConfig", arch, wp,
                {"n_classes": n_classes, "img_size": img_size},
                wrapper=wrapper)
        model = ObjectDetector(model_name=arch, n_classes=n_classes,
                               img_size=img_size)
        model.compile()
        if wp is not None:
            model.load_weights(wp)
            logger.info("ObjectDetectionConfig: %s weights from %s",
                        arch, wp)
        else:
            logger.info("ObjectDetectionConfig: %s randomly "
                        "initialized (allow_random=True)", arch)
        return model
