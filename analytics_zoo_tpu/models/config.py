"""Pretrained-model registries: published name → architecture +
weights file.

Reference: `ObjectDetectionConfig.scala:31` and
`ImageClassificationConfig` map published model names (e.g.
``"analytics-zoo_ssd-vgg16-300x300_PASCAL_0.1.0"``) to downloadable
``.model`` artifacts. The TPU registry keeps the name→architecture
mapping and loads weights from LOCAL ``.npz`` files (produced by
``ZooModel.save_weights``) — TPU VMs have no implicit download path,
and weight provenance stays explicit. Resolution order for weights:

1. an explicit ``weights_path=`` argument;
2. ``$ZOO_TPU_PRETRAINED_DIR/<name>.npz`` when the env var is set;
3. none → randomly initialized (architecture only), with a log line.

Every load shape-validates each tensor against the built architecture
(`ZooModel.load_weights`).
"""

from __future__ import annotations

import os
import re
from typing import Optional, Tuple

from analytics_zoo_tpu.common.nncontext import logger


def _resolve_weights(name: str, weights_path: Optional[str]) -> \
        Optional[str]:
    if weights_path is not None:
        if not os.path.exists(weights_path):
            raise FileNotFoundError(weights_path)
        return weights_path
    root = os.environ.get("ZOO_TPU_PRETRAINED_DIR")
    if root:
        cand = os.path.join(root, f"{name}.npz")
        if os.path.exists(cand):
            return cand
    return None


def _strip_published_name(name: str) -> str:
    """Accept the reference's full published names
    (``analytics-zoo_<arch>_<dataset>_<version>``) as well as bare
    architecture names."""
    parts = name.split("_")
    if len(parts) >= 2 and parts[0] in ("analytics-zoo", "zoo"):
        return parts[1]
    return name


class ImageClassificationConfig:
    """(reference `ImageClassificationConfig`): published
    classification models."""

    @staticmethod
    def names() -> Tuple[str, ...]:
        from analytics_zoo_tpu.models.image.imageclassification import \
            ImageClassifier
        return tuple(ImageClassifier.ARCHS)

    @staticmethod
    def create(name: str, input_shape=(224, 224, 3), classes: int = 1000,
               weights_path: Optional[str] = None):
        from analytics_zoo_tpu.models.image.imageclassification import \
            ImageClassifier
        arch = _strip_published_name(name).lower()
        model = ImageClassifier(model_name=arch,
                                input_shape=input_shape,
                                classes=classes)
        model.compile()
        wp = _resolve_weights(arch, weights_path)
        if wp is not None:
            model.load_weights(wp)
            logger.info("ImageClassificationConfig: %s weights from %s",
                        arch, wp)
        else:
            logger.info("ImageClassificationConfig: %s randomly "
                        "initialized (no weights file)", arch)
        return model


class ObjectDetectionConfig:
    """(reference `ObjectDetectionConfig.scala:31`): published
    detection models."""

    @staticmethod
    def names() -> Tuple[str, ...]:
        from analytics_zoo_tpu.models.image.objectdetection \
            .object_detector import CONFIGS
        return tuple(sorted(CONFIGS))

    @staticmethod
    def create(name: str, n_classes: Optional[int] = None,
               img_size: Optional[int] = None,
               weights_path: Optional[str] = None):
        from analytics_zoo_tpu.models.image.objectdetection import \
            ObjectDetector
        arch = _strip_published_name(name).lower()
        model = ObjectDetector(model_name=arch, n_classes=n_classes,
                               img_size=img_size)
        model.compile()
        wp = _resolve_weights(arch, weights_path)
        if wp is not None:
            model.load_weights(wp)
            logger.info("ObjectDetectionConfig: %s weights from %s",
                        arch, wp)
        else:
            logger.info("ObjectDetectionConfig: %s randomly "
                        "initialized (no weights file)", arch)
        return model
