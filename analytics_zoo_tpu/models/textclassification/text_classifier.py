"""TextClassifier (reference
`Z/models/textclassification/TextClassifier.scala:34-70`): CNN/LSTM/GRU
encoder → Dense(128) → Dropout(0.2) → ReLU → Dense(class_num, softmax).

Two input modes, like the reference:
- with an `embedding` layer (e.g. `WordEmbedding.from_glove`): input is
  (sequence_length,) token ids;
- without: input is pre-embedded (sequence_length, token_length).
"""

from __future__ import annotations

from typing import Optional

from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.pipeline.api.keras.models import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Activation, Convolution1D, Dense, Dropout, GlobalMaxPooling1D, GRU,
    LSTM)
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer


class TextClassifier(ZooModel):
    def __init__(self, class_num: int, token_length: int = 200,
                 sequence_length: int = 500, encoder: str = "cnn",
                 encoder_output_dim: int = 256,
                 embedding: Optional[KerasLayer] = None):
        super().__init__()
        if encoder.lower() not in ("cnn", "lstm", "gru"):
            raise ValueError(f"unsupported encoder {encoder}")
        self.class_num = int(class_num)
        self.token_length = int(token_length)
        self.sequence_length = int(sequence_length)
        self.encoder = encoder.lower()
        self.encoder_output_dim = int(encoder_output_dim)
        self.embedding = embedding

    def hyper_parameters(self):
        return {"class_num": self.class_num,
                "token_length": self.token_length,
                "sequence_length": self.sequence_length,
                "encoder": self.encoder,
                "encoder_output_dim": self.encoder_output_dim}

    def build_model(self) -> Sequential:
        m = Sequential(name="text_classifier")
        if self.embedding is not None:
            if self.embedding._given_input_shape is None:
                self.embedding._given_input_shape = \
                    (self.sequence_length,)
            m.add(self.embedding)
            first_shape = None
        else:
            first_shape = (self.sequence_length, self.token_length)
        if self.encoder == "cnn":
            m.add(Convolution1D(self.encoder_output_dim, 5,
                                activation="relu",
                                input_shape=first_shape))
            m.add(GlobalMaxPooling1D())
        elif self.encoder == "lstm":
            m.add(LSTM(self.encoder_output_dim,
                       input_shape=first_shape))
        else:
            m.add(GRU(self.encoder_output_dim,
                      input_shape=first_shape))
        m.add(Dense(128))
        m.add(Dropout(0.2))
        m.add(Activation("relu"))
        m.add(Dense(self.class_num, activation="softmax"))
        return m
