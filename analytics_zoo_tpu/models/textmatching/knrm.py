"""KNRM — kernel-pooling neural ranking model
(reference `Z/models/textmatching/KNRM.scala:60-105`, `TextMatcher` base).

Input: (batch, text1_length + text2_length) int ids — concatenated then
sliced, exactly like the reference ("share weights for embedding is not
supported, thus the model takes concatenated input and slices").
Output: 1 score per row; `target_mode="ranking"` trains with `rank_hinge`
(rows alternate positive/negative — `TextSet.from_relation_pairs`
produces that layout), `"classification"` ends in sigmoid.

Ranker mixin supplies NDCG/MAP evaluation over relation lists.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from analytics_zoo_tpu.models.textmatching.text_matcher import TextMatcher
from analytics_zoo_tpu.pipeline.api import autograd as A
from analytics_zoo_tpu.pipeline.api.keras.engine import Input
from analytics_zoo_tpu.pipeline.api.keras.models import Model
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Dense, Embedding, WordEmbedding)


class KNRM(TextMatcher):
    def __init__(self, text1_length: int, text2_length: int,
                 vocab_size: int, embed_size: int = 300,
                 embed_weights: Optional[np.ndarray] = None,
                 train_embed: bool = True, kernel_num: int = 21,
                 sigma: float = 0.1, exact_sigma: float = 0.001,
                 target_mode: str = "ranking"):
        super().__init__(text1_length, vocab_size,
                         embed_size=embed_size,
                         embed_weights=embed_weights,
                         train_embed=train_embed,
                         target_mode=target_mode)
        if kernel_num <= 1:
            raise ValueError("kernel_num must be > 1")
        self.text2_length = int(text2_length)
        self.kernel_num = int(kernel_num)
        self.sigma = float(sigma)
        self.exact_sigma = float(exact_sigma)

    def hyper_parameters(self):
        return {"text1_length": self.text1_length,
                "text2_length": self.text2_length,
                "vocab_size": self.vocab_size,
                "embed_size": self.embed_size,
                "train_embed": self.train_embed,
                "kernel_num": self.kernel_num,
                "sigma": self.sigma,
                "exact_sigma": self.exact_sigma,
                "target_mode": self.target_mode}

    def build_model(self) -> Model:
        t1, t2 = self.text1_length, self.text2_length
        inp = Input((t1 + t2,), name="concat_ids")
        if self.embed_weights is not None:
            embed_layer = WordEmbedding(self.embed_weights,
                                        trainable=self.train_embed,
                                        name="embedding")
        else:
            embed_layer = Embedding(self.vocab_size, self.embed_size,
                                    init="uniform", name="embedding")
            embed_layer.trainable = self.train_embed
        embedding = embed_layer(inp)
        text1 = embedding[0:t1]
        text2 = embedding[t1:t1 + t2]
        # translation matrix: (B, t1, t2)
        mm = A.batch_dot(text1, text2, axes=(2, 2))
        kernels = []
        for i in range(self.kernel_num):
            mu = 1.0 / (self.kernel_num - 1) + \
                (2.0 * i) / (self.kernel_num - 1) - 1.0
            if mu > 1.0:  # exact-match kernel
                mu = 1.0
                sigma = self.exact_sigma
            else:
                sigma = self.sigma
            mm_exp = A.exp((mm - mu) * (mm - mu) *
                           (-0.5 / (sigma * sigma)))
            mm_doc_sum = A.sum(mm_exp, axis=2)
            mm_log = A.log(mm_doc_sum + 1.0)
            kernels.append(A.sum(mm_log, axis=1, keepdims=True))
        phi = A.squeeze(A.stack(kernels, axis=1), dim=2)
        if self.target_mode == "ranking":
            out = Dense(1, init="uniform", name="score")(phi)
        else:
            out = Dense(1, init="uniform", activation="sigmoid",
                        name="score")(phi)
        return Model(inp, out, name="knrm")

    # -- convenience for relation data --------------------------------------
    @staticmethod
    def concat_inputs(x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        return np.concatenate([x1, x2], axis=1)

    def evaluate_ndcg_on_relations(self, x1, x2, labels, group_ids,
                                   k: int = 3, batch_size: int = 128
                                   ) -> float:
        scores = self.predict(self.concat_inputs(x1, x2),
                              batch_size=batch_size)
        return self.evaluate_ndcg(scores, labels, group_ids, k=k)

    def evaluate_map_on_relations(self, x1, x2, labels, group_ids,
                                  batch_size: int = 128) -> float:
        scores = self.predict(self.concat_inputs(x1, x2),
                              batch_size=batch_size)
        return self.evaluate_map(scores, labels, group_ids)
