from analytics_zoo_tpu.models.textmatching.knrm import KNRM
from analytics_zoo_tpu.models.textmatching.text_matcher import \
    TextMatcher

__all__ = ["KNRM", "TextMatcher"]
