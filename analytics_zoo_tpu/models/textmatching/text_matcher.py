"""TextMatcher — base class for text-matching models (reference
`P/models/textmatching/text_matcher.py:24-47`,
`Z/models/textmatching/TextMatcher.scala`).

Holds the shared text-matching hyperparameters (query length, vocab,
embedding config, ranking-vs-classification target) and the Ranker
NDCG/MAP evaluation; concrete models (KNRM) build their graph on top.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from analytics_zoo_tpu.models.common import Ranker, ZooModel


class TextMatcher(ZooModel, Ranker):
    """Base for text matchers scoring (text1, text2) pairs.

    ``target_mode``: "ranking" (pairwise rank-hinge training over
    alternating positive/negative rows) or "classification" (sigmoid
    relevance probability) — the reference's two training regimes.
    """

    def __init__(self, text1_length: int, vocab_size: int,
                 embed_size: int = 300,
                 embed_weights: Optional[np.ndarray] = None,
                 train_embed: bool = True,
                 target_mode: str = "ranking"):
        super().__init__()
        if target_mode not in ("ranking", "classification"):
            raise ValueError(
                "target_mode must be ranking|classification, got "
                f"{target_mode!r}")
        self.text1_length = int(text1_length)
        self.vocab_size = int(vocab_size)
        self.embed_size = int(embed_size)
        self.embed_weights = embed_weights
        self.train_embed = bool(train_embed)
        self.target_mode = target_mode
