"""ctypes bindings for the native runtime
(`analytics_zoo_tpu/native/src/*.cpp`).

The reference ships native code as JNI `.so`s in `zoo-core-dist-all`
(SURVEY.md §2.11); here the C++ ships as package data (`native/src/`) and is
built on first use with g++ (no pybind11 in the image — plain C ABI +
ctypes). Every consumer has a pure-Python fallback, so the framework
degrades gracefully where a toolchain is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

# sources ship as package data (src/); the .so is built next to them
# on first use, so pip-installed copies work without a build step
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "src")
_SO_PATH = os.path.join(_NATIVE_DIR, "libzoo_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    srcs = [os.path.join(_NATIVE_DIR, f)
            for f in ("host_arena.cpp", "serving_queue.cpp",
                      "serving_http.cpp")]
    cmd = ["g++", "-O2", "-fPIC", "-std=c++17", "-shared", "-o",
           _SO_PATH] + srcs + ["-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def load_native() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        srcs = [os.path.join(_NATIVE_DIR, f)
                for f in ("host_arena.cpp", "serving_queue.cpp",
                          "serving_http.cpp")]
        stale = os.path.exists(_SO_PATH) and any(
            os.path.getmtime(s) > os.path.getmtime(_SO_PATH)
            for s in srcs if os.path.exists(s))
        if (not os.path.exists(_SO_PATH) or stale) and not _build():
            if not os.path.exists(_SO_PATH):   # stale-but-present is usable
                _build_failed = True
                return None
            import logging
            logging.getLogger("analytics_zoo_tpu").warning(
                "native: rebuild failed; loading STALE %s (sources are "
                "newer than the binary)", _SO_PATH)
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            _build_failed = True
            return None
        # signatures
        lib.arena_create.restype = ctypes.c_void_p
        lib.arena_create.argtypes = [ctypes.c_size_t]
        lib.arena_destroy.argtypes = [ctypes.c_void_p]
        lib.arena_alloc.restype = ctypes.c_size_t
        lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                    ctypes.c_size_t]
        lib.arena_base.restype = ctypes.c_void_p
        lib.arena_base.argtypes = [ctypes.c_void_p]
        lib.arena_used.restype = ctypes.c_size_t
        lib.arena_used.argtypes = [ctypes.c_void_p]
        lib.arena_capacity.restype = ctypes.c_size_t
        lib.arena_capacity.argtypes = [ctypes.c_void_p]
        lib.arena_reset.argtypes = [ctypes.c_void_p]
        lib.arena_copy.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                   ctypes.c_void_p, ctypes.c_size_t]
        lib.squeue_create.restype = ctypes.c_void_p
        lib.squeue_destroy.argtypes = [ctypes.c_void_p]
        lib.squeue_put.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.squeue_take.restype = ctypes.c_int
        lib.squeue_take.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.squeue_size.restype = ctypes.c_int
        lib.squeue_size.argtypes = [ctypes.c_void_p]
        lib.zoo_http_create.restype = ctypes.c_void_p
        lib.zoo_http_create.argtypes = [ctypes.c_int, ctypes.c_long]
        lib.zoo_http_port.restype = ctypes.c_int
        lib.zoo_http_port.argtypes = [ctypes.c_void_p]
        lib.zoo_http_set_health.argtypes = [ctypes.c_void_p,
                                            ctypes.c_char_p]
        lib.zoo_http_next.restype = ctypes.c_long
        lib.zoo_http_next.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.c_long, ctypes.POINTER(ctypes.c_long),
            ctypes.c_char_p, ctypes.c_long]
        lib.zoo_http_respond.restype = ctypes.c_int
        lib.zoo_http_respond.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_long]
        try:  # absent from a stale pre-tracing .so — optional
            lib.zoo_http_respond_hdr.restype = ctypes.c_int
            lib.zoo_http_respond_hdr.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p]
        except AttributeError:
            pass
        lib.zoo_http_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class HostArena:
    """Bump-arena sample cache (PersistentMemoryAllocator analog).

    `put(array) -> offset`; `view(offset, shape, dtype)` returns a
    zero-copy numpy view into arena memory.
    """

    def __init__(self, capacity_bytes: int):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.arena_create(capacity_bytes)
        if not self._handle:
            raise MemoryError(f"arena_create({capacity_bytes}) failed")
        self.capacity = capacity_bytes

    def put(self, arr: np.ndarray) -> int:
        arr = np.ascontiguousarray(arr)
        off = self._lib.arena_alloc(self._handle, arr.nbytes, 64)
        if off == ctypes.c_size_t(-1).value:
            raise MemoryError("arena full")
        self._lib.arena_copy(self._handle, off,
                             arr.ctypes.data_as(ctypes.c_void_p),
                             arr.nbytes)
        return off

    def view(self, offset: int, shape, dtype) -> np.ndarray:
        base = self._lib.arena_base(self._handle)
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        buf = (ctypes.c_char * nbytes).from_address(base + offset)
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    @property
    def used(self) -> int:
        return self._lib.arena_used(self._handle)

    def reset(self):
        self._lib.arena_reset(self._handle)

    def close(self):
        if self._handle:
            self._lib.arena_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ServingQueue:
    """Blocking pool of slot ids (LinkedBlockingQueue analog)."""

    def __init__(self):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.squeue_create()

    def put(self, slot: int):
        self._lib.squeue_put(self._handle, slot)

    def take(self, timeout_ms: int = -1) -> int:
        """Returns a slot id, or -1 on timeout."""
        return self._lib.squeue_take(self._handle, timeout_ms)

    def size(self) -> int:
        return self._lib.squeue_size(self._handle)

    def close(self):
        if self._handle:
            self._lib.squeue_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PyServingQueue:
    """Pure-Python fallback with the same surface."""

    def __init__(self):
        import queue
        self._q = queue.Queue()

    def put(self, slot: int):
        self._q.put(slot)

    def take(self, timeout_ms: int = -1) -> int:
        import queue as _queue
        try:
            timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
            return self._q.get(timeout=timeout)
        except _queue.Empty:
            return -1

    def size(self) -> int:
        return self._q.qsize()

    def close(self):
        pass


def make_serving_queue():
    try:
        return ServingQueue()
    except RuntimeError:
        return PyServingQueue()


class NativeHttpServer:
    """C++ HTTP front-end (`src/serving_http.cpp`): accept/parse/queue
    run native (no GIL contention with the compute thread); Python
    pulls request bytes and posts response bytes."""

    def __init__(self, port: int = 0, max_body: int = 16 << 20):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._max_body = max_body
        self._handle = lib.zoo_http_create(port, max_body)
        if not self._handle:
            raise OSError(f"zoo_http_create({port}) failed")
        self._port = lib.zoo_http_port(self._handle)
        self._tls = threading.local()  # per-thread request buffers

    @property
    def port(self) -> int:
        return self._port

    def set_health(self, payload_json: str):
        if self._handle:
            self._lib.zoo_http_set_health(self._handle,
                                          payload_json.encode())

    def next_request(self, timeout_ms: int = -1):
        """Returns (req_id, path, body_bytes, trace_id_or_None), or
        None on timeout, or raises StopIteration after close().
        ``trace_id`` is the request's X-Zoo-Trace-Id header when the
        C++ side captured one (it rides the path buffer after a
        ``\\n``; a stale pre-tracing .so simply never sends it).
        Buffers are per-THREAD (reused across polls — no 16MB alloc
        churn), so concurrent worker pulls never share a buffer."""
        if not self._handle:
            raise StopIteration
        if not hasattr(self._tls, "buf"):
            self._tls.buf = ctypes.create_string_buffer(self._max_body)
            self._tls.path = ctypes.create_string_buffer(1024)
        buf, path = self._tls.buf, self._tls.path
        rid = ctypes.c_long()
        n = self._lib.zoo_http_next(
            self._handle, buf, len(buf), timeout_ms,
            ctypes.byref(rid), path, len(path))
        if n == -1:
            return None
        if n == -2:
            raise StopIteration
        route, _, trace = path.value.decode().partition("\n")
        return rid.value, route, buf.raw[:n], trace or None

    def respond(self, req_id: int, status: int, body: bytes,
                trace_id: "Optional[str]" = None) -> bool:
        if not self._handle:
            return False
        if trace_id and hasattr(self._lib, "zoo_http_respond_hdr"):
            return self._lib.zoo_http_respond_hdr(
                self._handle, req_id, status, body, len(body),
                trace_id.encode()) == 0
        return self._lib.zoo_http_respond(
            self._handle, req_id, status, body, len(body)) == 0

    def close(self):
        if self._handle:
            self._lib.zoo_http_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
