// Native HTTP serving front-end (L9 native tier).
//
// Role: the reference serves models behind native/JVM web frontends
// (OpenVINO JNI + Java POJO AbstractInferenceModel + Spring samples,
// SURVEY.md §2.8/§2.11.2). Here the socket/HTTP hot path is C++ — the
// Python side only sees (request bytes in, response bytes out) through
// a C ABI, so accept/parse/queue never touch the GIL while JAX runs.
//
// Protocol kept deliberately minimal and robust: HTTP/1.1,
// Connection: close per request, POST bodies up to a caller-set cap;
// GET /health answered entirely in C++ (no Python round trip).
//
// C ABI (ctypes-loaded by analytics_zoo_tpu.native):
//   zoo_http_create(port, max_body)  -> handle (0 on failure)
//   zoo_http_port(h)                 -> bound port
//   zoo_http_next(h, buf, cap, timeout_ms, &req_id, path, path_cap)
//       -> body length >=0, -1 timeout, -2 shutdown
//       (when the request carried an X-Zoo-Trace-Id header, the path
//        buffer holds "path\ntrace_id" — '\n' never appears in a
//        request line, and an old .so simply never emits it, so the
//        Python side degrades gracefully against a stale binary)
//   zoo_http_respond(h, req_id, status, body, len) -> 0 ok
//   zoo_http_respond_hdr(h, req_id, status, body, len, trace)
//       -> same, echoing trace as an X-Zoo-Trace-Id response header
//   zoo_http_set_health(h, json)     -> health payload
//   zoo_http_destroy(h)
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>

namespace {

struct Request {
    long id;
    std::string path;
    std::string body;
    std::string trace;  // X-Zoo-Trace-Id header value ("" = none)
    int fd;
};

struct Server {
    int listen_fd = -1;
    int port = 0;
    long max_body = 16 * 1024 * 1024;
    std::atomic<bool> stop{false};
    std::atomic<int> conn_threads{0};
    std::thread acceptor;
    std::mutex mu;
    std::condition_variable cv;
    std::deque<Request> queue;
    // req_id -> (connection fd, response content-type code:
    // 0 = application/json, 1 = Prometheus text (GET /metrics),
    // 2 = text/html (GET /debug/dashboard))
    std::map<long, std::pair<int, int>> pending;
    long next_id = 1;
    std::string health = "{\"status\": \"ok\"}";
};

void write_all(int fd, const char* p, size_t n) {
    while (n > 0) {
        ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
        if (w <= 0) return;
        p += w;
        n -= static_cast<size_t>(w);
    }
}

void send_response(int fd, int status, const std::string& body,
                   const char* ctype = "application/json",
                   const std::string& extra_hdr = "") {
    const char* reason = status == 200 ? "OK" : status == 400
        ? "Bad Request" : status == 404 ? "Not Found"
        : status == 413 ? "Payload Too Large" : status == 503
        ? "Service Unavailable" : "Error";
    std::string head = "HTTP/1.1 " + std::to_string(status) + " " +
        reason + "\r\nContent-Type: " + ctype + "\r\n"
        "Content-Length: " + std::to_string(body.size()) +
        "\r\nConnection: close\r\n" + extra_hdr + "\r\n";
    write_all(fd, head.data(), head.size());
    write_all(fd, body.data(), body.size());
}

// wire-safe trace ids only (mirrors tracing.sanitize_trace_id): no
// header/log injection, bounded length
std::string sanitize_trace(const std::string& v) {
    std::string out;
    for (char c : v) {
        if ((c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') ||
            (c >= '0' && c <= '9') || c == '_' || c == '.' ||
            c == '-') {
            out.push_back(c);
            if (out.size() >= 64) break;
        } else if (c != ' ' && c != '\t') {
            return "";  // anything else: drop the header entirely
        }
    }
    return out;
}

// read one HTTP request (headers + Content-Length body); false = drop
bool read_request(Server* s, int fd, std::string* method,
                  std::string* path, std::string* body,
                  std::string* trace) {
    // overall deadline: SO_RCVTIMEO only bounds each recv, not a
    // slow-trickle client; destroy() relies on this hard cap
    const auto deadline = std::chrono::steady_clock::now() +
        std::chrono::seconds(60);
    std::string buf;
    char chunk[4096];
    size_t header_end = std::string::npos;
    while (header_end == std::string::npos) {
        if (std::chrono::steady_clock::now() > deadline) return false;
        ssize_t r = ::recv(fd, chunk, sizeof(chunk), 0);
        if (r <= 0) return false;
        buf.append(chunk, static_cast<size_t>(r));
        header_end = buf.find("\r\n\r\n");
        if (buf.size() > 64 * 1024 && header_end == std::string::npos)
            return false;  // header flood
    }
    std::string head = buf.substr(0, header_end);
    size_t sp1 = head.find(' ');
    size_t sp2 = head.find(' ', sp1 + 1);
    if (sp1 == std::string::npos || sp2 == std::string::npos)
        return false;
    *method = head.substr(0, sp1);
    *path = head.substr(sp1 + 1, sp2 - sp1 - 1);
    long content_len = 0;
    // case-insensitive Content-Length / X-Zoo-Trace-Id scan
    for (size_t pos = 0; (pos = head.find(':', pos)) !=
         std::string::npos; ++pos) {
        size_t ls = head.rfind('\n', pos);
        ls = ls == std::string::npos ? 0 : ls + 1;
        std::string name = head.substr(ls, pos - ls);
        for (auto& c : name) c = static_cast<char>(::tolower(c));
        if (name == "content-length") {
            content_len = ::atol(head.c_str() + pos + 1);
        } else if (name == "x-zoo-trace-id" && trace) {
            size_t ve = head.find('\r', pos);
            if (ve == std::string::npos) ve = head.find('\n', pos);
            if (ve == std::string::npos) ve = head.size();
            *trace = sanitize_trace(head.substr(pos + 1,
                                                ve - pos - 1));
        }
    }
    if (content_len < 0 || content_len > s->max_body) {
        send_response(fd, 413, "{\"error\": \"body too large\"}");
        return false;
    }
    *body = buf.substr(header_end + 4);
    while (static_cast<long>(body->size()) < content_len) {
        if (std::chrono::steady_clock::now() > deadline) return false;
        ssize_t r = ::recv(fd, chunk, sizeof(chunk), 0);
        if (r <= 0) return false;
        body->append(chunk, static_cast<size_t>(r));
    }
    body->resize(static_cast<size_t>(content_len));
    return true;
}

// per-connection: read + parse + enqueue off the acceptor thread, so
// one slow client cannot stall other connections or /health
void handle_conn(Server* s, int fd) {
    std::string method, path, body, trace;
    if (read_request(s, fd, &method, &path, &body, &trace)) {
        // GET /metrics[?...], /metrics/json and GET /debug/* ride
        // the worker queue: Python owns the metrics registry, the
        // trace store, and the fleet federation collector. The
        // pending code picks the response content-type: Prometheus
        // text for /metrics (with or without a ?fleet=1 query),
        // HTML for /debug/dashboard, JSON for everything else
        // including /metrics/json.
        bool is_json_metrics = method == "GET" &&
            (path == "/metrics/json" ||
             path.rfind("/metrics/json?", 0) == 0);
        bool is_metrics = method == "GET" && !is_json_metrics &&
            (path == "/metrics" ||
             path.rfind("/metrics?", 0) == 0);
        bool is_debug = method == "GET" &&
            path.rfind("/debug/", 0) == 0;
        bool is_dashboard = method == "GET" &&
            (path == "/debug/dashboard" ||
             path.rfind("/debug/dashboard?", 0) == 0);
        if (method == "GET" && path == "/health") {
            std::string payload;
            {
                std::lock_guard<std::mutex> g(s->mu);
                payload = s->health;
            }
            send_response(fd, 200, payload);
            ::close(fd);
        } else if (method != "POST" && !is_metrics &&
                   !is_json_metrics && !is_debug) {
            send_response(fd, 404, "{\"error\": \"POST only\"}");
            ::close(fd);
        } else {
            {
                std::lock_guard<std::mutex> g(s->mu);
                Request req;
                req.id = s->next_id++;
                req.path = path;
                req.body = std::move(body);
                req.trace = std::move(trace);
                req.fd = fd;
                s->pending[req.id] =
                    {fd, is_metrics ? 1 : (is_dashboard ? 2 : 0)};
                s->queue.push_back(std::move(req));
            }
            s->cv.notify_one();
        }
    } else {
        ::close(fd);
    }
    s->conn_threads.fetch_sub(1);
}

void accept_loop(Server* s) {
    while (!s->stop.load()) {
        sockaddr_in peer{};
        socklen_t len = sizeof(peer);
        int fd = ::accept(s->listen_fd,
                          reinterpret_cast<sockaddr*>(&peer), &len);
        if (fd < 0) {
            if (s->stop.load()) return;
            // e.g. EMFILE under fd exhaustion: don't busy-spin a core
            std::this_thread::sleep_for(std::chrono::milliseconds(10));
            continue;
        }
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        timeval tv{30, 0};  // bound slow/stuck clients
        ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        s->conn_threads.fetch_add(1);
        try {
            std::thread(handle_conn, s, fd).detach();
        } catch (...) {  // thread spawn failure: shed the connection
            s->conn_threads.fetch_sub(1);
            send_response(fd, 503, "{\"error\": \"overloaded\"}");
            ::close(fd);
        }
    }
}

}  // namespace

extern "C" {

void* zoo_http_create(int port, long max_body) {
    auto* s = new Server();
    if (max_body > 0) s->max_body = max_body;
    s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (s->listen_fd < 0) {
        delete s;
        return nullptr;
    }
    int one = 1;
    ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
                 sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(s->listen_fd, 128) != 0) {
        ::close(s->listen_fd);
        delete s;
        return nullptr;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
                  &alen);
    s->port = ntohs(addr.sin_port);
    s->acceptor = std::thread(accept_loop, s);
    return s;
}

int zoo_http_port(void* h) {
    return h ? static_cast<Server*>(h)->port : -1;
}

void zoo_http_set_health(void* h, const char* json) {
    auto* s = static_cast<Server*>(h);
    std::lock_guard<std::mutex> g(s->mu);
    s->health = json ? json : "{}";
}

long zoo_http_next(void* h, char* buf, long cap, long timeout_ms,
                   long* req_id, char* path, long path_cap) {
    auto* s = static_cast<Server*>(h);
    std::unique_lock<std::mutex> g(s->mu);
    auto ready = [&] { return s->stop.load() || !s->queue.empty(); };
    if (timeout_ms < 0) {
        s->cv.wait(g, ready);
    } else if (!s->cv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                               ready)) {
        return -1;
    }
    if (s->stop.load()) return -2;
    Request req = std::move(s->queue.front());
    s->queue.pop_front();
    if (static_cast<long>(req.body.size()) > cap) {
        // caller buffer too small — answer 503 here, skip the request
        s->pending.erase(req.id);
        g.unlock();
        send_response(req.fd, 503,
                      "{\"error\": \"server buffer too small\"}");
        ::close(req.fd);
        return -1;
    }
    std::memcpy(buf, req.body.data(), req.body.size());
    if (path_cap > 0) {
        // piggyback the trace id after the path ('\n' separated) so
        // the ABI stays stable — a trace id never fits worse than
        // the path alone did (path_cap is 1024, ids cap at 64)
        std::string out = req.path;
        if (!req.trace.empty()) out += "\n" + req.trace;
        long n = std::min<long>(path_cap - 1,
                                static_cast<long>(out.size()));
        std::memcpy(path, out.data(), static_cast<size_t>(n));
        path[n] = '\0';
    }
    *req_id = req.id;
    return static_cast<long>(req.body.size());
}

static int respond_impl(void* h, long req_id, int status,
                        const char* body, long len,
                        const char* trace) {
    auto* s = static_cast<Server*>(h);
    int fd = -1;
    int ctype_code = 0;
    {
        std::lock_guard<std::mutex> g(s->mu);
        auto it = s->pending.find(req_id);
        if (it == s->pending.end()) return -1;
        fd = it->second.first;
        ctype_code = it->second.second;
        s->pending.erase(it);
    }
    std::string extra;
    if (trace && *trace) {
        std::string t = sanitize_trace(trace);
        if (!t.empty()) extra = "X-Zoo-Trace-Id: " + t + "\r\n";
    }
    send_response(fd, status,
                  std::string(body, static_cast<size_t>(len)),
                  ctype_code == 1 ? "text/plain; version=0.0.4"
                  : ctype_code == 2 ? "text/html; charset=utf-8"
                  : "application/json",
                  extra);
    ::close(fd);
    return 0;
}

int zoo_http_respond(void* h, long req_id, int status,
                     const char* body, long len) {
    return respond_impl(h, req_id, status, body, len, nullptr);
}

int zoo_http_respond_hdr(void* h, long req_id, int status,
                         const char* body, long len,
                         const char* trace) {
    return respond_impl(h, req_id, status, body, len, trace);
}

void zoo_http_destroy(void* h) {
    auto* s = static_cast<Server*>(h);
    if (!s) return;
    s->stop.store(true);
    ::shutdown(s->listen_fd, SHUT_RDWR);
    ::close(s->listen_fd);
    s->cv.notify_all();
    if (s->acceptor.joinable()) s->acceptor.join();
    // connection threads are detached; worst-case lifetime is the 60s
    // read deadline + one 30s SO_RCVTIMEO recv. Wait past that; if a
    // thread is somehow still alive, deliberately LEAK the Server —
    // a one-off leak at shutdown beats a use-after-free.
    for (int i = 0; i < 95000 && s->conn_threads.load() > 0; ++i)
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
    if (s->conn_threads.load() > 0) return;
    {
        std::lock_guard<std::mutex> g(s->mu);
        for (auto& kv : s->pending) ::close(kv.second.first);
    }
    delete s;
}

}  // extern "C"
