// Blocking model-pool queue — the serving concurrency core of the
// reference's InferenceModel (reference
// `Z/pipeline/inference/InferenceModel.scala:32-38`: a
// LinkedBlockingQueue holding `supportedConcurrentNum` weight-sharing
// model copies; threads take a model, predict, put it back).
//
// Here the queue holds integer slot ids referencing compiled executables
// on the Python side; take() blocks with an optional timeout so a
// serving facade can bound latency.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <queue>

namespace {

struct SQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::queue<int> items;
};

}  // namespace

extern "C" {

void* squeue_create() { return new SQueue(); }

void squeue_destroy(void* handle) {
  delete static_cast<SQueue*>(handle);
}

void squeue_put(void* handle, int id) {
  SQueue* q = static_cast<SQueue*>(handle);
  {
    std::lock_guard<std::mutex> lock(q->mu);
    q->items.push(id);
  }
  q->cv.notify_one();
}

// Returns the taken id, or -1 on timeout. timeout_ms < 0 waits forever.
int squeue_take(void* handle, long timeout_ms) {
  SQueue* q = static_cast<SQueue*>(handle);
  std::unique_lock<std::mutex> lock(q->mu);
  auto ready = [q] { return !q->items.empty(); };
  if (timeout_ms < 0) {
    q->cv.wait(lock, ready);
  } else if (!q->cv.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                             ready)) {
    return -1;
  }
  int id = q->items.front();
  q->items.pop();
  return id;
}

int squeue_size(void* handle) {
  SQueue* q = static_cast<SQueue*>(handle);
  std::lock_guard<std::mutex> lock(q->mu);
  return static_cast<int>(q->items.size());
}

}  // extern "C"
