// Host memory arena — the TPU-VM analog of the reference's persistent-
// memory JNI allocator (reference
// zoo/src/main/java/com/intel/analytics/zoo/pmem/PersistentMemoryAllocator.java:37-42
// `@native initialize/allocate/free/copy`, backed by libmemkind on Optane).
//
// TPU VMs have no Optane; the role of the tier — a large, cheaply
// allocated, sequentially filled sample cache that bypasses the Python
// allocator — is played by an mmap-backed bump arena with an atomic
// offset, safe for concurrent ingest threads.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>

#include <sys/mman.h>

namespace {

struct Arena {
  uint8_t* base;
  size_t capacity;
  std::atomic<size_t> used;
};

constexpr size_t kBad = ~static_cast<size_t>(0);

}  // namespace

extern "C" {

void* arena_create(size_t capacity) {
  void* mem = mmap(nullptr, capacity, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE, -1, 0);
  if (mem == MAP_FAILED) return nullptr;
  Arena* a = new (std::nothrow) Arena{static_cast<uint8_t*>(mem),
                                      capacity, {0}};
  if (!a) {
    munmap(mem, capacity);
    return nullptr;
  }
  return a;
}

void arena_destroy(void* handle) {
  if (!handle) return;
  Arena* a = static_cast<Arena*>(handle);
  munmap(a->base, a->capacity);
  delete a;
}

// Returns the offset of the allocation, or SIZE_MAX when full.
size_t arena_alloc(void* handle, size_t nbytes, size_t align) {
  Arena* a = static_cast<Arena*>(handle);
  if (align == 0) align = 64;
  size_t cur = a->used.load(std::memory_order_relaxed);
  size_t start, end;
  do {
    start = (cur + align - 1) & ~(align - 1);
    end = start + nbytes;
    if (end > a->capacity) return kBad;
  } while (!a->used.compare_exchange_weak(cur, end,
                                          std::memory_order_acq_rel));
  return start;
}

void* arena_base(void* handle) {
  return static_cast<Arena*>(handle)->base;
}

size_t arena_used(void* handle) {
  return static_cast<Arena*>(handle)->used.load(
      std::memory_order_acquire);
}

size_t arena_capacity(void* handle) {
  return static_cast<Arena*>(handle)->capacity;
}

void arena_reset(void* handle) {
  static_cast<Arena*>(handle)->used.store(0, std::memory_order_release);
}

// The analog of PersistentMemoryAllocator.copy: memcpy into the arena.
void arena_copy(void* handle, size_t offset, const void* src,
                size_t nbytes) {
  Arena* a = static_cast<Arena*>(handle);
  std::memcpy(a->base + offset, src, nbytes);
}

}  // extern "C"
