"""Relation datasets for ranking (reference
`Z/feature/common/Relations.scala`: `Relation(id1, id2, label)` container
+ CSV/parquet readers; pair/list generation lives in TextSet —
`fromRelationPairs` `TextSet.scala:398`, `fromRelationLists` `:502`)."""

from __future__ import annotations

import csv
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Relation:
    id1: str
    id2: str
    label: int


class Relations:
    @staticmethod
    def read(path: str) -> "list[Relation]":
        """CSV with columns id1,id2,label (reference `Relations.read`)."""
        out = []
        with open(path, newline="", encoding="utf-8") as f:
            reader = csv.reader(f)
            rows = list(reader)
        start = 0
        if rows and rows[0][:2] == ["id1", "id2"]:
            start = 1
        for row in rows[start:]:
            if len(row) < 3:
                continue
            out.append(Relation(row[0], row[1], int(row[2])))
        return out

    @staticmethod
    def read_parquet(path: str) -> "list[Relation]":
        import pandas as pd
        df = pd.read_parquet(path)
        return [Relation(str(r.id1), str(r.id2), int(r.label))
                for r in df.itertuples()]

    @staticmethod
    def generate_relation_pairs(relations: "list[Relation]",
                                seed: int = 0
                                ) -> "list[tuple[Relation, Relation]]":
        """(positive, negative) pairs per id1 — the training layout for
        `rank_hinge` loss (reference `TextSet.fromRelationPairs`)."""
        rng = np.random.RandomState(seed)
        by_q: "dict[str, dict[int, list[Relation]]]" = {}
        for r in relations:
            by_q.setdefault(r.id1, {}).setdefault(
                1 if r.label > 0 else 0, []).append(r)
        pairs = []
        for q, groups in by_q.items():
            pos, neg = groups.get(1, []), groups.get(0, [])
            if not pos or not neg:
                continue
            for p in pos:
                pairs.append((p, neg[rng.randint(len(neg))]))
        return pairs

    @staticmethod
    def group_by_query(relations: "list[Relation]"
                       ) -> "dict[str, list[Relation]]":
        """id1 → candidate list (reference `TextSet.fromRelationLists`
        evaluation layout for NDCG/MAP)."""
        groups: "dict[str, list[Relation]]" = {}
        for r in relations:
            groups.setdefault(r.id1, []).append(r)
        return groups
