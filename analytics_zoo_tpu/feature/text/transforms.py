"""Text transformers (reference `Z/feature/text/{Tokenizer,Normalizer,
WordIndexer,SequenceShaper,TextFeatureToSample}.scala`)."""

from __future__ import annotations

import re

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing, Sample
from analytics_zoo_tpu.feature.text.text_feature import TextFeature


class Tokenizer(Preprocessing):
    """Whitespace tokenization (reference `Tokenizer.scala`)."""

    def apply(self, feature: TextFeature) -> TextFeature:
        feature[TextFeature.TOKENS] = feature.text.split()
        return feature


class Normalizer(Preprocessing):
    """Lower-case + strip non-alphanumeric chars from tokens (reference
    `Normalizer.scala`)."""

    _pattern = re.compile(r"[^a-zA-Z0-9]")

    def apply(self, feature: TextFeature) -> TextFeature:
        tokens = feature.tokens
        if tokens is None:
            raise ValueError("Normalizer requires Tokenizer first")
        norm = [self._pattern.sub("", t.lower()) for t in tokens]
        feature[TextFeature.TOKENS] = [t for t in norm if t]
        return feature


class WordIndexer(Preprocessing):
    """tokens → indices using a word→index map (reference
    `WordIndexer.scala`). Unknown words are dropped (reference
    behavior)."""

    def __init__(self, word_index: "Dict[str, int]"):
        self.word_index = word_index

    def apply(self, feature: TextFeature) -> TextFeature:
        tokens = feature.tokens
        if tokens is None:
            raise ValueError("WordIndexer requires tokens")
        feature[TextFeature.INDEXED] = [
            self.word_index[t] for t in tokens if t in self.word_index]
        return feature


class SequenceShaper(Preprocessing):
    """Pad/truncate the index sequence to `len` (reference
    `SequenceShaper.scala`; `trunc_mode` pre|post, pad value 0)."""

    def __init__(self, len: int, trunc_mode: str = "pre",  # noqa: A002
                 pad_element: int = 0):
        self.seq_len = int(len)
        if trunc_mode not in ("pre", "post"):
            raise ValueError("trunc_mode must be pre|post")
        self.trunc_mode = trunc_mode
        self.pad_element = int(pad_element)

    def apply(self, feature: TextFeature) -> TextFeature:
        idx = feature.indices
        if idx is None:
            raise ValueError("SequenceShaper requires WordIndexer first")
        if len(idx) > self.seq_len:
            idx = (idx[-self.seq_len:] if self.trunc_mode == "pre"
                   else idx[:self.seq_len])
        else:
            idx = idx + [self.pad_element] * (self.seq_len - len(idx))
        feature[TextFeature.INDEXED] = idx
        return feature


class TextFeatureToSample(Preprocessing):
    """indices (+label) → Sample (reference
    `TextFeatureToSample.scala`)."""

    def apply(self, feature: TextFeature) -> TextFeature:
        idx = feature.indices
        if idx is None:
            raise ValueError("TextFeatureToSample requires indices")
        label = feature.label
        feature[TextFeature.SAMPLE] = Sample(
            feature=np.asarray(idx, np.int32),
            label=None if label is None else np.asarray(label))
        return feature
