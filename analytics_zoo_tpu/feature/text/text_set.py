"""TextSet (reference `Z/feature/text/TextSet.scala:43-246`): a corpus of
TextFeatures with the standard NLP pipeline — tokenize → normalize →
word2idx → shapeSequence → generateSample — plus vocab build/save/load,
directory/CSV/parquet readers, and relation-based ranking datasets
(`fromRelationPairs:398`, `fromRelationLists:502`)."""

from __future__ import annotations

import csv
import os
from collections import Counter
from typing import Dict, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.feature.feature_set import FeatureSet
from analytics_zoo_tpu.feature.text.relations import (Relation,
                                                      Relations)
from analytics_zoo_tpu.feature.text.text_feature import TextFeature
from analytics_zoo_tpu.feature.text.transforms import (
    Normalizer, SequenceShaper, TextFeatureToSample, Tokenizer,
    WordIndexer)


class TextSet:
    def __init__(self, features: "list[TextFeature]"):
        self.features = features
        self._word_index: Optional[Dict[str, int]] = None

    # -- readers (reference TextSet.read / readCSV / readParquet) ----------
    @staticmethod
    def read(path: str) -> "TextSet":
        """Read a `<dir>/<category>/<file>.txt` layout (the 20-newsgroups
        layout the reference's text-classification recipe uses)."""
        classes = sorted(d for d in os.listdir(path)
                         if os.path.isdir(os.path.join(path, d)))
        feats = []
        for label, c in enumerate(classes):
            cdir = os.path.join(path, c)
            for fname in sorted(os.listdir(cdir)):
                fpath = os.path.join(cdir, fname)
                if not os.path.isfile(fpath):
                    continue
                with open(fpath, encoding="utf-8", errors="ignore") as f:
                    feats.append(TextFeature(
                        f.read(), label=np.asarray([label], np.int32),
                        uri=fpath))
        ts = TextSet(feats)
        ts.n_classes = len(classes)
        return ts

    @staticmethod
    def read_csv(path: str) -> "TextSet":
        """CSV rows `id,text` (reference `TextSet.readCSV`)."""
        feats = []
        with open(path, newline="", encoding="utf-8") as f:
            for row in csv.reader(f):
                if len(row) < 2:
                    continue
                feats.append(TextFeature(row[1], uri=row[0]))
        return TextSet(feats)

    @staticmethod
    def read_parquet(path: str) -> "TextSet":
        import pandas as pd
        df = pd.read_parquet(path)
        return TextSet([TextFeature(str(r.text), uri=str(r.id))
                        for r in df.itertuples()])

    @staticmethod
    def from_texts(texts: Sequence[str], labels=None) -> "TextSet":
        feats = []
        for i, t in enumerate(texts):
            lbl = None if labels is None else \
                np.asarray([labels[i]], np.int32)
            feats.append(TextFeature(t, label=lbl))
        return TextSet(feats)

    # -- pipeline (each step returns self for chaining, reference style) ---
    def tokenize(self) -> "TextSet":
        tok = Tokenizer()
        for f in self.features:
            tok.apply(f)
        return self

    def normalize(self) -> "TextSet":
        norm = Normalizer()
        for f in self.features:
            norm.apply(f)
        return self

    def word2idx(self, remove_topn: int = 0,
                 max_words_num: int = -1,
                 min_freq: int = 1,
                 existing_map: Optional[Dict[str, int]] = None
                 ) -> "TextSet":
        """Build the vocab (reference `TextSet.word2idx`: drop the
        `remove_topn` most frequent, keep at most `max_words_num` with
        freq >= `min_freq`; index starts at 1, 0 = padding)."""
        if existing_map is not None:
            self._word_index = dict(existing_map)
        else:
            counter: Counter = Counter()
            for f in self.features:
                if f.tokens is None:
                    raise ValueError("call tokenize() before word2idx()")
                counter.update(f.tokens)
            ranked = counter.most_common()
            ranked = ranked[remove_topn:]
            ranked = [(w, c) for w, c in ranked if c >= min_freq]
            if max_words_num > 0:
                ranked = ranked[:max_words_num]
            self._word_index = {w: i + 1 for i, (w, _) in
                                enumerate(ranked)}
        indexer = WordIndexer(self._word_index)
        for f in self.features:
            indexer.apply(f)
        return self

    def shape_sequence(self, len: int,  # noqa: A002
                       trunc_mode: str = "pre") -> "TextSet":
        shaper = SequenceShaper(len, trunc_mode)
        for f in self.features:
            shaper.apply(f)
        return self

    def generate_sample(self) -> "TextSet":
        to_sample = TextFeatureToSample()
        for f in self.features:
            to_sample.apply(f)
        return self

    # -- vocab --------------------------------------------------------------
    def get_word_index(self) -> Optional[Dict[str, int]]:
        return self._word_index

    def save_word_index(self, path: str):
        if self._word_index is None:
            raise ValueError("no word index built")
        with open(path, "w", encoding="utf-8") as f:
            for w, i in self._word_index.items():
                f.write(f"{w} {i}\n")

    def load_word_index(self, path: str) -> "TextSet":
        idx = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                w, i = line.rsplit(" ", 1)
                idx[w] = int(i)
        self._word_index = idx
        return self

    # -- ranking datasets ---------------------------------------------------
    @staticmethod
    def from_relation_pairs(relations: "list[Relation]",
                            corpus1: "TextSet", corpus2: "TextSet",
                            seed: int = 0) -> "tuple[np.ndarray, np.ndarray]":
        """→ (x1, x2) arrays with rows alternating positive/negative —
        the `rank_hinge` training layout (reference
        `TextSet.fromRelationPairs:398`). Corpora must be indexed+shaped;
        URIs are the relation ids."""
        t1 = {f[TextFeature.URI]: f.indices for f in corpus1.features}
        t2 = {f[TextFeature.URI]: f.indices for f in corpus2.features}
        pairs = Relations.generate_relation_pairs(relations, seed=seed)
        rows1, rows2 = [], []
        for pos, neg in pairs:
            rows1 += [t1[pos.id1], t1[neg.id1]]
            rows2 += [t2[pos.id2], t2[neg.id2]]
        return (np.asarray(rows1, np.int32), np.asarray(rows2, np.int32))

    @staticmethod
    def from_relation_lists(
            relations: "list[Relation]", corpus1: "TextSet",
            corpus2: "TextSet"
    ) -> "tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]":
        """→ (x1, x2, labels, group_ids) flattened candidate lists for
        NDCG/MAP evaluation (reference `TextSet.fromRelationLists:502`)."""
        t1 = {f[TextFeature.URI]: f.indices for f in corpus1.features}
        t2 = {f[TextFeature.URI]: f.indices for f in corpus2.features}
        groups = Relations.group_by_query(relations)
        rows1, rows2, labels, gids = [], [], [], []
        for gid, (q, rels) in enumerate(sorted(groups.items())):
            for r in rels:
                rows1.append(t1[r.id1])
                rows2.append(t2[r.id2])
                labels.append(r.label)
                gids.append(gid)
        return (np.asarray(rows1, np.int32), np.asarray(rows2, np.int32),
                np.asarray(labels, np.int32), np.asarray(gids, np.int32))

    # -- export -------------------------------------------------------------
    def to_feature_set(self, memory_type="dram") -> FeatureSet:
        samples = []
        for f in self.features:
            s = f.get_sample()
            if s is None:
                raise ValueError("call generate_sample() first")
            samples.append(s)
        return FeatureSet.sample_rdd(samples, memory_type=memory_type)

    def to_arrays(self) -> "tuple[np.ndarray, Optional[np.ndarray]]":
        xs, ys = [], []
        has_label = False
        for f in self.features:
            if f.indices is None:
                raise ValueError("pipeline incomplete: no indices")
            xs.append(f.indices)
            if f.label is not None:
                has_label = True
                ys.append(np.asarray(f.label))
        return (np.asarray(xs, np.int32),
                np.stack(ys) if has_label else None)

    def __len__(self):
        return len(self.features)
