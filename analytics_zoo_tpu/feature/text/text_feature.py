"""TextFeature (reference `Z/feature/text/TextFeature.scala`): one text
record carrying text, label, tokens, indices, sample through the
pipeline."""

from __future__ import annotations

from typing import Optional



class TextFeature(dict):
    TEXT = "text"
    LABEL = "label"
    TOKENS = "tokens"
    INDEXED = "indexed_tokens"
    SAMPLE = "sample"
    URI = "uri"

    def __init__(self, text: Optional[str] = None, label=None,
                 uri: Optional[str] = None):
        super().__init__()
        if text is not None:
            self[self.TEXT] = text
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def text(self) -> str:
        return self.get(self.TEXT, "")

    @property
    def label(self):
        return self.get(self.LABEL)

    @property
    def tokens(self):
        return self.get(self.TOKENS)

    @property
    def indices(self):
        return self.get(self.INDEXED)

    def get_sample(self):
        return self.get(self.SAMPLE)
