from analytics_zoo_tpu.feature.text.text_feature import TextFeature
from analytics_zoo_tpu.feature.text.text_set import TextSet
from analytics_zoo_tpu.feature.text.transforms import (
    Tokenizer, Normalizer, WordIndexer, SequenceShaper,
    TextFeatureToSample)
from analytics_zoo_tpu.feature.text.relations import (
    Relation, Relations)

__all__ = ["TextFeature", "TextSet", "Tokenizer", "Normalizer",
           "WordIndexer", "SequenceShaper", "TextFeatureToSample",
           "Relation", "Relations"]
