from analytics_zoo_tpu.feature.common import (
    Preprocessing, ChainedPreprocessing, ArrayToTensor, SeqToTensor,
    ScalarToTensor, TensorToSample, FeatureLabelPreprocessing, Sample)
from analytics_zoo_tpu.feature.feature_set import FeatureSet, MemoryType
