from analytics_zoo_tpu.feature.common import (
    Preprocessing, ChainedPreprocessing, ArrayToTensor, SeqToTensor,
    ScalarToTensor, TensorToSample, FeatureLabelPreprocessing, Sample)
from analytics_zoo_tpu.feature.feature_set import FeatureSet, MemoryType
from analytics_zoo_tpu.feature.rdd import LocalRdd, collect_shard, \
    is_rdd_like, is_spark_dataframe, process_shard_spec
