"""Spark RDD/DataFrame ingest adapter (the L2↔Spark bridge).

Reference: the defining trait of analytics-zoo is that data arrives as
Spark `RDD[Sample]` / DataFrames — `FeatureSet.rdd`
(`Z/feature/FeatureSet.scala:308-335`), `KerasNet.fit(RDD[Sample])`
(`Z/pipeline/api/keras/models/Topology.scala:411`), and nnframes'
`NNEstimator.getDataSet` (`Z/pipeline/nnframes/NNEstimator.scala:361-390`).

TPU-native redesign: Spark stays an *ingest role*, not a runtime
dependency (SURVEY.md §2.10). Anything that quacks like an RDD —
``getNumPartitions()`` + ``mapPartitionsWithIndex(f)`` + ``collect()``
— can feed a :class:`FeatureSet`:

- a real ``pyspark.RDD`` (when pyspark is installed; none of the code
  here imports pyspark — the protocol is duck-typed, and the lambdas
  shipped to executors use only the stdlib);
- :class:`LocalRdd`, the in-process reference implementation used by
  tests and by no-Spark deployments.

Multi-host sharding: each JAX process keeps only the partitions
``p % process_count == process_index`` (round-robin over partitions, the
same per-host ownership Spark locality gave the reference's executors),
so an N-host TPU pod ingests 1/N of the RDD per host without any
cross-host traffic beyond what Spark itself does.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, Optional


from analytics_zoo_tpu.common.nncontext import logger


def process_shard_spec() -> "tuple[int, int]":
    """(shard_index, num_shards) for this host = (process_index,
    process_count). Single-process (the common case, incl. tests) is
    (0, 1)."""
    import jax

    try:
        return jax.process_index(), jax.process_count()
    except Exception:  # backend not initialized yet
        return 0, 1


def is_rdd_like(obj: Any) -> bool:
    """The duck-typed RDD protocol."""
    return all(hasattr(obj, m) for m in
               ("mapPartitionsWithIndex", "collect", "getNumPartitions"))


def is_spark_dataframe(obj: Any) -> bool:
    """A pyspark DataFrame quacks: has .rdd, .columns and .toPandas but
    is not a pandas DataFrame (pandas has no .rdd)."""
    return hasattr(obj, "rdd") and hasattr(obj, "toPandas") \
        and hasattr(obj, "columns")


def _partition_filter(shard_index: int, num_shards: int) -> Callable:
    """Closure shipped to executors: keep round-robin-owned partitions.

    Stdlib-only on purpose — a real pyspark executor pickles this and
    must not need analytics_zoo_tpu installed cluster-side."""

    def keep(pid, it):
        return it if pid % num_shards == shard_index else iter(())

    return keep


def iter_shard(rdd: Any, shard_index: Optional[int] = None,
               num_shards: Optional[int] = None) -> Iterator:
    """Stream this host's round-robin share of an RDD-like's records.

    Uses ``toLocalIterator()`` when the RDD provides it (pyspark does:
    one partition resident at a time on the driver, reference
    NNEstimator.scala:571-674 streams partitions through executors the
    same way) and falls back to ``collect()`` otherwise."""
    if shard_index is None or num_shards is None:
        shard_index, num_shards = process_shard_spec()
    if num_shards == 1:
        owned = rdd
    else:
        n_parts = rdd.getNumPartitions()
        if n_parts < num_shards:
            logger.warning(
                "RDD has %d partitions < %d ingest hosts; repartition "
                "the RDD for balanced multi-host ingest", n_parts,
                num_shards)
        owned = rdd.mapPartitionsWithIndex(
            _partition_filter(shard_index, num_shards))
    tli = getattr(owned, "toLocalIterator", None)
    src = tli() if callable(tli) else owned.collect()
    # driver-side record count (the executor-shipped closures above
    # stay stdlib-only); ONE chunked increment per stream, no lock in
    # the per-record path
    n = 0
    try:
        for rec in src:
            n += 1
            yield rec
    finally:
        from analytics_zoo_tpu.common.observability import counter
        if n:
            counter("zoo_tpu_ingest_records_total",
                    help="records emitted per ingest stage",
                    labels={"stage": "rdd"}).inc(n)


def collect_shard(rdd: Any, shard_index: Optional[int] = None,
                  num_shards: Optional[int] = None) -> "list":
    """Collect this host's round-robin share of an RDD-like's records
    (materialised; prefer :func:`iter_shard` for streaming)."""
    return list(iter_shard(rdd, shard_index, num_shards))


class LocalRdd:
    """In-process reference implementation of the RDD ingest protocol.

    Plays the role pyspark's RDD plays in the reference, for tests and
    Spark-less deployments; the FeatureSet/nnframes ingest code treats
    it and a real ``pyspark.RDD`` identically.
    """

    def __init__(self, records: Iterable[Any], num_partitions: int = 4):
        records = list(records)
        self._parts: "list[list]" = [[] for _ in range(num_partitions)]
        if records:
            # contiguous split, like sc.parallelize
            n = len(records)
            k = num_partitions
            lo = 0
            for i in range(k):
                hi = lo + n // k + (1 if i < n % k else 0)
                self._parts[i] = records[lo:hi]
                lo = hi

    @staticmethod
    def of_partitions(parts: "list[list]") -> "LocalRdd":
        r = LocalRdd([], num_partitions=len(parts))
        r._parts = [list(p) for p in parts]
        return r

    def getNumPartitions(self) -> int:
        return len(self._parts)

    def mapPartitionsWithIndex(self, f) -> "LocalRdd":
        return LocalRdd.of_partitions(
            [list(f(i, iter(p))) for i, p in enumerate(self._parts)])

    def mapPartitions(self, f) -> "LocalRdd":
        return self.mapPartitionsWithIndex(lambda i, it: f(it))

    def map(self, f) -> "LocalRdd":
        return self.mapPartitionsWithIndex(
            lambda i, it: (f(x) for x in it))

    def filter(self, f) -> "LocalRdd":
        return self.mapPartitionsWithIndex(
            lambda i, it: (x for x in it if f(x)))

    def repartition(self, n: int) -> "LocalRdd":
        return LocalRdd(self.collect(), num_partitions=n)

    def collect(self) -> "list":
        return list(itertools.chain.from_iterable(self._parts))

    def toLocalIterator(self) -> Iterator:
        """Stream records one partition at a time (pyspark parity);
        `partitions_fetched` counts entered partitions so tests can
        assert laziness."""
        for p in self._parts:
            self.partitions_fetched = getattr(
                self, "partitions_fetched", 0) + 1
            yield from p

    def count(self) -> int:
        return sum(len(p) for p in self._parts)
