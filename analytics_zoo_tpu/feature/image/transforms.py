"""Image preprocessing transformers.

Reference: the 25+ OpenCV-backed transformers in `Z/feature/image/*.scala`
(resize, crops, flip, color jitter, expand/filler, normalize, Mat→tensor,
to-sample — SURVEY.md §2.2). PIL+numpy play the OpenCV role on the host;
anything per-batch and differentiable can instead run on-device in JAX.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing, Sample
from analytics_zoo_tpu.feature.image.imageset import ImageFeature


class ImagePreprocessing(Preprocessing):
    """Base: operates on ImageFeature, transforming the `image` ndarray."""

    def apply_image(self, img: np.ndarray, feature: ImageFeature
                    ) -> np.ndarray:
        raise NotImplementedError

    def apply(self, feature: ImageFeature) -> ImageFeature:
        feature[ImageFeature.IMAGE] = self.apply_image(
            feature[ImageFeature.IMAGE], feature)
        return feature


class ImageResize(ImagePreprocessing):
    """(reference `ImageResize.scala`)"""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = int(resize_h), int(resize_w)

    def apply_image(self, img, feature):
        from PIL import Image
        pil = Image.fromarray(img.astype(np.uint8) if
                              img.dtype != np.uint8 else img)
        return np.asarray(pil.resize((self.w, self.h),
                                     Image.BILINEAR), img.dtype)


class ImageAspectScale(ImagePreprocessing):
    """Resize the short side to `scale` keeping aspect ratio, cap long
    side (reference `ImageAspectScale.scala`)."""

    def __init__(self, scale: int, max_size: int = 1000):
        self.scale, self.max_size = int(scale), int(max_size)

    def apply_image(self, img, feature):
        from PIL import Image
        h, w = img.shape[:2]
        ratio = self.scale / min(h, w)
        if round(ratio * max(h, w)) > self.max_size:
            ratio = self.max_size / max(h, w)
        nh, nw = int(round(h * ratio)), int(round(w * ratio))
        pil = Image.fromarray(img.astype(np.uint8))
        return np.asarray(pil.resize((nw, nh), Image.BILINEAR), img.dtype)


class ImageRandomAspectScale(ImagePreprocessing):
    """Pick a random short-side scale (reference
    `ImageRandomAspectScale`)."""

    def __init__(self, scales: Sequence[int], max_size: int = 1000,
                 seed: Optional[int] = None):
        self.scales = list(scales)
        self.max_size = max_size
        self.rng = np.random.RandomState(seed)

    def apply_image(self, img, feature):
        scale = self.scales[self.rng.randint(len(self.scales))]
        return ImageAspectScale(scale, self.max_size) \
            .apply_image(img, feature)


class ImageCenterCrop(ImagePreprocessing):
    """(reference `ImageCenterCrop.scala`)"""

    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = int(crop_h), int(crop_w)

    def apply_image(self, img, feature):
        h, w = img.shape[:2]
        top = max((h - self.h) // 2, 0)
        left = max((w - self.w) // 2, 0)
        return img[top:top + self.h, left:left + self.w]


class ImageRandomCrop(ImagePreprocessing):
    """(reference `ImageRandomCrop.scala`)"""

    def __init__(self, crop_h: int, crop_w: int,
                 seed: Optional[int] = None):
        self.h, self.w = int(crop_h), int(crop_w)
        self.rng = np.random.RandomState(seed)

    def apply_image(self, img, feature):
        h, w = img.shape[:2]
        top = self.rng.randint(max(h - self.h, 0) + 1)
        left = self.rng.randint(max(w - self.w, 0) + 1)
        return img[top:top + self.h, left:left + self.w]


class ImageHFlip(ImagePreprocessing):
    """Horizontal flip with probability p (reference `ImageHFlip`)."""

    def __init__(self, p: float = 0.5, seed: Optional[int] = None):
        self.p = float(p)
        self.rng = np.random.RandomState(seed)

    def apply_image(self, img, feature):
        if self.rng.rand() < self.p:
            return img[:, ::-1]
        return img


class ImageBrightness(ImagePreprocessing):
    """Additive brightness jitter in [delta_low, delta_high] (reference
    `ImageBrightness`)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self.rng = np.random.RandomState(seed)

    def apply_image(self, img, feature):
        delta = self.rng.uniform(self.lo, self.hi)
        return np.clip(img.astype(np.float32) + delta, 0, 255) \
            .astype(img.dtype)


class ImageContrast(ImagePreprocessing):
    """Multiplicative contrast jitter (reference `ImageContrast`)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self.rng = np.random.RandomState(seed)

    def apply_image(self, img, feature):
        scale = self.rng.uniform(self.lo, self.hi)
        return np.clip(img.astype(np.float32) * scale, 0, 255) \
            .astype(img.dtype)


def _rgb_to_hsv(img: np.ndarray) -> np.ndarray:
    import colorsys
    del colorsys  # vectorized below
    arr = img.astype(np.float32) / 255.0
    mx = arr.max(-1)
    mn = arr.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2,
                          (r - g) / diff + 4)) * 60.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    return np.stack([h, s, mx], axis=-1)


def _hsv_to_rgb(hsv: np.ndarray) -> np.ndarray:
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    c = v * s
    hp = (h / 60.0) % 6
    x = c * (1 - np.abs(hp % 2 - 1))
    z = np.zeros_like(c)
    conds = [
        (hp < 1, np.stack([c, x, z], -1)),
        ((hp >= 1) & (hp < 2), np.stack([x, c, z], -1)),
        ((hp >= 2) & (hp < 3), np.stack([z, c, x], -1)),
        ((hp >= 3) & (hp < 4), np.stack([z, x, c], -1)),
        ((hp >= 4) & (hp < 5), np.stack([x, z, c], -1)),
        (hp >= 5, np.stack([c, z, x], -1)),
    ]
    rgb = np.zeros(hsv.shape, np.float32)
    for cond, val in conds:
        rgb = np.where(cond[..., None], val, rgb)
    m = (v - c)[..., None]
    return np.clip((rgb + m) * 255.0, 0, 255)


class ImageSaturation(ImagePreprocessing):
    """Saturation jitter via HSV (reference `ImageSaturation`)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self.rng = np.random.RandomState(seed)

    def apply_image(self, img, feature):
        hsv = _rgb_to_hsv(img)
        hsv[..., 1] = np.clip(
            hsv[..., 1] * self.rng.uniform(self.lo, self.hi), 0, 1)
        return _hsv_to_rgb(hsv).astype(img.dtype)


class ImageHue(ImagePreprocessing):
    """Hue rotation in degrees (reference `ImageHue`)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 seed: Optional[int] = None):
        self.lo, self.hi = float(delta_low), float(delta_high)
        self.rng = np.random.RandomState(seed)

    def apply_image(self, img, feature):
        hsv = _rgb_to_hsv(img)
        hsv[..., 0] = (hsv[..., 0] +
                       self.rng.uniform(self.lo, self.hi)) % 360.0
        return _hsv_to_rgb(hsv).astype(img.dtype)


class ImageColorJitter(ImagePreprocessing):
    """Random brightness+contrast+saturation+hue (reference
    `ImageColorJitter`)."""

    def __init__(self, seed: Optional[int] = None):
        self.stages = [ImageBrightness(seed=seed),
                       ImageContrast(seed=seed),
                       ImageSaturation(seed=seed),
                       ImageHue(seed=seed)]

    def apply_image(self, img, feature):
        for s in self.stages:
            img = s.apply_image(img, feature)
        return img


class ImageExpand(ImagePreprocessing):
    """Place the image on a larger mean-filled canvas (reference
    `ImageExpand` — SSD augmentation)."""

    def __init__(self, means: Sequence[float] = (123.0, 117.0, 104.0),
                 max_expand_ratio: float = 4.0,
                 seed: Optional[int] = None):
        self.means = np.asarray(means, np.float32)
        self.max_ratio = float(max_expand_ratio)
        self.rng = np.random.RandomState(seed)

    def apply_image(self, img, feature):
        ratio = self.rng.uniform(1.0, self.max_ratio)
        h, w = img.shape[:2]
        nh, nw = int(h * ratio), int(w * ratio)
        canvas = np.broadcast_to(
            self.means, (nh, nw, img.shape[2])).astype(img.dtype).copy()
        top = self.rng.randint(nh - h + 1)
        left = self.rng.randint(nw - w + 1)
        canvas[top:top + h, left:left + w] = img
        feature["expand_offset"] = (top, left, ratio)
        return canvas


class ImageFiller(ImagePreprocessing):
    """Fill a sub-rectangle with a value (reference `ImageFiller`)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: int = 255):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def apply_image(self, img, feature):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        img = img.copy()
        img[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        return img


class ImageChannelNormalize(ImagePreprocessing):
    """(x - mean) / std per channel (reference
    `ImageChannelNormalize.scala`)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0,
                 std_b: float = 1.0):
        self.mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self.std = np.asarray([std_r, std_g, std_b], np.float32)

    def apply_image(self, img, feature):
        return (img.astype(np.float32) - self.mean) / self.std


class ImageChannelScaledNormalizer(ImagePreprocessing):
    """(x - mean) * scale (reference `ImageChannelScaledNormalizer`)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 scale: float):
        self.mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self.scale = float(scale)

    def apply_image(self, img, feature):
        return (img.astype(np.float32) - self.mean) * self.scale


class ImagePixelNormalizer(ImagePreprocessing):
    """Subtract a per-pixel mean image (reference
    `ImagePixelNormalizer`)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply_image(self, img, feature):
        return img.astype(np.float32) - self.means


class ImageMatToTensor(ImagePreprocessing):
    """uint8 HWC → float32 tensor (reference `ImageMatToTensor`; stays
    HWC — NHWC is the TPU layout; pass `to_chw=True` for parity needs)."""

    def __init__(self, to_chw: bool = False):
        self.to_chw = to_chw

    def apply_image(self, img, feature):
        out = np.asarray(img, np.float32)
        if self.to_chw:
            out = out.transpose(2, 0, 1)
        return out


class ImageSetToSample(ImagePreprocessing):
    """Wrap image (+label) into a Sample (reference
    `ImageSetToSample.scala`)."""

    def __init__(self, input_keys=(ImageFeature.IMAGE,),
                 target_keys=(ImageFeature.LABEL,)):
        self.input_keys = list(input_keys)
        self.target_keys = list(target_keys)

    def apply(self, feature: ImageFeature) -> ImageFeature:
        inputs = [np.asarray(feature[k], np.float32)
                  for k in self.input_keys]
        label = None
        if self.target_keys and self.target_keys[0] in feature:
            label = np.asarray(feature[self.target_keys[0]])
        feature[ImageFeature.SAMPLE] = Sample(
            feature=inputs if len(inputs) > 1 else inputs[0], label=label)
        return feature


class ImageRandomPreprocessing(ImagePreprocessing):
    """Apply an inner transform with probability p (reference
    `ImageRandomPreprocessing`)."""

    def __init__(self, preprocessing: ImagePreprocessing, prob: float,
                 seed: Optional[int] = None):
        self.inner = preprocessing
        self.prob = float(prob)
        self.rng = np.random.RandomState(seed)

    def apply(self, feature):
        if self.rng.rand() < self.prob:
            return self.inner.apply(feature)
        return feature


class ImageBytesToMat(ImagePreprocessing):
    """Decode encoded image bytes (JPEG/PNG) into an HWC uint8 array
    (reference `ImageBytesToMat.scala` — there OpenCV imdecode; here
    PIL). Reads the feature's `bytes` field when the image slot holds
    raw bytes."""

    def __init__(self, channel_order: str = "RGB"):
        if channel_order not in ("RGB", "BGR"):
            raise ValueError("channel_order must be RGB|BGR")
        self.channel_order = channel_order

    def apply(self, feature: ImageFeature) -> ImageFeature:
        import io

        from PIL import Image
        raw = feature[ImageFeature.IMAGE]
        if isinstance(raw, np.ndarray) and raw.ndim >= 2:
            # already decoded — framework decoders produce RGB, so
            # still honor a BGR request
            if self.channel_order == "BGR":
                feature[ImageFeature.IMAGE] = \
                    ImageChannelOrder().apply_image(raw, feature)
            return feature
        # np.array(PIL) is already a fresh contiguous writable array
        img = np.array(
            Image.open(io.BytesIO(bytes(raw))).convert("RGB"))
        if self.channel_order == "BGR":
            img = np.ascontiguousarray(img[..., ::-1])
        feature[ImageFeature.IMAGE] = img
        return feature


class ImagePixelBytesToMat(ImagePreprocessing):
    """Raw pixel bytes + (h, w, c) shape → ndarray (reference
    `ImagePixelBytesToMat.scala`)."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.shape = (int(height), int(width), int(channels))

    def apply(self, feature: ImageFeature) -> ImageFeature:
        raw = feature[ImageFeature.IMAGE]
        arr = np.frombuffer(bytes(raw), np.uint8).reshape(self.shape)
        # frombuffer views are read-only; own the memory
        feature[ImageFeature.IMAGE] = arr.copy()
        return feature


class ImageChannelOrder(ImagePreprocessing):
    """Swap RGB↔BGR (reference `ImageChannelOrder.scala`). No-op for
    grayscale (a channel swap is identity without channels — guarding
    keeps 2-D images from being mirrored along width)."""

    def apply_image(self, img, feature):
        if img.ndim < 3 or img.shape[-1] not in (3, 4):
            return img
        if img.shape[-1] == 4:  # RGBA: swap color planes, keep alpha
            return np.ascontiguousarray(np.concatenate(
                [img[..., 2::-1], img[..., 3:]], axis=-1))
        return np.ascontiguousarray(img[..., ::-1])


class ImageFixedCrop(ImagePreprocessing):
    """Crop a fixed region (reference `ImageFixedCrop.scala`):
    (x1, y1, x2, y2), normalized [0, 1] when ``normalized=True`` else
    absolute pixel coordinates."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True):
        self.box = (float(x1), float(y1), float(x2), float(y2))
        self.normalized = normalized

    def apply_image(self, img, feature):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        x1 = int(np.clip(round(x1), 0, w - 1))
        x2 = int(np.clip(round(x2), x1 + 1, w))
        y1 = int(np.clip(round(y1), 0, h - 1))
        y2 = int(np.clip(round(y2), y1 + 1, h))
        return np.ascontiguousarray(img[y1:y2, x1:x2])


class ImageMatToFloats(ImagePreprocessing):
    """Flatten the image into a float32 vector (reference
    `ImageMatToFloats.scala` — the raw-floats handoff used by the
    serving path)."""

    def apply_image(self, img, feature):
        return np.asarray(img, np.float32).reshape(-1)
