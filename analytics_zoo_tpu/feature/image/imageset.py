"""ImageSet / ImageFeature (reference `Z/feature/image/ImageSet.scala:34-
229`: local/distributed collections of `ImageFeature` read from
disk/HDFS, convertible to DataSet[Sample]).

Decoding uses PIL (the OpenCV role); pixel data is numpy HWC uint8 until
`ImageMatToTensor` converts to float HWC — NHWC being the TPU conv
layout (divergence from BigDL's CHW float means no transpose on device).
"""

from __future__ import annotations

import io
import logging
from typing import Optional

import numpy as np

from analytics_zoo_tpu.common import utils as zutils
from analytics_zoo_tpu.feature.common import Preprocessing, Sample
from analytics_zoo_tpu.feature.feature_set import FeatureSet

logger = logging.getLogger(__name__)


class ImageFeature(dict):
    """Mutable record for one image (reference BigDL `ImageFeature` keys:
    bytes/mat/floats/label/uri/...)."""

    IMAGE = "image"       # np.ndarray HWC (uint8 until MatToTensor)
    LABEL = "label"
    URI = "uri"
    SAMPLE = "sample"
    ORIGINAL_SIZE = "original_size"

    def __init__(self, image: Optional[np.ndarray] = None, label=None,
                 uri: Optional[str] = None):
        super().__init__()
        if image is not None:
            self[self.IMAGE] = image
            # encoded bytes (ImageBytesToMat input) have no shape yet
            if isinstance(image, np.ndarray) and image.ndim >= 2:
                self[self.ORIGINAL_SIZE] = image.shape
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.IMAGE]

    @image.setter
    def image(self, v):
        self[self.IMAGE] = v

    @property
    def label(self):
        return self.get(self.LABEL)


def _decode(path: str) -> np.ndarray:
    """Decode one image from a local path or any fsspec scheme
    (``gs://``/``s3://``/``memory://`` — reference `ImageSet.read`
    reads straight off HDFS the same way)."""
    return _decode_bytes(zutils.read_bytes(path))


def _decode_bytes(data: bytes) -> np.ndarray:
    from PIL import Image
    with Image.open(io.BytesIO(data)) as im:
        return np.asarray(im.convert("RGB"), np.uint8)


def _decode_many(blobs, keyed) -> "list":
    """Decode `(key, extra)` pairs via ``blobs[key]``; undecodable
    files are skipped with ONE summary warning (reference: Spark's
    input machinery logs bad records rather than failing the job or
    silently shrinking the dataset).

    Decoding runs on a thread pool (``ZOO_TPU_DECODE_WORKERS``,
    default 8): PIL's decompressors release the GIL, so this plays
    the role of the reference's per-executor parallel OpenCV decode
    for a many-thousand-image read."""
    def dec(pair):
        key, extra = pair
        try:
            return (key, extra, _decode_bytes(blobs[key]))
        except Exception:
            return (key, extra, None)  # None image == undecodable

    out, dropped = [], []
    for key, extra, img in zutils.parallel_map(dec, keyed):
        if img is None:
            dropped.append(key)
        else:
            out.append((key, extra, img))
    if dropped:
        logger.warning(
            "ImageSet.read: skipped %d of %d file(s) that failed to "
            "decode (first: %s)", len(dropped), len(keyed), dropped[0])
    return out


class ImageSet:
    """Collection of ImageFeatures with a lazy transform pipeline.

    `ImageSet.read(dir)` mirrors `ImageSet.read`
    (`ImageSet.scala:196`): reads every image under a path (glob or dir);
    `with_label_from_dirs` reads a `class_name/xxx.jpg` layout.
    """

    def __init__(self, features: "list[ImageFeature]"):
        self.features = features

    # -- readers ------------------------------------------------------------
    @staticmethod
    def read(path: str, with_label_from_dirs: bool = False,
             max_images: Optional[int] = None) -> "ImageSet":
        if zutils.is_dir(path):
            if with_label_from_dirs:
                class_dirs = zutils.list_dirs(path)
                label_map = {d: i for i, d in enumerate(class_dirs)}
                labelled = []          # (path, label) before decode
                for d in class_dirs:
                    for f in zutils.list_files(d):
                        labelled.append((f, label_map[d]))
                        if max_images and len(labelled) >= max_images:
                            break
                    if max_images and len(labelled) >= max_images:
                        break
                blobs = zutils.read_bytes_many([f for f, _ in labelled])
                return ImageSet([
                    ImageFeature(img, label=np.asarray([lbl], np.int32),
                                 uri=f)
                    for f, lbl, img in _decode_many(blobs, labelled)])
        files = zutils.list_files(path)
        if max_images:
            files = files[:max_images]
        blobs = zutils.read_bytes_many(files)
        return ImageSet([
            ImageFeature(img, uri=f)
            for f, _, img in _decode_many(blobs,
                                          [(f, None) for f in files])])

    @staticmethod
    def from_arrays(images: np.ndarray,
                    labels: Optional[np.ndarray] = None) -> "ImageSet":
        feats = []
        for i in range(len(images)):
            feats.append(ImageFeature(
                np.asarray(images[i]),
                label=None if labels is None else labels[i]))
        return ImageSet(feats)

    # -- pipeline -----------------------------------------------------------
    def transform(self, *transformers: Preprocessing) -> "ImageSet":
        feats = self.features
        for t in transformers:
            feats = [t.apply(f) for f in feats]
            feats = [f for f in feats if f is not None]
        return ImageSet(feats)

    def to_feature_set(self, memory_type="dram") -> FeatureSet:
        """→ FeatureSet of Samples (requires ImageSetToSample in the
        pipeline, or images already tensorized)."""
        samples = []
        for f in self.features:
            s = f.get(ImageFeature.SAMPLE)
            if s is None:
                s = Sample(feature=np.asarray(f.image, np.float32),
                           label=f.label)
            samples.append(s)
        return FeatureSet.sample_rdd(samples, memory_type=memory_type)

    def get_image(self) -> "list[np.ndarray]":
        return [f.image for f in self.features]

    def get_label(self) -> "list":
        return [f.label for f in self.features]

    def to_arrays(self) -> "tuple[np.ndarray, Optional[np.ndarray]]":
        """Stacked (images, labels-or-None) — lets an ImageSet be
        passed straight to `fit`/`evaluate`/`predict` like the
        reference's `model.fit(image_set, ...)` (TextSet has the same
        contract)."""
        xs = np.stack([np.asarray(f.image, np.float32)
                       for f in self.features])
        labels = [f.label for f in self.features]
        if any(lb is not None for lb in labels):
            ys = np.asarray([np.asarray(lb) for lb in labels])
            if ys.ndim == 1:
                ys = ys[:, None]
            return xs, ys
        return xs, None

    def __len__(self):
        return len(self.features)


LocalImageSet = ImageSet  # single-process variant name parity
