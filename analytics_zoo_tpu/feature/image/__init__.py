from analytics_zoo_tpu.feature.image.imageset import (
    ImageFeature, ImageSet, LocalImageSet)
from analytics_zoo_tpu.feature.image.transforms import (
    ImageBrightness, ImageBytesToMat, ImageCenterCrop,
    ImageChannelNormalize, ImageChannelOrder, ImageContrast,
    ImageExpand, ImageFiller, ImageFixedCrop, ImageHFlip, ImageHue,
    ImageMatToFloats, ImageMatToTensor, ImagePixelBytesToMat,
    ImagePixelNormalizer, ImageRandomCrop, ImageRandomPreprocessing,
    ImageResize, ImageSaturation, ImageSetToSample, ImageAspectScale,
    ImageChannelScaledNormalizer, ImageRandomAspectScale,
    ImageColorJitter)

__all__ = [
    "ImageFeature", "ImageSet", "LocalImageSet",
    "ImageResize", "ImageCenterCrop", "ImageRandomCrop", "ImageHFlip",
    "ImageBrightness", "ImageContrast", "ImageSaturation", "ImageHue",
    "ImageChannelNormalize", "ImagePixelNormalizer", "ImageMatToTensor",
    "ImageSetToSample", "ImageExpand", "ImageFiller",
    "ImageRandomPreprocessing", "ImageAspectScale",
    "ImageRandomAspectScale", "ImageChannelScaledNormalizer",
    "ImageColorJitter", "ImageBytesToMat", "ImagePixelBytesToMat",
    "ImageChannelOrder", "ImageFixedCrop", "ImageMatToFloats",
]
