"""On-device batched image augmentation (jit/vmap-native).

The reference augments per-record on executor CPUs through OpenCV
(`Z/feature/image/*.scala`, SURVEY.md §2.2); the host-side analog here
is `feature/image/transforms.py`. This module is the TPU-first
alternative: pure-JAX augmentations over an NHWC batch that run
*inside* the jitted train step — per-image randomness from one
`jax.random` key, static output shapes (XLA-friendly `dynamic_slice`
crops), elementwise color math fused by XLA into neighbouring ops.
Augmenting on-device frees host cores for decode/IO and rides the
batch's existing sharding (each data-parallel shard augments its own
images; no host round trip).

Example::

    aug = augment_pipeline(
        random_crop((224, 224)), random_hflip(),
        random_brightness(32.0), random_contrast(0.8, 1.2),
        normalize(mean=(123.68, 116.779, 103.939)))
    ...
    def train_step(params, opt_state, rng, x, y):
        x = aug(rng, x)                      # traced into the step
        ...

Every op is ``fn(rng, images) -> images`` over float NHWC; compose
with :func:`augment_pipeline` (per-op keys are position-`fold_in`
derived: appending ops preserves earlier ops' randomness).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

AugmentOp = Callable[[jax.Array, jax.Array], jax.Array]


def augment_pipeline(*ops: AugmentOp) -> AugmentOp:
    """Compose ops left-to-right under one rng key. Op i's key is
    ``fold_in(rng, i)`` — positional, so APPENDING ops never changes
    the randomness of earlier ones; inserting/reordering does."""
    def run(rng, images):
        for i, op in enumerate(ops):
            images = op(jax.random.fold_in(rng, i), images)
        return images
    return run


def random_crop(size: "Tuple[int, int]") -> AugmentOp:
    """Random spatial crop to ``(h, w)`` — static output shape, one
    `dynamic_slice` per image (reference `ImageRandomCrop`)."""
    ch, cw = int(size[0]), int(size[1])

    def op(rng, images):
        n, h, w, c = images.shape
        if h < ch or w < cw:
            raise ValueError(f"crop {ch}x{cw} larger than input "
                             f"{h}x{w}")
        ky, kx = jax.random.split(rng)
        ys = jax.random.randint(ky, (n,), 0, h - ch + 1)
        xs = jax.random.randint(kx, (n,), 0, w - cw + 1)

        def crop_one(img, y, x):
            return jax.lax.dynamic_slice(img, (y, x, 0), (ch, cw, c))

        return jax.vmap(crop_one)(images, ys, xs)
    return op


def center_crop(size: "Tuple[int, int]") -> AugmentOp:
    """Deterministic center crop (eval-path twin of `random_crop`)."""
    ch, cw = int(size[0]), int(size[1])

    def op(rng, images):
        del rng
        n, h, w, c = images.shape
        y, x = (h - ch) // 2, (w - cw) // 2
        return jax.lax.dynamic_slice(
            images, (0, y, x, 0), (n, ch, cw, c))
    return op


def random_hflip(p: float = 0.5) -> AugmentOp:
    """Horizontal flip with probability ``p`` per image (reference
    `ImageHFlip`)."""
    def op(rng, images):
        n = images.shape[0]
        flip = jax.random.bernoulli(rng, p, (n,))
        flipped = images[:, :, ::-1, :]
        return jnp.where(flip[:, None, None, None], flipped, images)
    return op


def random_brightness(delta_low: float,
                      delta_high: Optional[float] = None) -> AugmentOp:
    """Additive brightness jitter: per-image delta in pixel units,
    uniform in ``[delta_low, delta_high]`` (``(d)`` means ``(-d, d)``),
    clipped to [0, 255] — the host `ImageBrightness` semantics
    (`transforms.py`)."""
    lo, hi = ((-abs(delta_low), abs(delta_low))
              if delta_high is None else (delta_low, delta_high))

    def op(rng, images):
        n = images.shape[0]
        delta = jax.random.uniform(rng, (n, 1, 1, 1),
                                   minval=lo, maxval=hi)
        return jnp.clip(images + delta, 0.0, 255.0)
    return op


def _factor_range(delta_low, delta_high, default=(0.5, 1.5)):
    """Uniform-factor bounds around the identity 1.0: no args →
    ``default`` (the host transformers' default); ONE arg d →
    symmetric ``[1-d, 1+d]`` (mirrors `random_brightness(d)`); two
    args → ``[delta_low, delta_high]`` verbatim."""
    if delta_low is None:
        return default
    if delta_high is None:
        # symmetric around 1, floored at 0 (negative factors would
        # invert images)
        return (max(0.0, 1.0 - delta_low), 1.0 + delta_low)
    if delta_high < delta_low:
        raise ValueError(f"empty factor range [{delta_low}, "
                         f"{delta_high}]")
    return (float(delta_low), float(delta_high))


def random_contrast(delta_low: Optional[float] = None,
                    delta_high: Optional[float] = None) -> AugmentOp:
    """Multiplicative contrast jitter: per-image ``x * f``, clipped to
    [0, 255] — the host `ImageContrast` semantics. ``f`` is uniform in
    the :func:`_factor_range` bounds (default [0.5, 1.5]; one arg d
    means [1-d, 1+d])."""
    lo, hi = _factor_range(delta_low, delta_high)

    def op(rng, images):
        n = images.shape[0]
        f = jax.random.uniform(rng, (n, 1, 1, 1), minval=lo, maxval=hi)
        return jnp.clip(images * f, 0.0, 255.0)
    return op


def random_saturation(delta_low: Optional[float] = None,
                      delta_high: Optional[float] = None) -> AugmentOp:
    """Saturation jitter by blending with the ITU-R 601 luma gray
    image, factor uniform in the :func:`_factor_range` bounds (default
    [0.5, 1.5]; one arg d means [1-d, 1+d]), clipped to [0, 255].
    Close to (but cheaper than) the host `ImageSaturation`'s HSV round
    trip: XLA fuses the blend; an HSV conversion would not fuse."""
    lo, hi = _factor_range(delta_low, delta_high)

    def op(rng, images):
        n = images.shape[0]
        f = jax.random.uniform(rng, (n, 1, 1, 1), minval=lo, maxval=hi)
        gray = (0.299 * images[..., 0] + 0.587 * images[..., 1]
                + 0.114 * images[..., 2])[..., None]
        return jnp.clip((images - gray) * f + gray, 0.0, 255.0)
    return op


def random_hue(delta_low: Optional[float] = None,
               delta_high: Optional[float] = None) -> AugmentOp:
    """Hue shift by a per-image angle in degrees — no args →
    ``[-18, 18]`` (the host `ImageHue` default); ONE arg d →
    symmetric ``[-|d|, |d|]`` (the module's one-arg convention); two
    args verbatim. Implemented as a chroma rotation in YIQ space —
    the fuseable APPROXIMATION of the host `ImageHue`'s HSV round
    trip. Positive degrees shift in the HSV-positive direction
    (red → green); angles in the I-Q chroma plane track HSV hue only
    approximately (tens of degrees of warp across the wheel), so
    match ranges by eye, not digit-for-digit."""
    if delta_low is None:
        delta_low, delta_high = -18.0, 18.0
    elif delta_high is None:
        delta_low, delta_high = -abs(delta_low), abs(delta_low)
    elif delta_high < delta_low:
        raise ValueError(f"empty degree range [{delta_low}, "
                         f"{delta_high}]")

    def op(rng, images):
        n = images.shape[0]
        theta = jax.random.uniform(
            rng, (n, 1, 1), minval=delta_low, maxval=delta_high) \
            * (jnp.pi / 180.0)
        r, g, b = (images[..., 0], images[..., 1], images[..., 2])
        # RGB -> YIQ
        yy = 0.299 * r + 0.587 * g + 0.114 * b
        ii = 0.596 * r - 0.274 * g - 0.322 * b
        qq = 0.211 * r - 0.523 * g + 0.312 * b
        # rotate chroma by -theta: HSV hue + YIQ chroma angle run in
        # opposite directions, so this makes +degrees = red -> green,
        # matching ImageHue's positive direction
        c, s = jnp.cos(theta), jnp.sin(theta)
        i2 = c * ii + s * qq
        q2 = -s * ii + c * qq
        # YIQ -> RGB
        r2 = yy + 0.956 * i2 + 0.621 * q2
        g2 = yy - 0.272 * i2 - 0.647 * q2
        b2 = yy - 1.106 * i2 + 1.703 * q2
        return jnp.clip(jnp.stack([r2, g2, b2], axis=-1), 0.0, 255.0)
    return op


def random_resized_crop(size: "Tuple[int, int]",
                        scale: "Tuple[float, float]" = (0.08, 1.0),
                        ratio: "Tuple[float, float]" = (0.75, 4 / 3)
                        ) -> AugmentOp:
    """Inception-style crop: sample an area fraction in ``scale`` and
    an aspect ratio in ``ratio``, then bilinearly resample that window
    to ``size`` — the standard ImageNet training crop. Variable window
    sizes stay XLA-static by expressing the crop as a per-image
    `jax.image.scale_and_translate` (affine bilinear sampling), not a
    dynamic-shape slice."""
    th, tw = int(size[0]), int(size[1])

    def op(rng, images):
        n, h, w, c = images.shape
        k_area, k_ratio, k_y, k_x = jax.random.split(rng, 4)
        area = jax.random.uniform(k_area, (n,), minval=scale[0],
                                  maxval=scale[1]) * (h * w)
        log_r = jax.random.uniform(
            k_ratio, (n,), minval=jnp.log(ratio[0]),
            maxval=jnp.log(ratio[1]))
        r = jnp.exp(log_r)
        # window (wh, ww), clamped inside the image (>=1px: the
        # caller's scale range is otherwise honored verbatim)
        ww = jnp.clip(jnp.sqrt(area * r), 1.0, float(w))
        wh = jnp.clip(jnp.sqrt(area / r), 1.0, float(h))
        y0 = jax.random.uniform(k_y, (n,)) * (h - wh)
        x0 = jax.random.uniform(k_x, (n,)) * (w - ww)
        # output pixel (i, j) samples input at (y0 + i*wh/th, ...):
        # scale_and_translate maps in->out as out = in*scale + trans,
        # so scale = th/wh and trans = -y0*scale
        sy, sx = th / wh, tw / ww

        def one(img, sy_, sx_, ty, tx):
            return jax.image.scale_and_translate(
                img, (th, tw, c), (0, 1),
                jnp.array([sy_, sx_]), jnp.array([ty, tx]),
                method="bilinear")

        out = jax.vmap(one)(images, sy, sx, -y0 * sy, -x0 * sx)
        return out
    return op


def normalize(mean: Sequence[float],
              std: Sequence[float] = (1.0, 1.0, 1.0)) -> AugmentOp:
    """Per-channel ``(x - mean) / std`` (reference
    `ImageChannelNormalize`)."""
    mean_a = jnp.asarray(mean, jnp.float32)
    std_a = jnp.asarray(std, jnp.float32)

    def op(rng, images):
        del rng
        return (images - mean_a) / std_a
    return op


def cutout(size: int, fill: float = 0.0) -> AugmentOp:
    """Zero a random ``size``×``size`` square per image (regularizer;
    no reference analog — TPU-era extra)."""
    s = int(size)

    def op(rng, images):
        n, h, w, _ = images.shape
        ky, kx = jax.random.split(rng)
        # random top-left corner of an exactly s x s window
        y0 = jax.random.randint(ky, (n, 1, 1), 0, max(h - s, 0) + 1)
        x0 = jax.random.randint(kx, (n, 1, 1), 0, max(w - s, 0) + 1)
        yy = jnp.arange(h)[None, :, None]
        xx = jnp.arange(w)[None, None, :]
        inside = ((yy >= y0) & (yy < y0 + s)
                  & (xx >= x0) & (xx < x0 + s))
        return jnp.where(inside[..., None], fill, images)
    return op
