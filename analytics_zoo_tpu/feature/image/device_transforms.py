"""On-device batched image augmentation (jit/vmap-native).

The reference augments per-record on executor CPUs through OpenCV
(`Z/feature/image/*.scala`, SURVEY.md §2.2); the host-side analog here
is `feature/image/transforms.py`. This module is the TPU-first
alternative: pure-JAX augmentations over an NHWC batch that run
*inside* the jitted train step — per-image randomness from one
`jax.random` key, static output shapes (XLA-friendly `dynamic_slice`
crops), elementwise color math fused by XLA into neighbouring ops.
Augmenting on-device frees host cores for decode/IO and rides the
batch's existing sharding (each data-parallel shard augments its own
images; no host round trip).

Example::

    aug = augment_pipeline(
        random_crop((224, 224)), random_hflip(),
        random_brightness(32.0), random_contrast(0.8, 1.2),
        normalize(mean=(123.68, 116.779, 103.939)))
    ...
    def train_step(params, opt_state, rng, x, y):
        x = aug(rng, x)                      # traced into the step
        ...

Every op is ``fn(rng, images) -> images`` over float NHWC; compose
with :func:`augment_pipeline` (per-op keys are position-`fold_in`
derived: appending ops preserves earlier ops' randomness).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

AugmentOp = Callable[[jax.Array, jax.Array], jax.Array]


def augment_pipeline(*ops: AugmentOp) -> AugmentOp:
    """Compose ops left-to-right under one rng key. Op i's key is
    ``fold_in(rng, i)`` — positional, so APPENDING ops never changes
    the randomness of earlier ones; inserting/reordering does."""
    def run(rng, images):
        for i, op in enumerate(ops):
            images = op(jax.random.fold_in(rng, i), images)
        return images
    return run


def random_crop(size: "Tuple[int, int]") -> AugmentOp:
    """Random spatial crop to ``(h, w)`` — static output shape, one
    `dynamic_slice` per image (reference `ImageRandomCrop`)."""
    ch, cw = int(size[0]), int(size[1])

    def op(rng, images):
        n, h, w, c = images.shape
        if h < ch or w < cw:
            raise ValueError(f"crop {ch}x{cw} larger than input "
                             f"{h}x{w}")
        ky, kx = jax.random.split(rng)
        ys = jax.random.randint(ky, (n,), 0, h - ch + 1)
        xs = jax.random.randint(kx, (n,), 0, w - cw + 1)

        def crop_one(img, y, x):
            return jax.lax.dynamic_slice(img, (y, x, 0), (ch, cw, c))

        return jax.vmap(crop_one)(images, ys, xs)
    return op


def center_crop(size: "Tuple[int, int]") -> AugmentOp:
    """Deterministic center crop (eval-path twin of `random_crop`)."""
    ch, cw = int(size[0]), int(size[1])

    def op(rng, images):
        del rng
        n, h, w, c = images.shape
        y, x = (h - ch) // 2, (w - cw) // 2
        return jax.lax.dynamic_slice(
            images, (0, y, x, 0), (n, ch, cw, c))
    return op


def random_hflip(p: float = 0.5) -> AugmentOp:
    """Horizontal flip with probability ``p`` per image (reference
    `ImageHFlip`)."""
    def op(rng, images):
        n = images.shape[0]
        flip = jax.random.bernoulli(rng, p, (n,))
        flipped = images[:, :, ::-1, :]
        return jnp.where(flip[:, None, None, None], flipped, images)
    return op


def random_brightness(delta_low: float,
                      delta_high: Optional[float] = None) -> AugmentOp:
    """Additive brightness jitter: per-image delta in pixel units,
    uniform in ``[delta_low, delta_high]`` (``(d)`` means ``(-d, d)``),
    clipped to [0, 255] — the host `ImageBrightness` semantics
    (`transforms.py`)."""
    lo, hi = ((-abs(delta_low), abs(delta_low))
              if delta_high is None else (delta_low, delta_high))

    def op(rng, images):
        n = images.shape[0]
        delta = jax.random.uniform(rng, (n, 1, 1, 1),
                                   minval=lo, maxval=hi)
        return jnp.clip(images + delta, 0.0, 255.0)
    return op


def random_contrast(delta_low: float = 0.5,
                    delta_high: float = 1.5) -> AugmentOp:
    """Multiplicative contrast jitter: per-image ``x * f`` with ``f``
    uniform in ``[delta_low, delta_high]``, clipped to [0, 255] — the
    host `ImageContrast` semantics."""
    def op(rng, images):
        n = images.shape[0]
        f = jax.random.uniform(rng, (n, 1, 1, 1),
                               minval=delta_low, maxval=delta_high)
        return jnp.clip(images * f, 0.0, 255.0)
    return op


def random_saturation(delta_low: float = 0.5,
                      delta_high: float = 1.5) -> AugmentOp:
    """Saturation jitter by blending with the ITU-R 601 luma gray
    image, factor uniform in ``[delta_low, delta_high]``, clipped to
    [0, 255]. Close to (but cheaper than) the host `ImageSaturation`'s
    HSV round trip: XLA fuses the blend; an HSV conversion would not
    fuse."""
    def op(rng, images):
        n = images.shape[0]
        f = jax.random.uniform(rng, (n, 1, 1, 1),
                               minval=delta_low, maxval=delta_high)
        gray = (0.299 * images[..., 0] + 0.587 * images[..., 1]
                + 0.114 * images[..., 2])[..., None]
        return jnp.clip((images - gray) * f + gray, 0.0, 255.0)
    return op


def normalize(mean: Sequence[float],
              std: Sequence[float] = (1.0, 1.0, 1.0)) -> AugmentOp:
    """Per-channel ``(x - mean) / std`` (reference
    `ImageChannelNormalize`)."""
    mean_a = jnp.asarray(mean, jnp.float32)
    std_a = jnp.asarray(std, jnp.float32)

    def op(rng, images):
        del rng
        return (images - mean_a) / std_a
    return op


def cutout(size: int, fill: float = 0.0) -> AugmentOp:
    """Zero a random ``size``×``size`` square per image (regularizer;
    no reference analog — TPU-era extra)."""
    s = int(size)

    def op(rng, images):
        n, h, w, _ = images.shape
        ky, kx = jax.random.split(rng)
        # random top-left corner of an exactly s x s window
        y0 = jax.random.randint(ky, (n, 1, 1), 0, max(h - s, 0) + 1)
        x0 = jax.random.randint(kx, (n, 1, 1), 0, max(w - s, 0) + 1)
        yy = jnp.arange(h)[None, :, None]
        xx = jnp.arange(w)[None, None, :]
        inside = ((yy >= y0) & (yy < y0 + s)
                  & (xx >= x0) & (xx < x0 + s))
        return jnp.where(inside[..., None], fill, images)
    return op
