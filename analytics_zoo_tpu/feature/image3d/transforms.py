"""3D transforms (reference `Z/feature/image3d/`).

- `AffineTransform3D` — trilinear resampling under an affine map about
  the volume center (reference `Affine.scala`).
- `Crop3D` / `RandomCrop3D` / `CenterCrop3D` — sub-volume extraction
  (reference `Cropper.scala`: `Crop3D.apply(start, patchSize)`).
- `Rotation3D` — Euler-angle rotation, an affine special case
  (reference `Rotation.scala`).
- `WarpTransformer` — dense displacement-field warping (reference
  `Warp.scala`).

Volumes are numpy (D, H, W) or (D, H, W, C); channels transform
independently. Host-side preprocessing, mirroring the 2D pipeline's
CPU decode/augment stage (the reference computes these on Spark
executors' CPUs too; TPU time is reserved for the model).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing


class ImageFeature3D(dict):
    """Record for one volume (reference `ImageFeature3D.scala`)."""

    IMAGE = "image"
    LABEL = "label"
    URI = "uri"
    ORIGINAL_SIZE = "original_size"

    def __init__(self, image: Optional[np.ndarray] = None, label=None,
                 uri: Optional[str] = None):
        super().__init__()
        if image is not None:
            image = np.asarray(image)
            if image.ndim not in (3, 4):
                raise ValueError(
                    f"expected (D,H,W[,C]) volume, got {image.shape}")
            self[self.IMAGE] = image
            self[self.ORIGINAL_SIZE] = image.shape
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.IMAGE]

    @image.setter
    def image(self, v):
        self[self.IMAGE] = v


class ImagePreprocessing3D(Preprocessing):
    """Base: transforms the `image` volume of an ImageFeature3D (raw
    ndarrays are wrapped on the fly)."""

    def apply_volume(self, vol: np.ndarray,
                     feature: ImageFeature3D) -> np.ndarray:
        raise NotImplementedError

    def apply(self, feature):
        if not isinstance(feature, ImageFeature3D):
            feature = ImageFeature3D(np.asarray(feature))
        feature[ImageFeature3D.IMAGE] = self.apply_volume(
            feature[ImageFeature3D.IMAGE], feature)
        return feature


def _split_channels(vol: np.ndarray):
    """(D,H,W) → [(D,H,W)]; (D,H,W,C) → per-channel list."""
    if vol.ndim == 3:
        return [vol], False
    return [vol[..., c] for c in range(vol.shape[-1])], True


def _merge_channels(chans, had_channels: bool):
    return np.stack(chans, axis=-1) if had_channels else chans[0]


def trilinear_sample(vol: np.ndarray, coords: np.ndarray,
                     pad_mode: str = "clamp",
                     pad_value: float = 0.0) -> np.ndarray:
    """Sample `vol` (D,H,W) at float `coords` (3, N) trilinearly.

    pad_mode "clamp": out-of-bounds coordinates clamp to the border
    (reference Affine's default); "constant": fill `pad_value`.
    """
    d, h, w = vol.shape
    z, y, x = coords
    if pad_mode == "constant":
        oob = ((z < 0) | (z > d - 1) | (y < 0) | (y > h - 1) |
               (x < 0) | (x > w - 1))
    z = np.clip(z, 0.0, d - 1)
    y = np.clip(y, 0.0, h - 1)
    x = np.clip(x, 0.0, w - 1)
    z0 = np.floor(z).astype(np.int64)
    y0 = np.floor(y).astype(np.int64)
    x0 = np.floor(x).astype(np.int64)
    z1 = np.minimum(z0 + 1, d - 1)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    fz, fy, fx = z - z0, y - y0, x - x0
    out = np.zeros(z.shape, np.float64)
    for zz, wz in ((z0, 1 - fz), (z1, fz)):
        for yy, wy in ((y0, 1 - fy), (y1, fy)):
            for xx, wx in ((x0, 1 - fx), (x1, fx)):
                out += vol[zz, yy, xx].astype(np.float64) * \
                    (wz * wy * wx)
    if pad_mode == "constant":
        out = np.where(oob, pad_value, out)
    return out.astype(vol.dtype if np.issubdtype(
        vol.dtype, np.floating) else np.float32)


class AffineTransform3D(ImagePreprocessing3D):
    """Affine resample about the volume center (reference
    `Affine.scala`): for each output voxel o, samples input at
    ``mat^-1 @ (o - center - translation) + center``.

    `mat` is the 3x3 forward transform; `translation` a 3-vector.
    """

    def __init__(self, mat: np.ndarray,
                 translation: Sequence[float] = (0.0, 0.0, 0.0),
                 clamp_mode: str = "clamp", pad_value: float = 0.0):
        self.mat = np.asarray(mat, np.float64).reshape(3, 3)
        self.translation = np.asarray(translation, np.float64)
        if clamp_mode not in ("clamp", "padding"):
            raise ValueError("clamp_mode must be 'clamp' or 'padding'")
        self.clamp_mode = clamp_mode
        self.pad_value = float(pad_value)

    def apply_volume(self, vol, feature):
        chans, had_c = _split_channels(np.asarray(vol))
        shape = chans[0].shape
        center = (np.asarray(shape, np.float64) - 1.0) / 2.0
        inv = np.linalg.inv(self.mat)
        grid = np.stack(np.meshgrid(*[np.arange(s) for s in shape],
                                    indexing="ij"), axis=0
                        ).reshape(3, -1).astype(np.float64)
        src = inv @ (grid - center[:, None] -
                     self.translation[:, None]) + center[:, None]
        mode = "clamp" if self.clamp_mode == "clamp" else "constant"
        out = [trilinear_sample(c, src, pad_mode=mode,
                                pad_value=self.pad_value
                                ).reshape(shape) for c in chans]
        return _merge_channels(out, had_c)


class Rotation3D(AffineTransform3D):
    """Euler rotation (reference `Rotation.scala`): `rotation_angles`
    are radians about the (z, y, x) axes, composed Rz @ Ry @ Rx."""

    def __init__(self, rotation_angles: Sequence[float],
                 clamp_mode: str = "clamp", pad_value: float = 0.0):
        az, ay, ax = (float(a) for a in rotation_angles)
        cz, sz = math.cos(az), math.sin(az)
        cy, sy = math.cos(ay), math.sin(ay)
        cx, sx = math.cos(ax), math.sin(ax)
        rz = np.array([[1, 0, 0], [0, cz, -sz], [0, sz, cz]])
        ry = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]])
        rx = np.array([[cx, -sx, 0], [sx, cx, 0], [0, 0, 1]])
        super().__init__(rz @ ry @ rx, clamp_mode=clamp_mode,
                         pad_value=pad_value)
        self.rotation_angles = (az, ay, ax)


class Crop3D(ImagePreprocessing3D):
    """Fixed sub-volume (reference `Cropper.scala` `Crop3D`): `start`
    (z, y, x) corner + `patch_size` (d, h, w)."""

    def __init__(self, start: Sequence[int], patch_size: Sequence[int]):
        self.start = tuple(int(v) for v in start)
        self.patch = tuple(int(v) for v in patch_size)
        if len(self.start) != 3 or len(self.patch) != 3:
            raise ValueError("start and patch_size must be length 3")

    def apply_volume(self, vol, feature):
        for dim, (s, p) in enumerate(zip(self.start, self.patch)):
            if s < 0 or s + p > vol.shape[dim]:
                raise ValueError(
                    f"crop [{s}:{s + p}] exceeds dim {dim} of size "
                    f"{vol.shape[dim]}")
        z, y, x = self.start
        d, h, w = self.patch
        return vol[z:z + d, y:y + h, x:x + w]


class RandomCrop3D(ImagePreprocessing3D):
    """(reference `RandomCrop3D`)"""

    def __init__(self, crop_depth: int, crop_height: int,
                 crop_width: int, seed: Optional[int] = None):
        self.patch = (int(crop_depth), int(crop_height),
                      int(crop_width))
        self._rng = np.random.RandomState(seed)

    def apply_volume(self, vol, feature):
        starts = []
        for dim, p in enumerate(self.patch):
            if p > vol.shape[dim]:
                raise ValueError(
                    f"crop size {p} exceeds dim {dim} of "
                    f"size {vol.shape[dim]}")
            starts.append(self._rng.randint(0, vol.shape[dim] - p + 1))
        z, y, x = starts
        d, h, w = self.patch
        return vol[z:z + d, y:y + h, x:x + w]


class CenterCrop3D(ImagePreprocessing3D):
    """(reference `CenterCrop3D`)"""

    def __init__(self, crop_depth: int, crop_height: int,
                 crop_width: int):
        self.patch = (int(crop_depth), int(crop_height),
                      int(crop_width))

    def apply_volume(self, vol, feature):
        starts = []
        for dim, p in enumerate(self.patch):
            if p > vol.shape[dim]:
                raise ValueError(
                    f"crop size {p} exceeds dim {dim} of "
                    f"size {vol.shape[dim]}")
            starts.append((vol.shape[dim] - p) // 2)
        z, y, x = starts
        d, h, w = self.patch
        return vol[z:z + d, y:y + h, x:x + w]


class WarpTransformer(ImagePreprocessing3D):
    """Dense displacement warp (reference `Warp.scala`): samples input
    at ``grid + offset`` where `offset` is a (D, H, W, 3) field of
    (dz, dy, dx) displacements."""

    def __init__(self, offset: np.ndarray, clamp_mode: str = "clamp",
                 pad_value: float = 0.0):
        self.offset = np.asarray(offset, np.float64)
        if self.offset.ndim != 4 or self.offset.shape[-1] != 3:
            raise ValueError("offset must be (D, H, W, 3)")
        if clamp_mode not in ("clamp", "padding"):
            raise ValueError("clamp_mode must be 'clamp' or 'padding'")
        self.clamp_mode = clamp_mode
        self.pad_value = float(pad_value)

    def apply_volume(self, vol, feature):
        chans, had_c = _split_channels(np.asarray(vol))
        shape = chans[0].shape
        if self.offset.shape[:3] != shape:
            raise ValueError(
                f"offset field {self.offset.shape[:3]} does not match "
                f"volume {shape}")
        grid = np.stack(np.meshgrid(*[np.arange(s) for s in shape],
                                    indexing="ij"), axis=0
                        ).astype(np.float64)
        src = (grid + np.moveaxis(self.offset, -1, 0)).reshape(3, -1)
        mode = "clamp" if self.clamp_mode == "clamp" else "constant"
        out = [trilinear_sample(c, src, pad_mode=mode,
                                pad_value=self.pad_value
                                ).reshape(shape) for c in chans]
        return _merge_channels(out, had_c)
