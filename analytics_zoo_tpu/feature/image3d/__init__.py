"""3D image (volumetric / medical) transforms.

Reference: `Z/feature/image3d/*.scala` (~640 LoC): `AffineTransform3D`,
`Crop3D` (+ random/center), `Rotation3D`, `WarpTransformer`, on
`ImageFeature3D` records. Host-side numpy/scipy preprocessing like the
2D pipeline; volumes are (D, H, W) or (D, H, W, C) float arrays.
"""

from analytics_zoo_tpu.feature.image3d.transforms import (  # noqa: F401
    AffineTransform3D,
    CenterCrop3D,
    Crop3D,
    ImageFeature3D,
    RandomCrop3D,
    Rotation3D,
    WarpTransformer,
)

__all__ = [
    "ImageFeature3D", "AffineTransform3D", "Crop3D", "RandomCrop3D",
    "CenterCrop3D", "Rotation3D", "WarpTransformer",
]
