"""Preprocessing algebra + Sample container.

Reference: `Z/feature/common/Preprocessing.scala` — composable
`Preprocessing[A, B]` with `->` chaining, and the adapters
(`ArrayToTensor`, `SeqToTensor`, `ScalarToTensor`, `TensorToSample`,
`FeatureLabelPreprocessing`) that nnframes uses to turn DataFrame rows
into training `Sample`s (SURVEY.md §2.2).

Python uses `>>` for the Scala `->`: ``pre = SeqToTensor((3,)) >>
TensorToSample()``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


@dataclass
class Sample:
    """A (features, label) record — the BigDL `Sample` analog. Features
    may be a single ndarray or a list (multi-input models)."""

    feature: Any
    label: Optional[Any] = None

    def feature_arrays(self) -> "list[np.ndarray]":
        f = self.feature
        return [np.asarray(a) for a in (f if isinstance(f, (list, tuple))
                                        else [f])]


def _count_ingest(stage: str, records: int, nbytes: int = 0):
    """Per-stage ingest telemetry (docs/observability.md). One
    chunked increment per stream, not per record — the counters must
    not put a lock acquisition in the per-record path."""
    from analytics_zoo_tpu.common.observability import counter
    if records:
        counter("zoo_tpu_ingest_records_total",
                help="records emitted per ingest stage",
                labels={"stage": stage}).inc(records)
    if nbytes:
        counter("zoo_tpu_ingest_bytes_total",
                help="bytes ingested per ingest stage",
                labels={"stage": stage}).inc(nbytes)


class Preprocessing:
    """Composable transformer; subclass and implement
    :meth:`apply` (single record) or override :meth:`transform`
    (stream)."""

    def apply(self, record: Any) -> Any:
        raise NotImplementedError

    def transform(self, records: Iterable[Any]) -> Iterator[Any]:
        n = 0
        try:
            for r in records:
                out = self.apply(r)
                if out is not None:
                    n += 1
                    yield out
        finally:
            _count_ingest(type(self).__name__, n)

    def __rshift__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])

    def __call__(self, records: Iterable[Any]) -> Iterator[Any]:
        return self.transform(records)


class ChainedPreprocessing(Preprocessing):
    """(reference `ChainedPreprocessing`)"""

    def __init__(self, stages: Sequence[Preprocessing]):
        self.stages = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                self.stages.extend(s.stages)
            else:
                self.stages.append(s)

    def apply(self, record: Any) -> Any:
        for s in self.stages:
            record = s.apply(record)
            if record is None:
                return None
        return record

    def transform(self, records: Iterable[Any]) -> Iterator[Any]:
        for s in self.stages:
            records = s.transform(records)
        return iter(records)


class FnPreprocessing(Preprocessing):
    """Lift a plain function."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def apply(self, record):
        return self.fn(record)


class ArrayToTensor(Preprocessing):
    """ndarray-like → float32 ndarray with declared shape (reference
    `ArrayToTensor`)."""

    def __init__(self, size: Optional[Sequence[int]] = None):
        self.size = None if size is None else tuple(size)

    def apply(self, record):
        arr = np.asarray(record, np.float32)
        if self.size is not None:
            arr = arr.reshape(self.size)
        return arr


class SeqToTensor(ArrayToTensor):
    """sequence of numbers → tensor (reference `SeqToTensor`)."""


class ScalarToTensor(Preprocessing):
    """scalar → 1-element tensor (reference `ScalarToTensor`)."""

    def apply(self, record):
        return np.asarray([record], np.float32)


class MLlibVectorToTensor(ArrayToTensor):
    """dense-vector-like → tensor (reference `MLlibVectorToTensor`;
    accepts anything with `.toArray()` or array-like)."""

    def apply(self, record):
        if hasattr(record, "toArray"):
            record = record.toArray()
        return super().apply(record)


class TensorToSample(Preprocessing):
    """tensor → Sample(feature) (reference `TensorToSample`)."""

    def apply(self, record):
        return Sample(feature=record)


class FeatureLabelPreprocessing(Preprocessing):
    """(feature, label) tuple → Sample, with per-side preprocessing
    (reference `FeatureLabelPreprocessing`)."""

    def __init__(self, feature_preprocessing: Preprocessing,
                 label_preprocessing: Optional[Preprocessing] = None):
        self.feature_pre = feature_preprocessing
        self.label_pre = label_preprocessing

    def apply(self, record):
        feature, label = record
        f = self.feature_pre.apply(feature)
        l = label
        if label is not None and self.label_pre is not None:
            l = self.label_pre.apply(label)
        return Sample(feature=f, label=l)


class BigDLAdapter(FnPreprocessing):
    """Kept for API parity: lifts any unary callable (the reference lifts
    BigDL `Transformer`s)."""
