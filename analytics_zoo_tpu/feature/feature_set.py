"""FeatureSet (L2): the cached training-set abstraction.

Reference: `Z/feature/FeatureSet.scala` — `CachedDistributedFeatureSet`
caches samples per partition in an `ArrayLike` store with per-epoch
random-offset iteration and index-permutation reshuffle (`:216-296`), with
memory tiers DRAM / PMEM / DIRECT selectable per dataset
(`FeatureSet.scala:310-329`, `feature/pmem/FeatureSet.scala:171`).

TPU-native redesign: the "cluster" is the set of ingest hosts; each host
caches its shard of the dataset and hands fixed-shape batches to the
pjit'd step (the role Spark RDD partitions played). Memory tiers:

- DRAM   — materialized numpy arrays (the default, fastest)
- DIRECT — no cache; records re-read/re-transformed every epoch
- PMEM   — disk-backed `np.memmap` arena: the TPU-VM analog of the
  reference's Optane JNI allocator (persistent-memory tier for datasets
  larger than RAM), see §2.11.3

The native C arena allocator behind the PMEM tier lives in
`native/host_arena` (ctypes-loaded); numpy memmap is the fallback.
"""

from __future__ import annotations

import enum
import os
import tempfile
from typing import Any, Iterable, Iterator, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing, Sample


class MemoryType(enum.Enum):
    DRAM = "dram"
    PMEM = "pmem"
    DIRECT = "direct"

    @staticmethod
    def of(v: "str | MemoryType") -> "MemoryType":
        if isinstance(v, MemoryType):
            return v
        return MemoryType(v.lower())


def _stack_column(column: "list[np.ndarray]") -> np.ndarray:
    return np.stack([np.asarray(a) for a in column], axis=0)


class _MemmapStore:
    """PMEM-tier store: columns spilled to a disk-backed memmap arena."""

    def __init__(self, columns: "list[np.ndarray]", path: Optional[str]):
        self.dir = path or tempfile.mkdtemp(prefix="zoo_pmem_")
        os.makedirs(self.dir, exist_ok=True)
        self.columns = []
        for i, col in enumerate(columns):
            fname = os.path.join(self.dir, f"col{i}.mm")
            mm = np.memmap(fname, dtype=col.dtype, mode="w+",
                           shape=col.shape)
            mm[:] = col
            mm.flush()
            self.columns.append(mm)


def normalize_labels(y):
    """The ONE place deciding how user-supplied labels are read:
    returns ``(y_cols, multi)`` where ``y_cols`` is a list of numpy
    label columns (empty = unlabeled) and ``multi`` says whether they
    are separate output columns.

    Multi-output means a list/tuple of ARRAY-LIKES (objects with
    ``ndim >= 1`` — numpy/jax arrays): ``[ya, yb]`` stays two
    columns. A plain Python list of per-sample scalars or rows
    (``[0, 1, 0, 1]`` or ``[[0], [1]]``) is ONE label array, as it
    always was."""
    if y is None:
        return [], False
    if isinstance(y, (list, tuple)):
        if len(y) == 0:
            raise ValueError(
                "empty label list — pass None for unlabeled data")
        if all(getattr(c, "ndim", 0) >= 1 for c in y):
            return [np.asarray(c) for c in y], True
    return [np.asarray(y)], False


class FeatureSet:
    """Cached, shardable dataset implementing the Estimator data protocol
    (`num_samples`, `iter_batches`).

    Build with :meth:`array`, :meth:`sample_rdd` (any iterable of
    `Sample`s — the RDD role), or :meth:`from_iterable` + a
    `Preprocessing` chain via :meth:`transform`.
    """

    def __init__(self, x_columns: "list[np.ndarray]",
                 y_column=None,
                 memory_type: "str | MemoryType" = MemoryType.DRAM,
                 shard_index: int = 0, num_shards: int = 1,
                 pmem_path: Optional[str] = None):
        self.memory_type = MemoryType.of(memory_type)
        n = x_columns[0].shape[0]
        for c in x_columns:
            if c.shape[0] != n:
                raise ValueError("inconsistent column lengths")
        # ``y_column``: one label array, or a list/tuple of them
        # (multi-output training — the reference's nested TensorMeta
        # label contract); normalize_labels is the single decision
        # point for which is which
        y_cols, self._multi_y = normalize_labels(y_column)
        for c in y_cols:
            if c.ndim == 0 or c.shape[0] != n:
                raise ValueError(
                    f"label column shape {c.shape} does not match "
                    f"{n} samples")
        # multi-host sharding: this host keeps rows [lo, hi)
        if not (0 <= shard_index < num_shards):
            raise ValueError("bad shard spec")
        lo = shard_index * n // num_shards
        hi = (shard_index + 1) * n // num_shards
        x_columns = [c[lo:hi] for c in x_columns]
        y_cols = [c[lo:hi] for c in y_cols]

        if self.memory_type == MemoryType.PMEM:
            store = _MemmapStore(x_columns + y_cols, pmem_path)
            stored = store.columns
            self._x = stored[:len(x_columns)]
            y_cols = stored[len(x_columns):]
            self._store = store
        else:
            self._x = x_columns
        self._y_cols = y_cols
        self._n = self._x[0].shape[0]
        from analytics_zoo_tpu.feature.common import _count_ingest
        _count_ingest("feature_set", self._n,
                      sum(int(c.nbytes)
                          for c in list(self._x) + list(y_cols)))

    @property
    def _y(self):
        """Back-compat single-label view (None / array / list)."""
        if not self._y_cols:
            return None
        return list(self._y_cols) if self._multi_y else self._y_cols[0]

    # -- constructors (reference FeatureSet.rdd/array factories) -----------
    @staticmethod
    def array(x, y=None, memory_type="dram", **kw) -> "FeatureSet":
        xs = x if isinstance(x, (list, tuple)) else [x]
        xs = [np.asarray(a) for a in xs]
        return FeatureSet(xs, y, memory_type=memory_type, **kw)

    @staticmethod
    def sample_rdd(samples: Iterable[Sample], memory_type="dram",
                   **kw) -> "FeatureSet":
        """Materialize an iterable of `Sample`s (the reference's
        RDD[Sample] ingest path, cached like
        `CachedDistributedFeatureSet`)."""
        feats: "list[list[np.ndarray]]" = []
        labels: "list[list[np.ndarray]]" = []
        has_label = None
        multi_label = False
        for s in samples:
            arrays = s.feature_arrays()
            if not feats:
                feats = [[] for _ in arrays]
            for col, a in zip(feats, arrays):
                col.append(a)
            if has_label is None:
                has_label = s.label is not None
                multi_label = isinstance(s.label, (list, tuple))
                if has_label:
                    labels = [[] for _ in
                              (s.label if multi_label else [s.label])]
            if has_label:
                lab = s.label if multi_label else [s.label]
                for col, a in zip(labels, lab):
                    col.append(np.asarray(a))
        if not feats:
            raise ValueError("empty sample stream")
        x_cols = [_stack_column(c) for c in feats]
        if not has_label:
            y_col = None
        elif multi_label:
            # keep multi-output label columns separate (a bare
            # np.asarray over the pairs would silently stack
            # same-shaped outputs into one bogus column)
            y_col = [_stack_column(c) for c in labels]
        else:
            y_col = _stack_column(labels[0])
        return FeatureSet(x_cols, y_col, memory_type=memory_type, **kw)

    @staticmethod
    def from_rdd(rdd: Any,
                 preprocessing: Optional[Preprocessing] = None,
                 memory_type="dram",
                 shard_index: Optional[int] = None,
                 num_shards: Optional[int] = None, **kw) -> "FeatureSet":
        """Ingest from anything implementing the RDD protocol — a real
        ``pyspark.RDD`` or :class:`~analytics_zoo_tpu.feature.rdd.LocalRdd`
        (reference: ``FeatureSet.rdd``, `Z/feature/FeatureSet.scala:308`).

        Each JAX process collects only its round-robin share of the
        partitions (defaults wired to ``jax.process_index()`` /
        ``jax.process_count()``), so multi-host ingest needs no flags.
        Records may be `Sample`s or raw values run through
        ``preprocessing``.
        """
        from analytics_zoo_tpu.feature.rdd import collect_shard, \
            is_spark_dataframe
        if is_spark_dataframe(rdd):
            rdd = rdd.rdd
        records = collect_shard(rdd, shard_index, num_shards)
        if records and not isinstance(records[0], Sample) \
                and preprocessing is None:
            # raw (feature, label) tuples or bare feature arrays
            records = [Sample(feature=r[0], label=r[1])
                       if isinstance(r, tuple) and len(r) == 2
                       else Sample(feature=r) for r in records]
        # the shard filter already ran; the row-range splitter must not
        # re-shard what is now purely local data
        return FeatureSet.from_iterable(
            records, preprocessing, memory_type=memory_type,
            shard_index=0, num_shards=1, **kw)

    @staticmethod
    def from_iterable(records: Iterable[Any],
                      preprocessing: Optional[Preprocessing] = None,
                      memory_type="dram", **kw) -> "FeatureSet":
        stream: Iterable[Any] = records
        if preprocessing is not None:
            stream = preprocessing.transform(stream)
        return FeatureSet.sample_rdd(stream, memory_type=memory_type, **kw)

    # -- transforms ---------------------------------------------------------
    def transform(self, preprocessing: Preprocessing) -> "FeatureSet":
        """Apply a Preprocessing chain, re-caching the result (reference
        `FeatureSet.transform` returning a transformed cached set)."""
        return FeatureSet.from_iterable(
            self._iter_samples(), preprocessing,
            memory_type=self.memory_type.value)

    def _iter_samples(self) -> Iterator[Sample]:
        for i in range(self._n):
            feats = [c[i] for c in self._x]
            if not self._y_cols:
                label = None
            elif self._multi_y:
                label = [c[i] for c in self._y_cols]
            else:
                label = self._y_cols[0][i]
            yield Sample(feature=feats if len(feats) > 1 else feats[0],
                         label=label)

    # -- Estimator data protocol -------------------------------------------
    @property
    def num_samples(self) -> int:
        return self._n

    def iter_batches(self, batch_size: int, shuffle: bool = True,
                     seed: int = 0, drop_last: bool = True
                     ) -> Iterator[Tuple[Any, Any]]:
        """Per-epoch index permutation (the reference's reshuffle via
        shuffled index array, `FeatureSet.scala:216-296`)."""
        idx = np.arange(self._n)
        if shuffle:
            np.random.RandomState(seed).shuffle(idx)
        end = (self._n - self._n % batch_size) if drop_last else self._n
        for start in range(0, end, batch_size):
            sel = np.sort(idx[start:start + batch_size]) if \
                self.memory_type == MemoryType.PMEM else \
                idx[start:start + batch_size]
            xb = [np.asarray(c[sel]) for c in self._x]
            xb = xb[0] if len(xb) == 1 else xb
            if not self._y_cols:
                yb = None
            elif self._multi_y:
                yb = [np.asarray(c[sel]) for c in self._y_cols]
            else:
                yb = np.asarray(self._y_cols[0][sel])
            yield xb, yb

    def __len__(self):
        return self._n

    def __repr__(self):
        return (f"FeatureSet(n={self._n}, tier={self.memory_type.value}, "
                f"x_cols={len(self._x)}, "
                f"labeled={self._y is not None})")
