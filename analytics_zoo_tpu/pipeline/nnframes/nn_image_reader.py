"""NNImageReader / NNImageSchema (reference
`Z/pipeline/nnframes/NNImageReader.scala:144-182`): read images into a
DataFrame with the image-schema struct columns
(origin, height, width, nChannels, mode, data). Paths resolve through
`common.utils`' fsspec helpers, so ``gs://``/``s3://``/``hdfs://``
trees read end-to-end like the reference's HDFS reads."""

from __future__ import annotations

import io
import logging
from typing import List

import numpy as np
import pandas as pd

from analytics_zoo_tpu.common import utils as zutils

logger = logging.getLogger(__name__)


class NNImageSchema:
    """Column names of the image struct (reference `NNImageSchema`)."""

    ORIGIN = "origin"
    HEIGHT = "height"
    WIDTH = "width"
    N_CHANNELS = "nChannels"
    MODE = "mode"
    DATA = "data"

    COLUMNS = [ORIGIN, HEIGHT, WIDTH, N_CHANNELS, MODE, DATA]

    @staticmethod
    def to_ndarray(row) -> np.ndarray:
        """image struct row → HWC uint8 ndarray."""
        return np.asarray(row[NNImageSchema.DATA], np.uint8).reshape(
            int(row[NNImageSchema.HEIGHT]),
            int(row[NNImageSchema.WIDTH]),
            int(row[NNImageSchema.N_CHANNELS]))


class NNImageReader:
    @staticmethod
    def read_images(path: str, min_partitions: int = 1,
                    resize_h: int = -1, resize_w: int = -1,
                    image_codec: int = -1) -> pd.DataFrame:
        """(reference `NNImageReader.readImages`; `min_partitions` and
        `image_codec` kept for signature parity.)"""
        from PIL import Image
        del min_partitions, image_codec
        if zutils.is_dir(path):
            files = zutils.walk_files(path)
        else:
            files = zutils.list_files(path)
        # one batched fetch (fs.cat) for remote schemes; IO errors
        # propagate — only DECODE failures mark a file as non-image
        blobs = zutils.read_bytes_many(files)

        def dec(f):
            try:
                with Image.open(io.BytesIO(blobs[f])) as im:
                    rgb = im.convert("RGB")
                    if resize_h > 0 and resize_w > 0:
                        rgb = rgb.resize((resize_w, resize_h),
                                         Image.BILINEAR)
                    return np.asarray(rgb, np.uint8)
            except Exception:
                return None  # non-image file → skipped (with warning)

        # PIL decode/resize release the GIL: thread-pool the batch
        # (same knob as ImageSet.read's decoder)
        rows = []
        dropped: List[str] = []
        for f, arr in zip(files, zutils.parallel_map(dec, files)):
            if arr is None:
                dropped.append(f)
                continue
            rows.append({
                NNImageSchema.ORIGIN: f,
                NNImageSchema.HEIGHT: arr.shape[0],
                NNImageSchema.WIDTH: arr.shape[1],
                NNImageSchema.N_CHANNELS: arr.shape[2],
                NNImageSchema.MODE: 16,  # CV_8UC3 parity
                NNImageSchema.DATA: arr.reshape(-1),
            })
        if dropped:
            logger.warning(
                "NNImageReader: skipped %d of %d file(s) that failed "
                "to decode (first: %s)", len(dropped), len(files),
                dropped[0])
        return pd.DataFrame(rows, columns=NNImageSchema.COLUMNS)
