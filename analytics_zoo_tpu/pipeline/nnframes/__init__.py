from analytics_zoo_tpu.pipeline.nnframes.nn_estimator import (
    NNEstimator, NNModel, NNClassifier, NNClassifierModel)
from analytics_zoo_tpu.pipeline.nnframes.nn_image_reader import (
    NNImageReader, NNImageSchema)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader", "NNImageSchema"]
