"""nnframes (L8): DataFrame-native ML pipeline.

Reference: `Z/pipeline/nnframes/NNEstimator.scala:183-816` — a Spark
`ml.Estimator` that maps DataFrame rows through a `Preprocessing` chain
into Samples/MiniBatches, drives the distributed optimizer, and returns
an `NNModel` transformer that appends a prediction column
(`NNClassifier.scala:42,140` adds classification sugar).

The DataFrame engine here is pandas (Spark isn't part of the TPU-native
core; SURVEY.md §2.10 keeps "RDD/DataFrame" as an ingest role only). The
API surface — estimator params, `fit(df) -> NNModel`,
`transform(df) -> df + prediction`, ML-style setters — is kept, so
nnframes user code ports by changing the DataFrame import.
"""

from __future__ import annotations

import os
import pickle
from typing import Callable, Optional

import numpy as np
import pandas as pd

from analytics_zoo_tpu.common.nncontext import get_nncontext
from analytics_zoo_tpu.feature.common import Preprocessing, Sample
from analytics_zoo_tpu.feature.feature_set import FeatureSet
from analytics_zoo_tpu.pipeline.estimator import Estimator, Trigger


class _Params:
    """Spark-ML-style param plumbing: `set_x(v)`/`setX(v)` both work."""

    def __getattr__(self, name):
        # camelCase aliases for API parity (setFeaturesCol, ...)
        if name.startswith("set") and len(name) > 3 and \
                name[3].isupper():
            snake = "set_" + "".join(
                ("_" + c.lower()) if c.isupper() else c
                for c in name[3:]).lstrip("_")
            return object.__getattribute__(self, snake)
        raise AttributeError(name)


class NNEstimator(_Params):
    def __init__(self, model, criterion="mse",
                 feature_preprocessing: Optional[Preprocessing] = None,
                 label_preprocessing: Optional[Preprocessing] = None):
        self.model = model
        self.criterion = criterion
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        self.features_col = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"
        self.batch_size = 32
        self.max_epoch = 10
        self.optim_method = "adam"
        self.learning_rate: Optional[float] = None
        self.validation_df: Optional[pd.DataFrame] = None
        self.validation_trigger: Optional[Trigger] = None
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.tensorboard: Optional[tuple] = None
        self.clip_l2: Optional[float] = None
        self.clip_const: Optional[tuple] = None
        self.metrics: "list" = []

    # -- param setters (reference `NNEstimator` params) --------------------
    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    def set_batch_size(self, v):
        self.batch_size = int(v)
        return self

    def set_max_epoch(self, v):
        self.max_epoch = int(v)
        return self

    def set_optim_method(self, v):
        self.optim_method = v
        return self

    def set_learning_rate(self, v):
        self.learning_rate = float(v)
        return self

    def set_validation(self, df, trigger: Optional[Trigger] = None,
                       metrics: Optional[list] = None):
        """(reference `setValidation`)"""
        self.validation_df = df
        self.validation_trigger = trigger
        if metrics:
            self.metrics = metrics
        return self

    def set_checkpoint(self, path, trigger: Optional[Trigger] = None):
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        return self

    def set_tensorboard(self, log_dir, app_name="nnframes"):
        self.tensorboard = (log_dir, app_name)
        return self

    def set_gradient_clipping_by_l2_norm(self, v):
        self.clip_l2 = float(v)
        return self

    def set_constant_gradient_clipping(self, lo, hi):
        self.clip_const = (float(lo), float(hi))
        return self

    # -- data plumbing (reference `getDataSet`, NNEstimator.scala:361) -----
    def _row_to_feature(self, value):
        if self.feature_preprocessing is not None:
            return self.feature_preprocessing.apply(value)
        return np.asarray(value, np.float32)

    def _collect_rows(self, df, with_label: bool):
        """Yield (feature_value, label_value|None) from a pandas
        DataFrame, a Spark DataFrame, or an RDD of (feature, label)
        tuples/Samples. Spark rows are narrowed to the needed columns
        executor-side, and each JAX process collects only its partition
        share (reference NNEstimator.scala:361-390 maps df.rdd the same
        way; here multi-host replaces multi-executor)."""
        from analytics_zoo_tpu.feature.rdd import is_rdd_like, \
            is_spark_dataframe, iter_shard
        if isinstance(df, pd.DataFrame):
            has_label = with_label and self.label_col in df.columns
            for _, row in df.iterrows():
                yield row[self.features_col], \
                    (row[self.label_col] if has_label else None)
            return
        if is_spark_dataframe(df):
            has_label = with_label and self.label_col in df.columns
            cols = [self.features_col] + \
                ([self.label_col] if has_label else [])
            rdd = df.select(*cols).rdd
            for row in iter_shard(rdd):
                yield row[0], (row[1] if has_label else None)
            return
        if is_rdd_like(df):
            for rec in iter_shard(df):
                if isinstance(rec, Sample):
                    yield rec, None
                elif isinstance(rec, tuple) and len(rec) == 2:
                    yield rec[0], (rec[1] if with_label else None)
                else:
                    yield rec, None
            return
        raise TypeError(
            f"unsupported DataFrame/RDD type: {type(df).__name__}")

    def _df_to_feature_set(self, df,
                           with_label: bool = True) -> FeatureSet:
        samples = []
        for value, label_val in self._collect_rows(df, with_label):
            if isinstance(value, Sample):
                samples.append(value)
                continue
            feat = self._row_to_feature(value)
            if isinstance(feat, Sample):
                samples.append(feat)
                continue
            label = None
            if label_val is not None:
                if self.label_preprocessing is not None:
                    label = self.label_preprocessing.apply(label_val)
                else:
                    label = np.atleast_1d(
                        np.asarray(label_val, np.float32))
            samples.append(Sample(feature=feat, label=label))
        return FeatureSet.sample_rdd(samples)

    # -- fit ----------------------------------------------------------------
    def _build_optimizer(self):
        from analytics_zoo_tpu.ops import optimizers as optim_lib
        opt = self.optim_method
        if isinstance(opt, str) and self.learning_rate is not None:
            opt = optim_lib._REGISTRY[opt.lower()](lr=self.learning_rate)
        return opt

    def fit(self, df) -> "NNModel":
        """(reference `NNEstimator.fit → internalFit`,
        NNEstimator.scala:392-450)"""
        fs = self._df_to_feature_set(df)
        est = Estimator(self.model, optimizer=self._build_optimizer(),
                        loss=self.criterion, metrics=self.metrics)
        # a model that already carries weights (pretrained backbone
        # loaded via compile+load_weights, prior fit, ...) trains FROM
        # them — re-initializing would silently discard the transfer-
        # learning starting point (reference trains the model it was
        # given, NNEstimator.scala:415). _place_params COPIES onto the
        # mesh: the jitted step donates its params, and sharing
        # buffers with the model's own estimator would invalidate them
        prior = getattr(self.model, "_estimator", None)
        if prior is not None and prior.params is not None:
            from analytics_zoo_tpu.pipeline.estimator import \
                _check_params_compatible
            try:
                _check_params_compatible(self.model, prior.params)
                est.params = est._place_params(prior.params)
            except (KeyError, ValueError):
                from analytics_zoo_tpu.common.nncontext import logger
                logger.warning(
                    "NNEstimator.fit: existing params no longer match "
                    "the model topology; re-initializing")
        if self.clip_l2 is not None:
            est.set_gradient_clipping_by_l2_norm(self.clip_l2)
        if self.clip_const is not None:
            est.set_constant_gradient_clipping(*self.clip_const)
        if self.checkpoint_path:
            est.set_checkpoint(self.checkpoint_path,
                               self.checkpoint_trigger)
        if self.tensorboard:
            est.set_tensorboard(*self.tensorboard)
        val = None
        if self.validation_df is not None:
            val = self._df_to_feature_set(self.validation_df)
        est.train(fs, batch_size=self.batch_size,
                  nb_epoch=self.max_epoch, validation_data=val,
                  validation_trigger=self.validation_trigger)
        if prior is not None:
            # reference semantics: fit mutates the given model — a
            # second fit (or model.predict) continues from the trained
            # weights, not the pre-fit ones
            prior.params = est.params
            prior.opt_state = None        # moments belong to est
            prior._train_step = None
        return self._wrap_model(est)

    def _wrap_model(self, est: Estimator) -> "NNModel":
        m = NNModel(self.model, self.feature_preprocessing,
                    estimator=est)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = self.batch_size
        return m


class NNModel(_Params):
    """`ml.Transformer` analog: batched inference appending a prediction
    column (reference NNEstimator.scala:571-816, incl. persistence)."""

    def __init__(self, model,
                 feature_preprocessing: Optional[Preprocessing] = None,
                 estimator: Optional[Estimator] = None):
        self.model = model
        self.feature_preprocessing = feature_preprocessing
        self.features_col = "features"
        self.prediction_col = "prediction"
        self.batch_size = 32
        if estimator is None:
            estimator = Estimator(model, optimizer="adam", loss="mse")
            estimator._ensure_initialized()
        self.estimator = estimator

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    def set_batch_size(self, v):
        self.batch_size = int(v)
        return self

    @staticmethod
    def _spark_session_of(df):
        return getattr(df, "sparkSession", None) or \
            df.sql_ctx.sparkSession

    @staticmethod
    def _spark_safe(pdf: pd.DataFrame) -> pd.DataFrame:
        # createDataFrame rejects ndarray cells (e.g. a features column
        # that round-tripped through toPandas) — listify them
        return pdf.apply(lambda col: col.map(
            lambda v: v.tolist() if isinstance(v, np.ndarray) else v))

    def _features_array(self, df: pd.DataFrame) -> np.ndarray:
        rows = []
        for v in df[self.features_col]:
            f = (self.feature_preprocessing.apply(v)
                 if self.feature_preprocessing is not None
                 else np.asarray(v, np.float32))
            if isinstance(f, Sample):
                f = f.feature
            rows.append(np.asarray(f, np.float32))
        return np.stack(rows)

    def _raw_predict(self, df: pd.DataFrame) -> np.ndarray:
        x = self._features_array(df)
        return self.estimator.predict(x, batch_size=self.batch_size)

    def transform(self, df):
        """Append the prediction column. Spark DataFrames stream
        through the driver in bounded chunks (``toLocalIterator`` →
        predict → per-chunk ``createDataFrame`` → union), so the
        resident feature set is one chunk, not the whole DataFrame —
        the driver-side analog of the reference's batched
        executor-side predict (NNEstimator.scala:571-674). Chunk rows:
        ``ZOO_TPU_TRANSFORM_CHUNK`` (default 1024, floored at
        batch_size)."""
        from analytics_zoo_tpu.feature.rdd import is_spark_dataframe
        if is_spark_dataframe(df):
            return self._stream_spark_transform(
                df, lambda col: [[float(v)
                                  for v in np.asarray(p).reshape(-1)]
                                 for p in col],
                scalar_pred=False)
        preds = self._raw_predict(df)
        out = df.copy()
        out[self.prediction_col] = [np.asarray(p).reshape(-1)
                                    for p in preds]
        return out

    def _output_schema(self, df, scalar_pred: bool):
        """Input schema + the prediction field, so every chunk's
        createDataFrame uses ONE schema regardless of what the chunk's
        values would infer (an all-None nullable column in some chunk
        must not change types). None when pyspark types are
        unavailable (duck-typed test doubles) — falls back to
        first-chunk inference."""
        base = getattr(df, "schema", None)
        if base is None:
            return None
        if self.prediction_col in df.columns:
            # re-scoring: the pandas transform overwrites the column
            # IN PLACE, so positions differ from base-fields-then-
            # prediction — let first-chunk inference (which matches
            # the pandas order by construction) pin the schema
            return None
        try:
            from pyspark.sql.types import (ArrayType, DoubleType,
                                           StructField, StructType)
        except ImportError:
            return None
        pred_t = DoubleType() if scalar_pred \
            else ArrayType(DoubleType())
        fields = [f for f in base.fields
                  if f.name != self.prediction_col]
        return StructType(
            fields + [StructField(self.prediction_col, pred_t, True)])

    def _stream_spark_transform(self, df, finalize: Callable,
                                scalar_pred: bool = False):
        """Chunked Spark-DataFrame transform: toLocalIterator →
        (subclass) pandas transform per chunk → per-chunk
        createDataFrame → tree-reduced union (O(log n) plan depth).
        The Python-resident feature chunk is bounded; every chunk uses
        ONE output schema — built from ``df.schema`` + the prediction
        field when pyspark is importable, else pinned from the first
        chunk's inference. `finalize` serialises the prediction column
        for Spark rows."""
        import itertools
        spark = self._spark_session_of(df)
        chunk_rows = max(self.batch_size, int(os.environ.get(
            "ZOO_TPU_TRANSFORM_CHUNK", "1024")))
        cols = list(df.columns)
        schema = self._output_schema(df, scalar_pred)

        def flush(buf):
            nonlocal schema
            out = self.transform(pd.DataFrame(buf, columns=cols))
            out[self.prediction_col] = finalize(
                out[self.prediction_col])
            safe = self._spark_safe(out)
            part = spark.createDataFrame(safe) if schema is None \
                else spark.createDataFrame(safe, schema=schema)
            if schema is None:
                schema = getattr(part, "schema", None)
            return part

        # tree-reduce the unions: stack of (level, df), equal levels
        # merge — keeps both plan depth and union count logarithmic
        stack: "list" = []

        def push(part):
            level = 0
            while stack and stack[-1][0] == level:
                _, prev = stack.pop()
                part = prev.unionAll(part)
                level += 1
            stack.append((level, part))

        it = df.toLocalIterator()
        chunks = iter(
            lambda: [tuple(r) for r in itertools.islice(it, chunk_rows)],
            [])
        n = 0
        for buf in chunks:
            push(flush(buf))
            n += 1
        if n == 0:          # empty input: same error surface as pandas
            push(flush([]))
        result = None
        for _, part in stack:
            result = part if result is None else result.unionAll(part)
        return result

    # -- persistence (MLWritable/MLReadable analog) -------------------------
    def save(self, path: str, over_write: bool = False):
        if os.path.exists(path) and not over_write:
            raise FileExistsError(path)
        import jax
        state = {
            "model": self.model,
            "params": jax.device_get(self.estimator.params),
            "features_col": self.features_col,
            "prediction_col": self.prediction_col,
            "batch_size": self.batch_size,
            "feature_preprocessing": self.feature_preprocessing,
            "class": type(self).__name__,
        }
        with open(path, "wb") as f:
            pickle.dump(state, f)

    @classmethod
    def load(cls, path: str) -> "NNModel":
        from analytics_zoo_tpu.common.safe_pickle import checked_load
        from analytics_zoo_tpu.parallel.mesh import shard_params
        state = checked_load(path)  # class-whitelist deserialization
        klass = (NNClassifierModel
                 if state.get("class") == "NNClassifierModel" else cls)
        m = klass(state["model"], state["feature_preprocessing"])
        m.features_col = state["features_col"]
        m.prediction_col = state["prediction_col"]
        m.batch_size = state["batch_size"]
        m.estimator.params = shard_params(state["params"],
                                          get_nncontext().mesh)
        return m


class NNClassifier(NNEstimator):
    """Classification sugar (reference `NNClassifier.scala:42`): float
    labels, argmax prediction."""

    def fit(self, df) -> "NNClassifierModel":
        nn_model = super().fit(df)
        m = NNClassifierModel(self.model, self.feature_preprocessing,
                              estimator=nn_model.estimator)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = self.batch_size
        return m


class NNClassifierModel(NNModel):
    """(reference `NNClassifierModel`, NNClassifier.scala:140): appends
    the argmax class as a scalar prediction."""

    def transform(self, df):
        from analytics_zoo_tpu.feature.rdd import is_spark_dataframe
        if is_spark_dataframe(df):
            return self._stream_spark_transform(
                df, lambda col: [float(v) for v in col],
                scalar_pred=True)
        preds = self._raw_predict(df)
        out = df.copy()
        if preds.ndim > 1 and preds.shape[-1] > 1:
            out[self.prediction_col] = np.argmax(preds, axis=-1) \
                .astype(np.float64)
        else:
            out[self.prediction_col] = (preds.reshape(-1) > 0.5) \
                .astype(np.float64)
        return out
