"""TF integration (L5, the TFPark analog).

Reference: `P/pipeline/api/net.py` + `Z/pipeline/api/net/TFNet.scala` —
TFNet executes a frozen TF graph via a JNI session inside BigDL
(`TFNet.scala:216-384`), TFOptimizer exports the loss graph + gradients
and drives BigDL's optimizer (`net.py:365-714`), TFDataset is the
distributed tensor dataset (`net.py:724-931`).

TPU-native redesign (the BASELINE.json north star: "TFNet/TFOptimizer
exports its frozen TF graph straight to XLA HLO"):

- :class:`TFNet` bridges a TF SavedModel / frozen GraphDef / concrete
  `tf.function` into JAX with `jax2tf.call_tf` — the graph is compiled
  by XLA and runs on TPU inside `jit`; no session, no JNI, no
  per-batch tensor copies (`TFNet.scala:484-525`'s zero-copy dance is
  simply gone).
- :class:`TFOptimizer` trains a TF-authored differentiable function on
  the TPU mesh: weights are explicit JAX arrays, gradients flow through
  `call_tf` (TF computes the local VJP, XLA fuses it), and the update
  loop is the framework's pjit Estimator step. After training the
  trained weights are written back into the live TF objects —
  preserving the reference's assign-back-to-session contract
  (`net.py:703-714`).
- :class:`TFDataset` keeps the API (batch_size divisibility over the
  data-parallel size, `net.py:741-749`) over FeatureSet.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common.nncontext import get_nncontext
from analytics_zoo_tpu.feature.feature_set import FeatureSet


def _tf():
    import tensorflow as tf
    return tf


class TFNet:
    """A TF graph as a JAX-callable compiled by XLA.

    Create via :meth:`from_saved_model`, :meth:`from_frozen_graph`, or
    :meth:`from_function`; call with numpy/JAX arrays. Usable inside
    `jit` and as a frozen feature extractor in a larger zoo model (the
    reference's transfer-learning TFNet role).
    """

    def __init__(self, jax_fn: Callable, output_names: Optional[list] =
                 None, keepalive: Any = None):
        self._fn = jax_fn
        self.output_names = output_names
        # holds the loaded TF module so its variables outlive the closure
        self._keepalive = keepalive

    def __call__(self, *inputs):
        return self._fn(*inputs)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_function(fn, output_names: Optional[list] = None) -> "TFNet":
        """Wrap a `tf.function` (or python fn of TF ops)."""
        from jax.experimental import jax2tf
        return TFNet(jax2tf.call_tf(fn), output_names)

    @staticmethod
    def from_saved_model(path: str, signature: str = "serving_default",
                         ) -> "TFNet":
        """(reference `TFNet.fromSavedModel`)"""
        tf = _tf()
        loaded = tf.saved_model.load(path)
        if signature in getattr(loaded, "signatures", {}):
            sig = loaded.signatures[signature]
            names = list(sig.structured_outputs.keys())

            def fn(*xs):
                kwargs = {k: v for k, v in
                          zip(sig.structured_input_signature[1], xs)}
                out = sig(**{name: x for name, x in
                             zip(sig.structured_input_signature[1].keys(),
                                 xs)})
                return [out[k] for k in names]

            from jax.experimental import jax2tf
            return TFNet(jax2tf.call_tf(fn), names, keepalive=loaded)
        # plain callable module
        from jax.experimental import jax2tf
        return TFNet(jax2tf.call_tf(loaded.__call__), keepalive=loaded)

    @staticmethod
    def from_frozen_graph(pb_path: str, inputs: Sequence[str],
                          outputs: Sequence[str]) -> "TFNet":
        """Frozen `GraphDef` → XLA (reference `TFNet(path)` over
        `frozen_inference_graph.pb`, TFNet.scala:595-651)."""
        tf = _tf()
        gdef = tf.compat.v1.GraphDef()
        with open(pb_path, "rb") as f:
            gdef.ParseFromString(f.read())

        def _norm(name):
            return name if ":" in name else name + ":0"

        in_names = [_norm(n) for n in inputs]
        out_names = [_norm(n) for n in outputs]

        def import_fn(*xs):
            results = tf.graph_util.import_graph_def(
                gdef,
                input_map={n: x for n, x in zip(in_names, xs)},
                return_elements=out_names)
            return results if len(results) > 1 else results[0]

        wrapped = tf.compat.v1.wrap_function(
            import_fn,
            [tf.TensorSpec(None, tf.float32) for _ in in_names])
        from jax.experimental import jax2tf
        return TFNet(jax2tf.call_tf(wrapped), list(outputs))

    def predict(self, x, batch_size: int = 32,
                distributed: bool = True) -> np.ndarray:
        """Batched inference (reference `TFNet.predict`)."""
        del distributed
        import jax
        fn = jax.jit(self._fn)
        xs = x if isinstance(x, (list, tuple)) else [x]
        n = xs[0].shape[0]
        outs = []
        for s in range(0, n, batch_size):
            chunk = [a[s:s + batch_size] for a in xs]
            outs.append(np.asarray(fn(*chunk)))
        return np.concatenate(outs, axis=0)


class TFDataset:
    """(reference `TFDataset`, `P/pipeline/api/net.py:724-931`): the
    batch-size contract over the data-parallel size, on FeatureSet."""

    def __init__(self, feature_set: FeatureSet, batch_size: int):
        ctx = get_nncontext()
        ctx.check_batch_size(batch_size)
        self.feature_set = feature_set
        self.batch_size = batch_size

    @staticmethod
    def from_ndarrays(x, y=None, batch_size: int = 32) -> "TFDataset":
        return TFDataset(FeatureSet.array(x, y), batch_size)

    @staticmethod
    def from_feature_set(fs: FeatureSet, batch_size: int = 32
                         ) -> "TFDataset":
        return TFDataset(fs, batch_size)

    @property
    def num_samples(self):
        return self.feature_set.num_samples

    def iter_batches(self, batch_size=None, **kw):
        return self.feature_set.iter_batches(
            batch_size or self.batch_size, **kw)


class _TFFunctionNet:
    """Internal KerasNet-protocol shim: a TF-authored function with
    explicit weights, trained by the Estimator."""

    def __init__(self, jax_fn, weight_template):
        self._fn = jax_fn
        self._template = weight_template
        self.name = "tf_function_net"
        self.layers = []

    def init_params(self, rng=None, input_shape=None,
                    device=None):  # host numpy either way
        return {"weights": [np.asarray(w) for w in self._template]}

    def init(self, rng, input_shape=None):
        return self.init_params(rng)

    def apply(self, params, x, *, training=False, rng=None):
        xs = x if isinstance(x, (list, tuple)) else [x]
        return self._fn(*params["weights"], *xs), {}

    def forward(self, params, x, *, training=False, rng=None):
        out, _ = self.apply(params, x, training=training, rng=rng)
        return out

    def regularization_loss(self, params):
        import jax.numpy as jnp
        return jnp.zeros((), jnp.float32)

    def trainable_mask(self, params):
        import jax
        return jax.tree_util.tree_map(lambda _: True, params)


class TFOptimizer:
    """Train a TF-authored model function on the TPU mesh (reference
    `TFOptimizer`, `net.py:365-714`).

    ``model_fn(*weights, *features) -> outputs`` is a TF-ops function;
    gradients flow through `jax2tf.call_tf` (TF provides the VJP, XLA
    compiles both directions). ``variables`` are live `tf.Variable`s:
    their values seed training and receive the trained weights back at
    the end (the reference's weights→session assign-back,
    `net.py:703-714`).
    """

    def __init__(self, model_fn, variables: Sequence,
                 loss="mse", optimizer="adam", metrics=None):
        from jax.experimental import jax2tf

        from analytics_zoo_tpu.pipeline.estimator import Estimator
        self.variables = list(variables)
        jax_fn = jax2tf.call_tf(model_fn)
        net = _TFFunctionNet(jax_fn,
                             [v.numpy() for v in self.variables])
        self.net = net
        self.estimator = Estimator(net, optimizer=optimizer, loss=loss,
                                   metrics=metrics or [])

    @staticmethod
    def from_loss(model_fn, variables, loss="mse", optimizer="adam",
                  **kw) -> "TFOptimizer":
        return TFOptimizer(model_fn, variables, loss=loss,
                           optimizer=optimizer, **kw)

    def optimize(self, dataset, batch_size: int = 32,
                 end_trigger=None, nb_epoch: int = 1):
        """Run training then write trained weights back into the live TF
        variables."""
        if isinstance(dataset, tuple) and len(dataset) == 2:
            data, y = dataset
        else:
            data, y = dataset, None
        result = self.estimator.train(
            data, y, batch_size=batch_size, nb_epoch=nb_epoch,
            end_trigger=end_trigger)
        import jax
        trained = jax.device_get(self.estimator.params)["weights"]
        for var, w in zip(self.variables, trained):
            var.assign(w)
        return result

    def predict(self, x, batch_size: int = 32):
        return self.estimator.predict(x, batch_size=batch_size)


class TFPredictor:
    """Distributed-inference wrapper over a TF session-style (fn,
    outputs) pair (reference `TFPredictor`, `P/pipeline/api/net.py:
    1004-1054`: wraps sess+outputs as a TFNet and maps the dataset).

    Here the "session" is a tf.function / keras model / TFNet; predict
    runs the XLA-bridged graph over host batches (batched, single
    process — multi-chip sharding comes from serving many predictors
    or using `Estimator.predict` on a native model).
    """

    def __init__(self, net):
        if not isinstance(net, TFNet):
            net = TFNet.from_function(net)
        self.net = net

    @staticmethod
    def from_keras(model) -> "TFPredictor":
        """(reference `TFPredictor.from_keras`)"""
        return TFPredictor(TFNet.from_function(
            lambda x: model(x, training=False)))

    @staticmethod
    def from_session(fn, outputs=None) -> "TFPredictor":
        """TF1-style (session, outputs) pairs map to a tf.function in
        TF2; ``outputs`` kept for API parity."""
        del outputs
        return TFPredictor(TFNet.from_function(fn))

    def predict(self, data, batch_size: int = 32):
        return self.net.predict(data, batch_size=batch_size)
