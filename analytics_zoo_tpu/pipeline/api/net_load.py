"""Interop model loaders (reference `Z/pipeline/api/Net.scala:91-189`:
`Net.load{BigDL,Torch,Caffe,TF,Keras}`).

TPU-native mapping:
- :meth:`Net.load` — the framework's own saved models
  (`ZooModel.save_model` pickles / `save_weights` npz), restored through
  the class-whitelist safe unpickler (reference
  `CheckedObjectInputStream`, SURVEY.md §2.1).
- :meth:`Net.load_torch` — imports a `torch.nn.Sequential` of standard
  modules into native zoo layers (weights transposed to our layouts:
  Dense kernel (in,out), conv kernel HWIO) so the result runs as pure
  XLA on TPU; the reference loaded legacy Torch7 `.t7` files.
- :meth:`Net.load_keras` — tf.keras `.keras`/`.h5` files via
  `tf.keras.models.load_model` + the tfpark GraphDef→XLA bridge.
- :meth:`Net.load_tf` — SavedModel / frozen GraphDef via `TFNet`.
- :meth:`Net.load_caffe` — prototxt+caffemodel via the self-contained
  importer (`caffe_load.py`).
- :meth:`Net.load_bigdl` / :meth:`Net.load` — BigDL/zoo-Keras ``.model``
  protobuf files and this framework's own `ZooModel.save_model` files
  (format-sniffed).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common.nncontext import logger


class Net:
    """(reference `pipeline/api/Net.scala:40-189`)"""

    @staticmethod
    def load_tf(path: str, inputs: Optional[Sequence[str]] = None,
                outputs: Optional[Sequence[str]] = None):
        """SavedModel dir or frozen `.pb` → `TFNet` (reference
        `Net.loadTF`)."""
        from analytics_zoo_tpu.pipeline.api.net import TFNet
        import os
        if os.path.isdir(path):
            return TFNet.from_saved_model(path)
        if inputs is None or outputs is None:
            raise ValueError(
                "frozen-graph import needs inputs=[...] and "
                "outputs=[...] tensor names")
        return TFNet.from_frozen_graph(path, inputs, outputs)

    @staticmethod
    def load_keras(path_or_model, by_name: bool = False):
        """tf.keras model file → trainable `tfpark.KerasModel`
        (reference `Net.loadKeras`; `by_name` kept for API parity)."""
        del by_name
        import tensorflow as tf

        from analytics_zoo_tpu.tfpark import KerasModel
        model = (path_or_model
                 if isinstance(path_or_model, tf.keras.Model)
                 else tf.keras.models.load_model(path_or_model))
        if not getattr(model, "optimizer", None):
            model.compile(optimizer="adam", loss="mse")
        return KerasModel(model)

    @staticmethod
    def load_caffe(def_path: str, model_path: Optional[str] = None,
                   input_shape=None):
        """Caffe prototxt (+ caffemodel weights) → native Sequential
        (reference `Net.loadCaffe`, Net.scala:130-146); self-contained
        codec, no caffe/protobuf install needed."""
        from analytics_zoo_tpu.pipeline.api.caffe_load import load_caffe
        return load_caffe(def_path, model_path, input_shape=input_shape)

    @staticmethod
    def load_bigdl(path: str, weight_path: Optional[str] = None,
                   input_shape=None):
        """BigDL ``.model`` protobuf → native Sequential (reference
        `Net.loadBigDL`, Net.scala:91-118). ``weight_path`` is accepted
        for API parity (weights are embedded in the proto)."""
        del weight_path
        from analytics_zoo_tpu.pipeline.api.bigdl_load import load_bigdl
        return load_bigdl(path, input_shape=input_shape)

    @staticmethod
    def load(path: str, weight_path: Optional[str] = None,
             input_shape=None):
        """Load an analytics-zoo saved model (reference `Net.load`,
        Net.scala:91). Handles both formats by sniffing: the
        reference's BigDL protobuf ``.model`` files AND this
        framework's own ``ZooModel.save_model``/`saveModel` files."""
        with open(path, "rb") as f:
            head = f.read(2)
        if head[:1] == b"\x80":  # pickle protocol marker → ZooModel
            from analytics_zoo_tpu.models.common import ZooModel
            return ZooModel.load_model(path)
        return Net.load_bigdl(path, weight_path,
                              input_shape=input_shape)

    # -- torch import -------------------------------------------------------
    @staticmethod
    def load_torch(module_or_path, input_shape) -> Any:
        """Import a `torch.nn.Sequential` (or a path to a pickled one /
        state-dict-compatible module) into a native zoo `Sequential`.

        ``input_shape`` excludes the batch dim and uses torch's
        channels-first layout for images (C, H, W). Weights are copied
        in, so the returned model predicts identically (and can be
        fine-tuned natively on TPU).
        """
        import torch

        module = module_or_path
        if isinstance(module_or_path, str):
            module = _safe_torch_load(module_or_path)
        if not isinstance(module, torch.nn.Module):
            raise TypeError(f"expected torch.nn.Module, got "
                            f"{type(module)}")
        zoo_layers, weight_map = _torch_to_zoo(
            module, input_shape=input_shape)
        from analytics_zoo_tpu.pipeline.api.keras.models import \
            Sequential
        net = Sequential()
        first = True
        for lyr in zoo_layers:
            if first:
                lyr._given_input_shape = tuple(input_shape)
                first = False
            net.add(lyr)
        net.compile(optimizer="sgd", loss="mse")
        est = net.estimator
        est._ensure_initialized()
        import jax
        params = jax.device_get(est.params)
        for layer_name, assignments in weight_map.items():
            sub = params[layer_name]
            for key, value in assignments.items():
                if key == "_state":
                    for sk, sv in value.items():
                        _check_and_set(sub["_state"], sk, sv,
                                       layer_name)
                else:
                    _check_and_set(sub, key, value, layer_name)
        from analytics_zoo_tpu.parallel.mesh import shard_params
        est.params = shard_params(params, est.ctx.mesh)
        est._train_step = None
        est._predict_fn = None
        logger.info("load_torch: imported %d layers, %d weighted",
                    len(zoo_layers), len(weight_map))
        return net


def _safe_torch_load(path: str):
    """Load a pickled torch module WITHOUT executing arbitrary pickle
    code: ``weights_only=True`` plus an allowlist of exactly the
    ``torch.nn`` classes the importer can map. Arbitrary-code pickles
    require the explicit opt-in env ``ZOO_TPU_TRUST_TORCH_PICKLE=1``
    (mirrors the framework-wide CheckedUnpickler hardening)."""
    import torch
    import torch.nn as nn

    safe = [
        nn.Sequential, nn.Linear, nn.Conv2d, nn.MaxPool2d, nn.AvgPool2d,
        nn.AdaptiveAvgPool2d, nn.BatchNorm1d, nn.BatchNorm2d,
        nn.LayerNorm, nn.Embedding, nn.Flatten, nn.Dropout, nn.Identity,
        nn.ReLU, nn.Sigmoid, nn.Tanh, nn.GELU, nn.SiLU, nn.Softmax,
        nn.LeakyReLU, nn.ELU,
    ]
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        with torch.serialization.safe_globals(safe):
            return torch.load(path, weights_only=True)
    except (pickle.UnpicklingError, RuntimeError, ValueError) as e:
        # only unpickling-safety failures reach the trust gate;
        # missing/corrupt-file errors propagate as themselves
        if os.environ.get("ZOO_TPU_TRUST_TORCH_PICKLE") == "1":
            logger.warning(
                "load_torch: %s failed the weights-only safety check "
                "(%s); loading with arbitrary pickle execution because "
                "ZOO_TPU_TRUST_TORCH_PICKLE=1 — only do this for "
                "trusted files", path, e)
            return torch.load(path, weights_only=False)
        raise RuntimeError(
            f"refusing to unpickle {path!r} with code execution "
            f"(weights-only load failed: {e}); if the file is trusted, "
            "set ZOO_TPU_TRUST_TORCH_PICKLE=1 or pass the live module "
            "object instead of a path") from e


def _check_and_set(sub: dict, key: str, value: np.ndarray, name: str):
    if key not in sub:
        raise KeyError(f"layer {name} has no param {key!r}")
    if tuple(sub[key].shape) != tuple(value.shape):
        raise ValueError(
            f"{name}.{key}: shape {tuple(value.shape)} does not match "
            f"model {tuple(sub[key].shape)}")
    sub[key] = np.ascontiguousarray(value)


def _flatten_torch(module):
    import torch.nn as nn
    if isinstance(module, nn.Sequential):
        out = []
        for child in module.children():
            out.extend(_flatten_torch(child))
        return out
    return [module]


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _torch_to_zoo(module, input_shape=None):
    """torch modules → (zoo layers, {zoo_layer_name: param assignments}).

    Images stay in torch's NCHW layout via ``dim_ordering="th"`` — no
    transpose nodes; XLA lays out either ordering onto the MXU.
    ``input_shape`` (torch layout, no batch) lets the walker track the
    running shape through the emitted layers, unlocking modules whose
    mapping needs static sizes (AdaptiveAvgPool2d to any output size).
    """
    import torch.nn as nn

    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    zoo_layers = []
    weights = {}
    shape = {"cur": tuple(input_shape) if input_shape else None}

    def emit(layer, assignments=None):
        zoo_layers.append(layer)
        if assignments:
            weights[id(layer)] = assignments
        if shape["cur"] is not None:
            try:
                shape["cur"] = tuple(
                    layer.compute_output_shape(shape["cur"]))
            except Exception as e:
                # stop tracking but keep importing; remember why so
                # shape-dependent modules can say which layer broke it
                shape["cur"] = None
                shape["lost_at"] = f"{type(layer).__name__}: {e}"
        return layer

    for m in _flatten_torch(module):
        if isinstance(m, nn.Identity):
            continue
        if isinstance(m, nn.Linear):
            lyr = emit(L.Dense(m.out_features, bias=m.bias is not None))
            asg = {"kernel": m.weight.detach().numpy().T}
            if m.bias is not None:
                asg["bias"] = m.bias.detach().numpy()
            weights[id(lyr)] = asg
        elif isinstance(m, nn.Conv2d):
            if m.padding_mode != "zeros":
                raise NotImplementedError(
                    f"Conv2d padding_mode={m.padding_mode!r}; only "
                    "'zeros' imports exactly")
            pad = _pair(m.padding) if not isinstance(m.padding, str) \
                else m.padding
            if pad not in ("same", "valid") and any(pad):
                emit(L.ZeroPadding2D(padding=pad, dim_ordering="th"))
                border = "valid"
            else:
                border = pad if isinstance(pad, str) else "valid"
            lyr = emit(L.Convolution2D(
                m.out_channels, *_pair(m.kernel_size),
                subsample=_pair(m.stride), border_mode=border,
                dilation=_pair(m.dilation), dim_ordering="th",
                groups=m.groups, bias=m.bias is not None))
            # torch grouped weight (O, I/g, kH, kW) transposes to the
            # grouped HWIO layout (kH, kW, I/g, O) the same way
            # torch (O, I, kH, kW) → HWIO
            asg = {"kernel":
                   m.weight.detach().numpy().transpose(2, 3, 1, 0)}
            if m.bias is not None:
                asg["bias"] = m.bias.detach().numpy()
            weights[id(lyr)] = asg
        elif isinstance(m, (nn.MaxPool2d, nn.AvgPool2d)):
            ceil_extra = (0, 0)
            if getattr(m, "ceil_mode", False):
                # MaxPool ceil_mode: with the running shape known, the
                # ceil windows exist iff we extend the right/bottom
                # -inf padding so floor pooling yields them (torch
                # drops windows starting entirely in the right pad)
                if shape["cur"] is None or len(shape["cur"]) != 3:
                    raise NotImplementedError(
                        "pooling ceil_mode=True needs a tracked "
                        "running shape (lost at "
                        f"{shape.get('lost_at', 'non-3D input')})")
                kh, kw = _pair(m.kernel_size)
                sh_, sw_ = _pair(m.stride if m.stride is not None
                                 else m.kernel_size)
                ph_, pw_ = _pair(m.padding)
                from analytics_zoo_tpu.common.utils import \
                    ceil_pool_extra
                ceil_extra = tuple(
                    ceil_pool_extra(dim, k, s_, p_, p_)
                    for dim, k, s_, p_ in (
                        (shape["cur"][1], kh, sh_, ph_),
                        (shape["cur"][2], kw, sw_, pw_)))
                if isinstance(m, nn.AvgPool2d) and any(ceil_extra):
                    # ceil genuinely adds windows; their avg divisor
                    # excludes the ceil extension — no pad rewrite
                    raise NotImplementedError(
                        "AvgPool2d ceil_mode=True with ceil-extended "
                        "windows (divisor excludes the extension); "
                        "harmless ceil_mode (ceil==floor) imports")
            if getattr(m, "dilation", 1) not in (1, (1, 1)):
                raise NotImplementedError("dilated torch MaxPool2d")
            if isinstance(m, nn.AvgPool2d) and \
                    getattr(m, "divisor_override", None) is not None:
                raise NotImplementedError(
                    "AvgPool2d divisor_override (fixed divisor "
                    "replaces the kernel-area average)")
            pad = _pair(m.padding)
            if any(pad):
                if isinstance(m, nn.AvgPool2d):
                    if not getattr(m, "count_include_pad", True):
                        raise NotImplementedError(
                            "padded torch AvgPool2d with "
                            "count_include_pad=False (per-window "
                            "divisor varies)")
                    # count_include_pad=True (the torch default):
                    # avg over the window INCLUDING pad zeros ==
                    # explicit zero pad + valid average — exact
                    emit(L.ZeroPadding2D(padding=pad,
                                         dim_ordering="th"))
                else:
                    # torch MaxPool pads implicitly with -inf, NOT
                    # zeros: a window of all-negative activations must
                    # keep its true max, so pad with the dtype floor
                    emit(L.ZeroPadding2D(
                        padding=((pad[0], pad[0] + ceil_extra[0]),
                                 (pad[1], pad[1] + ceil_extra[1])),
                        dim_ordering="th", value=float("-inf")))
                    ceil_extra = (0, 0)
            if any(ceil_extra):   # ceil windows without base padding
                emit(L.ZeroPadding2D(
                    padding=((0, ceil_extra[0]), (0, ceil_extra[1])),
                    dim_ordering="th", value=float("-inf")))
            cls = (L.MaxPooling2D if isinstance(m, nn.MaxPool2d)
                   else L.AveragePooling2D)
            stride = m.stride if m.stride is not None \
                else m.kernel_size
            emit(cls(pool_size=_pair(m.kernel_size),
                     strides=_pair(stride), dim_ordering="th"))
        elif isinstance(m, nn.AdaptiveAvgPool2d):
            out_hw = (_pair(m.output_size)
                      if m.output_size is not None else (None, None))
            if None in out_hw:
                raise NotImplementedError(
                    "AdaptiveAvgPool2d with a None output dim "
                    "(keep-input-size) is not supported")
            if out_hw == (1, 1):
                emit(L.GlobalAveragePooling2D(dim_ordering="th"))
            elif shape["cur"] is not None and len(shape["cur"]) == 3:
                in_h, in_w = shape["cur"][1], shape["cur"][2]
                if in_h % out_hw[0] or in_w % out_hw[1]:
                    raise NotImplementedError(
                        f"AdaptiveAvgPool2d {out_hw} from "
                        f"({in_h},{in_w}): non-divisible adaptive "
                        "windows (torch uses variable window sizes)")
                kh, kw = in_h // out_hw[0], in_w // out_hw[1]
                emit(L.AveragePooling2D(pool_size=(kh, kw),
                                        strides=(kh, kw),
                                        dim_ordering="th"))
            else:
                raise NotImplementedError(
                    "AdaptiveAvgPool2d with output_size>1 needs the "
                    "running shape, which was lost at "
                    f"{shape.get('lost_at', 'a non-3D input_shape')}")
        elif isinstance(m, (nn.BatchNorm1d, nn.BatchNorm2d)):
            if m.running_mean is None:
                raise NotImplementedError(
                    "BatchNorm with track_running_stats=False (eval "
                    "semantics differ: batch stats vs moving stats)")
            affine = m.weight is not None
            ordering = "th" if isinstance(m, nn.BatchNorm2d) else "tf"
            lyr = emit(L.BatchNormalization(
                epsilon=m.eps, momentum=1.0 - (m.momentum or 0.1),
                dim_ordering=ordering, scale=affine, center=affine))
            asg = {"_state": {
                "moving_mean": m.running_mean.detach().numpy(),
                "moving_var": m.running_var.detach().numpy(),
            }}
            if affine:
                asg["gamma"] = m.weight.detach().numpy()
                asg["beta"] = m.bias.detach().numpy()
            weights[id(lyr)] = asg
        elif isinstance(m, nn.LayerNorm):
            if m.weight is None:
                raise NotImplementedError(
                    "LayerNorm with elementwise_affine=False")
            lyr = emit(L.LayerNormalization(epsilon=m.eps))
            weights[id(lyr)] = {
                "gamma": m.weight.detach().numpy(),
                "beta": m.bias.detach().numpy(),
            }
        elif isinstance(m, nn.Embedding):
            lyr = emit(L.Embedding(m.num_embeddings, m.embedding_dim))
            weights[id(lyr)] = {
                "embeddings": m.weight.detach().numpy()}
        elif isinstance(m, nn.Flatten):
            emit(L.Flatten())
        elif isinstance(m, nn.Dropout):
            emit(L.Dropout(m.p))
        elif isinstance(m, nn.ReLU):
            emit(L.Activation("relu"))
        elif isinstance(m, nn.Sigmoid):
            emit(L.Activation("sigmoid"))
        elif isinstance(m, nn.Tanh):
            emit(L.Activation("tanh"))
        elif isinstance(m, nn.GELU):
            emit(L.Activation("gelu"))
        elif isinstance(m, nn.SiLU):
            emit(L.Activation("silu" if _has_act("silu") else "swish"))
        elif isinstance(m, nn.Softmax):
            emit(L.Activation("softmax"))
        elif isinstance(m, nn.LeakyReLU):
            emit(L.LeakyReLU(alpha=m.negative_slope))
        elif isinstance(m, nn.ELU):
            emit(L.ELU(alpha=m.alpha))
        else:
            raise NotImplementedError(
                f"no zoo mapping for torch module {type(m).__name__}; "
                "export to ONNX and use OnnxLoader for full coverage")

    # resolve id()-keyed weights to final canonical layer names AFTER
    # Sequential renames them — caller builds the Sequential, so defer
    # by returning a name map bound late
    return zoo_layers, _LateNameMap(zoo_layers, weights)


def _has_act(name: str) -> bool:
    from analytics_zoo_tpu.ops import activations
    try:
        return activations.get(name) is not None
    except Exception:
        return False


class _LateNameMap:
    """Maps layer-id-keyed weight assignments to layer NAMES lazily —
    Sequential canonicalizes names at add() time, after construction."""

    def __init__(self, layers, by_id):
        self._layers = layers
        self._by_id = by_id

    def items(self):
        for lyr in self._layers:
            if id(lyr) in self._by_id:
                yield lyr.name, self._by_id[id(lyr)]

    def __len__(self):
        return len(self._by_id)
