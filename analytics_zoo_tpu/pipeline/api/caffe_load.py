"""Caffe model importer (prototxt + caffemodel → native Sequential).

Reference: ``Net.loadCaffe(defPath, modelPath)``
(`Z/pipeline/api/Net.scala:130-146`) loads Caffe nets via BigDL's
converter; the round-1 gap was an outright `NotImplementedError`
(VERDICT round-1 missing item 2). This importer is self-contained:

- a protobuf TEXT-format parser for the ``.prototxt`` architecture
  (subset: scalars, strings, enums, nested blocks, repeated fields);
- a binary ``NetParameter`` codec (on the shared proto base) for the
  ``.caffemodel`` weights, matched to layers by name (V2 ``layer`` and
  V1 ``layers`` both handled);
- layer mapping onto the native Keras API in channels-first layout
  (Caffe is NCHW): Convolution, InnerProduct, Pooling, ReLU/Sigmoid/
  TanH/Softmax, Dropout, BatchNorm(+Scale), Input.

Tested against the reference's own fixtures
(`pyzoo/test/zoo/resources/test.{prototxt,caffemodel}`,
`zoo/src/test/resources/models/caffe/test_persist.*`).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.pipeline.api.onnx.onnx_pb import (
    Message, _MESSAGE_TYPES)


# -- binary caffemodel schema -------------------------------------------------

class BlobShape(Message):
    FIELDS = {1: ("dim", "int64", True)}


class BlobProto(Message):
    FIELDS = {
        1: ("num", "int64", False),
        2: ("channels", "int64", False),
        3: ("height", "int64", False),
        4: ("width", "int64", False),
        5: ("data", "float", True),
        7: ("shape", "BlobShape", False),
        9: ("double_data", "double", True),
    }

    def to_numpy(self) -> np.ndarray:
        data = (np.asarray(self.double_data, np.float64)
                if self.double_data else
                np.asarray(self.data, np.float32))
        if self.shape is not None and self.shape.dim:
            return data.reshape([int(d) for d in self.shape.dim])
        legacy = [self.num, self.channels, self.height, self.width]
        if any(v is not None for v in legacy):
            shape = [int(v) for v in legacy if v is not None]
            try:
                return data.reshape(shape)
            except ValueError:
                pass
        return data


class CaffeLayerParameter(Message):
    FIELDS = {
        1: ("name", "string", False),
        2: ("type", "string", False),
        3: ("bottom", "string", True),
        4: ("top", "string", True),
        7: ("blobs", "BlobProto", True),
    }


class CaffeV1LayerParameter(Message):
    # V1 (caffe.proto): bottom=2, top=3, name=4, type(enum)=5, blobs=6
    FIELDS = {
        2: ("bottom", "string", True),
        3: ("top", "string", True),
        4: ("name", "string", False),
        5: ("type", "int64", False),
        6: ("blobs", "BlobProto", True),
    }


class NetParameter(Message):
    FIELDS = {
        1: ("name", "string", False),
        2: ("layers", "CaffeV1LayerParameter", True),  # V1
        3: ("input", "string", True),
        4: ("input_dim", "int64", True),
        8: ("input_shape", "BlobShape", True),
        100: ("layer", "CaffeLayerParameter", True),   # V2
    }


_MESSAGE_TYPES.update({
    "BlobShape": BlobShape,
    "BlobProto": BlobProto,
    "CaffeLayerParameter": CaffeLayerParameter,
    "CaffeV1LayerParameter": CaffeV1LayerParameter,
    "NetParameter": NetParameter,
})

# V1 LayerType enum values → V2 type strings (subset); binary protos
# carry the int, text prototxts the UPPERCASE enum identifier
_V1_TYPES = {
    4: "Convolution", 14: "InnerProduct", 17: "Pooling", 18: "ReLU",
    19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss", 23: "TanH",
    6: "Dropout", 5: "Data", 8: "Flatten", 15: "LRN",
}
_V1_NAME_TYPES = {
    "CONVOLUTION": "Convolution", "INNER_PRODUCT": "InnerProduct",
    "POOLING": "Pooling", "RELU": "ReLU", "SIGMOID": "Sigmoid",
    "SOFTMAX": "Softmax", "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "TANH": "TanH", "DROPOUT": "Dropout", "DATA": "Data",
    "FLATTEN": "Flatten", "LRN": "LRN",
}


# -- prototxt text-format parser ----------------------------------------------

_TOKEN = re.compile(
    r'\s*(?:(#[^\n]*)|([A-Za-z_][A-Za-z0-9_]*)|("(?:[^"\\]|\\.)*")'
    r"|([{}:])|([^\s{}:#]+))")


def _tokenize(text: str):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None or m.end() == pos:
            break
        pos = m.end()
        comment, ident, string, punct, other = m.groups()
        if comment:
            continue
        if ident is not None:
            yield ident
        elif string is not None:
            yield ("STR", string[1:-1])
        elif punct is not None:
            yield punct
        elif other is not None:
            yield ("VAL", other)


def parse_prototxt(text: str) -> "Dict[str, list]":
    """Protobuf text format → {field: [values]} with nested dicts for
    blocks. Every field is a list (repeated-friendly)."""
    tokens = list(_tokenize(text))
    pos = 0

    def block():
        nonlocal pos
        out: Dict[str, list] = {}
        while pos < len(tokens) and tokens[pos] != "}":
            key = tokens[pos]
            if not isinstance(key, str):
                raise ValueError(f"prototxt parse error near {key!r}")
            pos += 1
            if pos < len(tokens) and tokens[pos] == ":":
                pos += 1
                tok = tokens[pos]
                pos += 1
                if isinstance(tok, tuple):
                    kind, raw = tok
                    value = raw if kind == "STR" else _coerce(raw)
                else:
                    value = _coerce(tok)  # enum identifier
                out.setdefault(key, []).append(value)
            elif pos < len(tokens) and tokens[pos] == "{":
                pos += 1
                value = block()
                if pos >= len(tokens) or tokens[pos] != "}":
                    raise ValueError("prototxt: unbalanced braces")
                pos += 1
                out.setdefault(key, []).append(value)
            else:
                raise ValueError(f"prototxt parse error after {key!r}")
        return out

    def _coerce(raw: str):
        for cast in (int, float):
            try:
                return cast(raw)
            except (TypeError, ValueError):
                continue
        if raw in ("true", "false"):
            return raw == "true"
        return raw

    return block()


def _one(d: dict, key: str, default=None):
    v = d.get(key)
    return v[0] if v else default


# -- importer -----------------------------------------------------------------

def load_caffe(def_path: str, model_path: Optional[str] = None,
               input_shape: Optional[Tuple[int, ...]] = None):
    """(reference `Net.loadCaffe`, Net.scala:130) → native Sequential,
    channels-first. ``model_path`` may be omitted for a weights-free
    architecture load (random init)."""
    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    with open(def_path) as f:
        net_def = parse_prototxt(f.read())

    blobs_by_name: Dict[str, List[np.ndarray]] = {}
    if model_path is not None:
        with open(model_path, "rb") as f:
            weights = NetParameter()
            weights.ParseFromString(f.read())
        for lyr in list(weights.layer) + list(weights.layers):
            if lyr.blobs:
                blobs_by_name[lyr.name] = [b.to_numpy()
                                           for b in lyr.blobs]

    # input shape: explicit arg > input_shape block > input_dim
    if input_shape is None:
        ishape = net_def.get("input_shape")
        if ishape:
            dims = ishape[0].get("dim", [])
            input_shape = tuple(int(d) for d in dims[1:])
        elif net_def.get("input_dim"):
            input_shape = tuple(int(d)
                                for d in net_def["input_dim"][1:])

    layer_defs = net_def.get("layer") or net_def.get("layers") or []
    converted: List[Tuple[Any, Dict[str, np.ndarray]]] = []
    flattened = False

    for ld in layer_defs:
        lname = _one(ld, "name")
        ltype = _one(ld, "type")
        if isinstance(ltype, int):
            ltype = _V1_TYPES.get(ltype, str(ltype))
        elif isinstance(ltype, str) and ltype in _V1_NAME_TYPES:
            ltype = _V1_NAME_TYPES[ltype]  # V1 text-format enum name
        blobs = blobs_by_name.get(lname, [])
        if ltype in ("Input", "Data", "DummyData"):
            p = _one(ld, "input_param")
            if input_shape is None and p:
                dims = _one(p, "shape", {}).get("dim", [])
                input_shape = tuple(int(d) for d in dims[1:])
            continue

        if ltype == "Convolution":
            p = _one(ld, "convolution_param", {})
            n_out = _one(p, "num_output")
            kh = _one(p, "kernel_h", _one(p, "kernel_size"))
            kw = _one(p, "kernel_w", _one(p, "kernel_size"))
            sh = _one(p, "stride_h", _one(p, "stride", 1))
            sw = _one(p, "stride_w", _one(p, "stride", 1))
            ph = _one(p, "pad_h", _one(p, "pad", 0))
            pw = _one(p, "pad_w", _one(p, "pad", 0))
            groups = int(_one(p, "group", 1))
            if ph or pw:
                converted.append((L.ZeroPadding2D(
                    padding=(ph, pw), dim_ordering="th"), {}))
            bias_term = _one(p, "bias_term", True)
            ws: Dict[str, np.ndarray] = {}
            if blobs:
                # legacy blobs may carry sparse dims; the prototxt pins
                # (out, kh, kw), leaving in_channels = size/(out*kh*kw)
                w = blobs[0].reshape(int(n_out), -1, int(kh), int(kw))
                ws["kernel"] = np.ascontiguousarray(
                    np.transpose(w, (2, 3, 1, 0)))  # OIHW → HWIO
                if bias_term and len(blobs) > 1:
                    ws["bias"] = blobs[1].reshape(-1)
            converted.append((L.Convolution2D(
                n_out, (kh, kw), subsample=(sh, sw),
                border_mode="valid", dim_ordering="th", groups=groups,
                bias=bool(bias_term), name=lname), ws))
        elif ltype == "InnerProduct":
            p = _one(ld, "inner_product_param", {})
            n_out = _one(p, "num_output")
            bias_term = _one(p, "bias_term", True)
            if not flattened:
                converted.append((L.Flatten(), {}))
                flattened = True
            ws = {}
            if blobs:
                w = blobs[0].reshape(int(n_out), -1)
                ws["kernel"] = np.ascontiguousarray(w.T)
                if bias_term and len(blobs) > 1:
                    ws["bias"] = blobs[1].reshape(-1)
            converted.append((L.Dense(
                n_out, bias=bool(bias_term), name=lname), ws))
        elif ltype == "Pooling":
            p = _one(ld, "pooling_param", {})
            pool = _one(p, "pool", "MAX")
            k = _one(p, "kernel_size", 2)
            kh = _one(p, "kernel_h", k)
            kw = _one(p, "kernel_w", k)
            s = _one(p, "stride", 1)  # caffe PoolingParameter default
            sh = _one(p, "stride_h", s)
            sw = _one(p, "stride_w", s)
            if _one(p, "global_pooling", False):
                cls = (L.GlobalMaxPooling2D if pool == "MAX"
                       else L.GlobalAveragePooling2D)
                converted.append((cls(dim_ordering="th", name=lname),
                                  {}))
                continue
            if _one(p, "pad", 0) or _one(p, "pad_h", 0) or \
                    _one(p, "pad_w", 0):
                raise NotImplementedError(
                    "padded Caffe pooling not supported")
            cls = (L.MaxPooling2D if pool == "MAX"
                   else L.AveragePooling2D)
            converted.append((cls(pool_size=(kh, kw), strides=(sh, sw),
                                  dim_ordering="th", name=lname), {}))
        elif ltype in ("ReLU", "Sigmoid", "TanH", "Softmax",
                       "SoftmaxWithLoss", "ELU"):
            act = {"ReLU": "relu", "Sigmoid": "sigmoid",
                   "TanH": "tanh", "Softmax": "softmax",
                   "SoftmaxWithLoss": "softmax", "ELU": "elu"}[ltype]
            converted.append((L.Activation(act, name=lname), {}))
        elif ltype == "Dropout":
            p = _one(ld, "dropout_param", {})
            converted.append((L.Dropout(
                _one(p, "dropout_ratio", 0.5), name=lname), {}))
        elif ltype == "BatchNorm":
            p = _one(ld, "batch_norm_param", {})
            eps = _one(p, "eps", 1e-5)
            lyr = L.BatchNormalization(
                epsilon=eps, dim_ordering="th", scale=False,
                center=False, name=lname)
            ws = {}
            if len(blobs) >= 3:
                scale = float(blobs[2].reshape(-1)[0]) or 1.0
                ws["_state"] = {
                    "moving_mean": blobs[0].reshape(-1) / scale,
                    "moving_var": blobs[1].reshape(-1) / scale,
                }
            converted.append((lyr, ws))
        elif ltype == "Scale":
            lyr = L.BatchNormalization(
                epsilon=0.0, dim_ordering="th", name=lname)
            ws = {}
            if blobs:
                ws["gamma"] = blobs[0].reshape(-1)
                if len(blobs) > 1:
                    ws["beta"] = blobs[1].reshape(-1)
                n = blobs[0].size
                ws["_state"] = {
                    "moving_mean": np.zeros((n,), np.float32),
                    "moving_var": np.ones((n,), np.float32),
                }
            converted.append((lyr, ws))
        elif ltype == "Flatten":
            converted.append((L.Flatten(name=lname), {}))
            flattened = True
        else:
            raise NotImplementedError(
                f"Caffe layer type {ltype!r} has no TPU import mapping")

    if not converted:
        raise ValueError(f"{def_path}: no importable layers")
    if input_shape is None:
        raise ValueError("input_shape required (prototxt declares no "
                         "input dims)")

    from analytics_zoo_tpu.pipeline.api._import_common import \
        build_sequential
    return build_sequential(converted, input_shape, "load_caffe")
