"""Shared machinery for external-model importers (BigDL, Caffe):
build a native Sequential from converted layers and install the saved
weights after shape inference."""

from __future__ import annotations

from typing import Tuple

import numpy as np

from analytics_zoo_tpu.common.nncontext import logger


def assign_param(sub: dict, key: str, value, name: str) -> None:
    if key not in sub:
        raise KeyError(f"imported layer {name} has no param {key!r}")
    if tuple(sub[key].shape) != tuple(np.shape(value)):
        raise ValueError(
            f"{name}.{key}: saved shape {tuple(np.shape(value))} does "
            f"not match model {tuple(sub[key].shape)}")
    sub[key] = np.asarray(value, np.float32)


def build_sequential(converted: "Sequence[Tuple[object, Dict]]",
                     input_shape: Tuple[int, ...], origin: str):
    """(layer, weights) pairs → compiled Sequential with the saved
    weights installed (same install contract as Net.load_torch:
    shape-checked assignment into the initialized param tree, then
    re-sharded)."""
    from analytics_zoo_tpu.pipeline.api.keras.models import Sequential

    net = Sequential()
    first = True
    for lyr, _ in converted:
        if first:
            lyr._given_input_shape = tuple(input_shape)
            first = False
        net.add(lyr)
    net.compile(optimizer="sgd", loss="mse")
    est = net.estimator
    est._ensure_initialized()

    import jax
    params = jax.device_get(est.params)
    n_assigned = 0
    for lyr, ws in converted:
        if not ws:
            continue
        sub = params[lyr.name]
        for key, value in ws.items():
            if key == "_state":
                for sk, sv in value.items():
                    assign_param(sub["_state"], sk, sv, lyr.name)
                    n_assigned += 1
            else:
                assign_param(sub, key, value, lyr.name)
                n_assigned += 1
    from analytics_zoo_tpu.parallel.mesh import shard_params
    est.params = shard_params(params, est.ctx.mesh)
    est._train_step = None
    est._predict_fn = None
    logger.info("%s: imported %d layers, %d weight tensors",
                origin, len(converted), n_assigned)
    return net
