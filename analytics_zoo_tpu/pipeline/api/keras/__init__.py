from analytics_zoo_tpu.pipeline.api.keras import layers
from analytics_zoo_tpu.pipeline.api.keras.engine import (
    KerasLayer,
    Input,
    Variable,
)
from analytics_zoo_tpu.pipeline.api.keras.models import Sequential, Model

__all__ = ["KerasLayer", "Input", "Variable", "Sequential", "Model",
           "layers"]
