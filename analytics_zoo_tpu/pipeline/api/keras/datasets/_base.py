"""Shared helpers for the offline-capable dataset loaders."""

from __future__ import annotations

import os

import numpy as np

from analytics_zoo_tpu.common.nncontext import logger

DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".zoo", "dataset")


def cache_path(dest_dir: str, name: str) -> str:
    return os.path.join(os.path.expanduser(dest_dir), name)


_LEGACY_DIR = "/tmp/.zoo/dataset"


def synthetic_notice(dataset: str, why: str) -> None:
    legacy = ""
    # never READ the world-writable legacy location (ADVICE r2), but
    # do tell users their old cache needs moving to the per-user dir
    if os.path.isdir(_LEGACY_DIR):
        legacy = (f" NOTE: a legacy cache dir exists at {_LEGACY_DIR}; "
                  f"it is no longer read — move your files to "
                  f"{DEFAULT_DIR} (after verifying you created them).")
    logger.warning(
        "datasets.%s: %s — generating a deterministic SYNTHETIC "
        "stand-in (real shapes/dtypes, fake content). Place the "
        "reference cache file locally to use real data.%s",
        dataset, why, legacy)


def synthetic_sequences(n, vocab, seed, mean_len=120, max_len=400):
    """Ragged int index sequences like the imdb/reuters pickles."""
    rs = np.random.RandomState(seed)
    lengths = np.clip(rs.poisson(mean_len, size=n), 8, max_len)
    # skewed unigram distribution: low indices frequent, like
    # frequency-ordered word indices
    return [list(np.minimum(
        rs.zipf(1.3, size=int(ln)) + 3, vocab - 1).astype(np.int64))
        for ln in lengths]


def apply_nb_words(seqs, nb_words, oov_char):
    """The reference's vocabulary truncation contract
    (`imdb.py:40-76`): indices >= nb_words become ``oov_char``, or are
    dropped when ``oov_char`` is None."""
    if nb_words is None:
        return seqs
    if oov_char is not None:
        return [[w if w < nb_words else oov_char for w in s]
                for s in seqs]
    return [[w for w in s if w < nb_words] for s in seqs]
