"""Keras-style dataset loaders (reference:
`pyzoo/zoo/pipeline/api/keras/datasets/{mnist,imdb,reuters,
boston_housing}.py`).

TPU-first redesign: the reference's loaders download from public
mirrors via `bigdl.dataset.base.maybe_download`. TPU pods commonly run
with no egress, so each loader here resolves in order:

1. a local cache file in ``dest_dir`` (the SAME on-disk formats the
   reference caches: MNIST idx-gzip, ``boston_housing.npz``,
   pickled/npz index sequences) — drop files in place and they are
   used;
2. otherwise a small deterministic synthetic dataset with the real
   shapes/dtypes/label ranges (seeded; clearly logged) so examples and
   tests run offline.

Every ``load_data`` returns ``(x_train, y_train), (x_test, y_test)``
with the reference's dtypes.
"""

from analytics_zoo_tpu.pipeline.api.keras.datasets import (  # noqa: F401
    boston_housing, imdb, mnist, reuters)

__all__ = ["mnist", "imdb", "reuters", "boston_housing"]
