"""Boston-housing regression loader (reference
`P/pipeline/api/keras/datasets/boston_housing.py`).

Reads the standard ``boston_housing.npz`` (keys ``x``, ``y``) when
present, else a seeded synthetic stand-in with the real 13-feature
shape. Same seeded shuffle + split contract as the reference
(`boston_housing.py:45-76`).
"""

from __future__ import annotations

import os

import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.datasets._base import (
    DEFAULT_DIR, cache_path, synthetic_notice)


def load_data(path="boston_housing.npz", dest_dir=DEFAULT_DIR,
              test_split=0.2):
    full = cache_path(dest_dir, path)
    if os.path.exists(full):
        with np.load(full, allow_pickle=False) as f:
            x, y = f["x"], f["y"]
    else:
        synthetic_notice("boston_housing", f"no cache at {full}")
        rs = np.random.RandomState(30)
        x = rs.rand(506, 13).astype(np.float64) * [100] * 13
        w = rs.randn(13)
        y = (x @ w / 50 + rs.randn(506) * 2 + 22).astype(np.float64)
    rs = np.random.RandomState(seed=113)          # reference seed
    idx = rs.permutation(len(x))
    x, y = x[idx], y[idx]
    n_test = int(len(x) * test_split)
    return ((x[n_test:], y[n_test:]), (x[:n_test], y[:n_test]))
