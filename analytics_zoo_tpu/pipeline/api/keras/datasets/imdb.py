"""IMDB sentiment loader (reference
`P/pipeline/api/keras/datasets/imdb.py`).

Reads the reference's cached ``imdb_full.pkl`` (a pickled
``((x_train, y_train), (x_test, y_test))`` of index sequences) when
present, else a seeded synthetic stand-in. ``nb_words``/``oov_char``
follow the reference's truncation contract (`imdb.py:40-76`).
"""

from __future__ import annotations

import os

import numpy as np

from analytics_zoo_tpu.common.safe_pickle import CheckedUnpickler
from analytics_zoo_tpu.pipeline.api.keras.datasets._base import (
    DEFAULT_DIR, apply_nb_words, cache_path, synthetic_notice,
    synthetic_sequences)

_VOCAB = 20000


def load_data(dest_dir=DEFAULT_DIR, nb_words=None, oov_char=2):
    path = cache_path(dest_dir, "imdb_full.pkl")
    if os.path.exists(path):
        with open(path, "rb") as f:
            # lists/ints only — the checked unpickler rejects anything
            # with a reduce gadget
            (x_train, y_train), (x_test, y_test) = \
                CheckedUnpickler(f).load()
    else:
        synthetic_notice("imdb", f"no cache at {path}")
        x_train = synthetic_sequences(512, _VOCAB, seed=10)
        x_test = synthetic_sequences(128, _VOCAB, seed=11)
        rs = np.random.RandomState(12)
        y_train = list(rs.randint(0, 2, size=len(x_train)))
        y_test = list(rs.randint(0, 2, size=len(x_test)))
    x_train = apply_nb_words(x_train, nb_words, oov_char)
    x_test = apply_nb_words(x_test, nb_words, oov_char)
    return (x_train, y_train), (x_test, y_test)
