"""MNIST loader (reference `P/pipeline/api/keras/datasets/mnist.py`).

Reads the standard idx-gzip cache files when present (same names the
reference downloads: ``train-images-idx3-ubyte.gz`` etc.), else a
seeded synthetic stand-in. Normalization constants match the
reference (`mnist.py:24-27`).
"""

from __future__ import annotations

import gzip
import os

import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.datasets._base import (
    DEFAULT_DIR, synthetic_notice,
)

TRAIN_MEAN = 0.13066047740239506 * 255
TRAIN_STD = 0.3081078 * 255
TEST_MEAN = 0.13251460696903547 * 255
TEST_STD = 0.31048024 * 255

_FILES = {
    "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz",
              60000),
    "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz",
             10000),
}


def _read32(stream):
    return np.frombuffer(stream.read(4),
                         np.dtype(np.uint32).newbyteorder(">"))[0]


def extract_images(f):
    """idx3 gzip → uint8 (n, 28, 28, 1) (reference `mnist.py:35-56`)."""
    with gzip.GzipFile(fileobj=f) as s:
        if _read32(s) != 2051:
            raise ValueError(f"bad magic in MNIST image file {f.name}")
        n, rows, cols = _read32(s), _read32(s), _read32(s)
        data = np.frombuffer(s.read(int(rows * cols * n)), np.uint8)
        return data.reshape(int(n), int(rows), int(cols), 1)


def extract_labels(f):
    with gzip.GzipFile(fileobj=f) as s:
        if _read32(s) != 2049:
            raise ValueError(f"bad magic in MNIST label file {f.name}")
        n = _read32(s)
        return np.frombuffer(s.read(int(n)), np.uint8)


def _synthetic(n, seed):
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 10, size=n).astype(np.uint8)
    # blobby per-class patterns so a model can actually fit them
    base = rs.rand(10, 28, 28, 1) * 255
    x = base[y] * (0.6 + 0.4 * rs.rand(n, 28, 28, 1))
    return x.astype(np.uint8), y


def read_data_sets(train_dir, data_type="train"):
    """(features uint8 (n,28,28,1), labels uint8 (n,)) — reference
    `mnist.py:74-120` contract."""
    img_name, lbl_name, n = _FILES[data_type]
    img_path = os.path.join(train_dir, img_name)
    lbl_path = os.path.join(train_dir, lbl_name)
    if os.path.exists(img_path) and os.path.exists(lbl_path):
        with open(img_path, "rb") as f:
            images = extract_images(f)
        with open(lbl_path, "rb") as f:
            labels = extract_labels(f)
        return images, labels
    synthetic_notice("mnist", f"no cache at {img_path}")
    return _synthetic(min(n, 2048), seed=0 if data_type == "train"
                      else 1)


def load_data(location=os.path.join(DEFAULT_DIR, "mnist")):
    x_train, y_train = read_data_sets(location, "train")
    x_test, y_test = read_data_sets(location, "test")
    return (x_train, y_train), (x_test, y_test)
