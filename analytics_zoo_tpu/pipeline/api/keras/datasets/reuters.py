"""Reuters newswire topic loader (reference
`P/pipeline/api/keras/datasets/reuters.py`).

Reads a cached ``reuters.npz``/``reuters.pkl`` when present, else a
seeded synthetic stand-in with the dataset's 46 topic classes.
``test_split`` partitions the training set like the reference
(`reuters.py:40-78`).
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from analytics_zoo_tpu.common.safe_pickle import CheckedUnpickler
from analytics_zoo_tpu.pipeline.api.keras.datasets._base import (
    DEFAULT_DIR, apply_nb_words, cache_path, synthetic_notice,
    synthetic_sequences)

_VOCAB = 30980
_CLASSES = 46


def load_data(dest_dir=DEFAULT_DIR, nb_words=None, oov_char=2,
              test_split=0.2):
    npz = cache_path(dest_dir, "reuters.npz")
    pkl = cache_path(dest_dir, "reuters.pkl")
    xs = None
    bad_npz = False
    if os.path.exists(npz):
        # Ragged sequences are stored flat (x_flat) + offsets (x_off)
        # so the npz never contains object arrays and loads with
        # allow_pickle=False — object-array caches would need
        # unrestricted pickle, which the repo's CheckedUnpickler
        # policy forbids.
        try:
            with np.load(npz, allow_pickle=False) as f:
                flat, off = f["x_flat"], f["x_off"]
                xs = [list(flat[off[i]:off[i + 1]])
                      for i in range(len(off) - 1)]
                ys = list(f["y"])
        except (KeyError, ValueError):
            bad_npz = True
            xs = None
    if bad_npz:
        from analytics_zoo_tpu.common.nncontext import logger
        logger.warning(
            "datasets.reuters: cache %s is not in the flat+offsets "
            "format and was ignored; re-save it with "
            "x_flat=concat(seqs), x_off=cumsum([0]+lengths), y=labels "
            "(legacy object-array caches can be converted from the "
            "reuters.pkl via CheckedUnpickler)", npz)
    if xs is None and os.path.exists(pkl):
        with open(pkl, "rb") as f:
            xs, ys = CheckedUnpickler(f).load()
    if xs is None:
        if not bad_npz:
            synthetic_notice("reuters", f"no cache at {npz}")
        xs = synthetic_sequences(640, _VOCAB, seed=20, mean_len=80)
        ys = list(np.random.RandomState(21).randint(
            0, _CLASSES, size=len(xs)))
    xs = apply_nb_words(xs, nb_words, oov_char)
    n_test = int(len(xs) * test_split)
    x_train, y_train = xs[n_test:], ys[n_test:]
    x_test, y_test = xs[:n_test], ys[:n_test]
    return (x_train, y_train), (x_test, y_test)
