"""Reuters newswire topic loader (reference
`P/pipeline/api/keras/datasets/reuters.py`).

Reads a cached ``reuters.npz``/``reuters.pkl`` when present, else a
seeded synthetic stand-in with the dataset's 46 topic classes.
``test_split`` partitions the training set like the reference
(`reuters.py:40-78`).
"""

from __future__ import annotations

import io
import os
import zipfile

import numpy as np

from analytics_zoo_tpu.common.safe_pickle import (
    CheckedUnpickler, UnsafePickleError)
from analytics_zoo_tpu.pipeline.api.keras.datasets._base import (
    DEFAULT_DIR, apply_nb_words, cache_path, synthetic_notice,
    synthetic_sequences)

_VOCAB = 30980
_CLASSES = 46


def _load_legacy_npz(path):
    """One-time migration of a legacy object-array ``reuters.npz``
    (the format this repo wrote before the flat+offsets scheme).

    `np.load(allow_pickle=True)` would run unrestricted pickle; an
    object-dtype ``.npy`` member is just a header followed by a pickle
    stream, so the stream is fed through `CheckedUnpickler` instead —
    same whitelist as every other cache this repo reads. Returns
    ``(xs, ys)`` or None if the file is not a legacy cache."""
    from numpy.lib import format as npy_format

    def member(zf, name):
        with zf.open(name) as f:
            version = npy_format.read_magic(f)
            read_header = {          # public per-version readers only
                (1, 0): npy_format.read_array_header_1_0,
                (2, 0): npy_format.read_array_header_2_0,
            }.get(version)
            if read_header is None:
                raise ValueError(f"unsupported npy version {version}")
            _, _, dtype = read_header(f)
            if dtype.hasobject:
                return CheckedUnpickler(f).load()
            f2 = io.BytesIO(zf.read(name))
            return np.lib.format.read_array(f2, allow_pickle=False)

    try:
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
            if not {"x.npy", "y.npy"} <= names:
                return None
            xs = [list(map(int, seq)) for seq in member(zf, "x.npy")]
            ys = [int(v) for v in np.asarray(member(zf, "y.npy"))]
            return xs, ys
    except UnsafePickleError:
        # a security rejection must be distinguishable from a merely
        # stale cache — surface it, don't fold into the format warning
        from analytics_zoo_tpu.common.nncontext import logger
        logger.error(
            "datasets.reuters: legacy cache %s contains a pickle "
            "payload outside the deserialization whitelist — "
            "REFUSING to load it (tampered or foreign file?)", path)
        return None
    except (zipfile.BadZipFile, KeyError, ValueError, TypeError,
            OSError):
        return None


def _save_flat_npz(path, xs, ys):
    off = np.cumsum([0] + [len(s) for s in xs])
    flat = np.concatenate([np.asarray(s, np.int64) for s in xs]) \
        if off[-1] else np.zeros((0,), np.int64)
    tmp = path + ".tmp.npz"  # .npz suffix stops np.savez renaming it
    try:                     # atomic replace: a crash mid-write must
        np.savez(tmp, x_flat=flat, x_off=off,   # not leave a
                 y=np.asarray(ys, np.int64))    # truncated cache
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def load_data(dest_dir=DEFAULT_DIR, nb_words=None, oov_char=2,
              test_split=0.2):
    npz = cache_path(dest_dir, "reuters.npz")
    pkl = cache_path(dest_dir, "reuters.pkl")
    xs = None
    bad_npz = False
    if os.path.exists(npz):
        # Ragged sequences are stored flat (x_flat) + offsets (x_off)
        # so the npz never contains object arrays and loads with
        # allow_pickle=False — object-array caches would need
        # unrestricted pickle, which the repo's CheckedUnpickler
        # policy forbids.
        try:
            with np.load(npz, allow_pickle=False) as f:
                flat, off = f["x_flat"], f["x_off"]
                xs = [list(flat[off[i]:off[i + 1]])
                      for i in range(len(off) - 1)]
                ys = list(f["y"])
        except (KeyError, ValueError, OSError,
                zipfile.BadZipFile):  # truncated/foreign file →
            bad_npz = True            # legacy probe, then synthetic
            xs = None
    if bad_npz:
        from analytics_zoo_tpu.common.nncontext import logger
        legacy = _load_legacy_npz(npz)
        if legacy is not None:
            xs, ys = legacy
            try:             # migrate in place to flat+offsets
                _save_flat_npz(npz, xs, ys)
                logger.info(
                    "datasets.reuters: migrated legacy object-array "
                    "cache %s to the flat+offsets format", npz)
            except OSError:
                pass         # read-only cache dir: converted in memory
        else:
            logger.warning(
                "datasets.reuters: cache %s is not in the flat+offsets "
                "format and was ignored; re-save it with "
                "x_flat=concat(seqs), x_off=cumsum([0]+lengths), "
                "y=labels", npz)
    if xs is None and os.path.exists(pkl):
        with open(pkl, "rb") as f:
            xs, ys = CheckedUnpickler(f).load()
    if xs is None:
        if not bad_npz:
            synthetic_notice("reuters", f"no cache at {npz}")
        xs = synthetic_sequences(640, _VOCAB, seed=20, mean_len=80)
        ys = list(np.random.RandomState(21).randint(
            0, _CLASSES, size=len(xs)))
    xs = apply_nb_words(xs, nb_words, oov_char)
    n_test = int(len(xs) * test_split)
    x_train, y_train = xs[n_test:], ys[n_test:]
    x_test, y_test = xs[:n_test], ys[:n_test]
    return (x_train, y_train), (x_test, y_test)
