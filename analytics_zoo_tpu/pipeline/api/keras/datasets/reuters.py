"""Reuters newswire topic loader (reference
`P/pipeline/api/keras/datasets/reuters.py`).

Reads a cached ``reuters.npz``/``reuters.pkl`` when present, else a
seeded synthetic stand-in with the dataset's 46 topic classes.
``test_split`` partitions the training set like the reference
(`reuters.py:40-78`).
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from analytics_zoo_tpu.common.safe_pickle import CheckedUnpickler
from analytics_zoo_tpu.pipeline.api.keras.datasets._base import (
    DEFAULT_DIR, apply_nb_words, cache_path, synthetic_notice,
    synthetic_sequences)

_VOCAB = 30980
_CLASSES = 46


def load_data(dest_dir=DEFAULT_DIR, nb_words=None, oov_char=2,
              test_split=0.2):
    npz = cache_path(dest_dir, "reuters.npz")
    pkl = cache_path(dest_dir, "reuters.pkl")
    if os.path.exists(npz):
        with np.load(npz, allow_pickle=True) as f:
            xs, ys = list(f["x"]), list(f["y"])
    elif os.path.exists(pkl):
        with open(pkl, "rb") as f:
            xs, ys = CheckedUnpickler(f).load()
    else:
        synthetic_notice("reuters", f"no cache at {npz}")
        xs = synthetic_sequences(640, _VOCAB, seed=20, mean_len=80)
        ys = list(np.random.RandomState(21).randint(
            0, _CLASSES, size=len(xs)))
    xs = apply_nb_words(xs, nb_words, oov_char)
    n_test = int(len(xs) * test_split)
    x_train, y_train = xs[n_test:], ys[n_test:]
    x_test, y_test = xs[:n_test], ys[:n_test]
    return (x_train, y_train), (x_test, y_test)
