"""Containers: `Sequential` and functional `Model` (+ shared `KerasNet`).

Analog of reference `Z/pipeline/api/keras/models/Topology.scala:572-889`
(`Model` graph / `Sequential`). Training methods (`compile/fit/...`) are
attached in `topology.py`; this module is the structural half: parameter
init with Keras-style shape-inference chaining, pure forward, summary.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.engine import (
    KerasLayer, ShapeLike, Variable, _InputLayer,
    collect_layers, topological_order, unique_name,
)


class KerasNet(KerasLayer):
    """Shared container behavior. Containers are layers, so they nest."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(name=name)

    def _canonicalize_names(self, layers: "list[KerasLayer]") -> None:
        """Rename auto-named layers to container-scoped deterministic
        names (`dense_1`, `dense_2`, ... in container order).

        Auto-generated names are process-global counters, so two builds
        of the same architecture get different names; params dicts are
        keyed by name, so checkpoints/save_model would not transfer.
        Scoping the numbering to the container makes names a pure
        function of the architecture. User-provided names are kept.
        Note: a shared layer re-used across two separately-built models
        is renamed by whichever container canonicalizes it last.
        """
        counters: "dict[str, int]" = {}
        for lyr in layers:
            prefix = type(lyr).__name__.lower()
            counters[prefix] = counters.get(prefix, 0) + 1
            if getattr(lyr, "_auto_named", False):
                lyr.name = f"{prefix}_{counters[prefix]}"

    # -- to be provided by subclasses ---------------------------------------
    @property
    def layers(self) -> "list[KerasLayer]":
        raise NotImplementedError

    # -- params -------------------------------------------------------------
    def init_params(self, rng=None,
                    input_shape: Optional[ShapeLike] = None,
                    device=None) -> dict:
        """Build the whole parameter pytree.

        ``rng`` defaults to a key from the process NNContext so plain
        ``model.init_params()`` "just works" after ``init_nncontext()``.

        Init is ~hundreds of tiny eager ops (one per leaf); against a
        remote accelerator each would pay a dispatch round trip, so on
        non-CPU backends the ops run on the host CPU backend and the
        finished pytree transfers in ONE ``device_put`` (the
        remote-TPU analog of the reference's driver-side weight init +
        broadcast). ``device``: a placement target, or ``"host"`` to
        skip the transfer and return the CPU-resident pytree (callers
        that re-place with their own shardings — Estimator — avoid a
        full-replica round trip through device 0 that way).
        """
        import jax

        if rng is None:
            from analytics_zoo_tpu.common.nncontext import get_nncontext
            rng = get_nncontext().next_rng_key()
        try:
            cpu0 = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # no host backend under a platform pin
            cpu0 = None
        if cpu0 is None or (device is None
                            and jax.default_backend() == "cpu"):
            return self.init(rng, input_shape)
        with jax.default_device(cpu0):
            params = self.init(jax.device_put(rng, cpu0), input_shape)
        if device == "host":
            return params
        return jax.device_put(
            params, device if device is not None else jax.devices()[0])

    def forward(self, params: dict, inputs, *, training: bool = False,
                rng=None):
        out, _ = self.apply(params, inputs, training=training, rng=rng)
        return out

    def regularization_loss(self, params: dict):
        loss = jnp.zeros((), jnp.float32)
        for lyr in self.layers:
            sub = params.get(lyr.name, {})
            loss = loss + lyr.regularization_loss(sub)
        return loss

    def trainable_mask(self, params: dict) -> dict:
        """Bool pytree: True where the optimizer should update.

        ``_state`` subtrees (BatchNorm stats) and layers frozen via
        ``trainable=False`` are masked out (reference analog: `freezeUpTo`,
        `NetUtils.scala:47-140`).
        """
        def mask_layer(lyr: KerasLayer, sub: dict) -> Any:
            if isinstance(lyr, KerasNet):
                return {inner.name: mask_layer(inner,
                                               sub.get(inner.name, {}))
                        for inner in lyr.layers if inner.name in sub}
            def mask_sub(node):
                # "_state" subtrees are non-trainable at ANY nesting
                # depth (composite layers like FusedBottleneck keep
                # per-BN state under params["bn1"]["_state"], ...)
                if isinstance(node, dict):
                    return {k: (jax.tree_util.tree_map(
                                    lambda _: False, v)
                                if k == "_state" else mask_sub(v))
                            for k, v in node.items()}
                return jax.tree_util.tree_map(
                    lambda _: bool(lyr.trainable), node)
            out = mask_sub(sub)
            return out
        return {lyr.name: mask_layer(lyr, params.get(lyr.name, {}))
                for lyr in self.layers if lyr.name in params}

    def freeze(self, *layer_names: str) -> "KerasNet":
        """Freeze named layers (all layers if no names given)."""
        targets = set(layer_names)
        for lyr in self.layers:
            if not targets or lyr.name in targets:
                lyr.trainable = False
        return self

    def unfreeze(self, *layer_names: str) -> "KerasNet":
        targets = set(layer_names)
        for lyr in self.layers:
            if not targets or lyr.name in targets:
                lyr.trainable = True
        return self

    # -- training surface (reference `Topology.scala:128-540`:
    #    compile/fit/evaluate/predict + tensorboard/checkpoint/clipping) ----
    def compile(self, optimizer="adam", loss="mse", metrics=None):
        """Configure training (reference `KerasNet.compile`,
        `Topology.scala:128-184`; accepts string names, optimizer objects,
        loss callables incl. `autograd.CustomLoss`). Re-compiling keeps
        already-initialized weights (keras semantics — imported/trained
        params survive an optimizer/loss change)."""
        from analytics_zoo_tpu.pipeline.estimator import (
            Estimator,
            _check_params_compatible,
        )
        old = getattr(self, "_estimator", None)
        self._estimator = Estimator(self, optimizer=optimizer, loss=loss,
                                    metrics=metrics)
        if old is not None and old.params is not None:
            try:
                _check_params_compatible(self, old.params)
                self._estimator.params = old.params
            except (KeyError, ValueError):
                # topology changed since the old compile — re-init
                from analytics_zoo_tpu.common.nncontext import logger
                logger.warning(
                    "compile: existing params no longer match the "
                    "model topology; weights will be re-initialized")
        return self

    @property
    def estimator(self):
        est = getattr(self, "_estimator", None)
        if est is None:
            raise RuntimeError("call compile(...) first")
        return est

    def set_tensorboard(self, log_dir: str, app_name: str = "zoo_tpu"):
        """(reference `Topology.scala:197`)"""
        self.estimator.set_tensorboard(log_dir, app_name)
        return self

    def set_summary_trigger(self, name: str, trigger):
        """Extra TB summaries on a trigger — "Parameters" writes
        per-layer weight histograms (BigDL
        `TrainSummary.setSummaryTrigger`)."""
        self.estimator.set_summary_trigger(name, trigger)
        return self

    def set_checkpoint(self, path: str, trigger=None):
        """(reference `Topology.scala:238-248`)"""
        self.estimator.set_checkpoint(path, trigger)
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float):
        """(reference `Topology.scala:254-284`)"""
        self.estimator.set_gradient_clipping_by_l2_norm(clip_norm)
        return self

    def set_constant_gradient_clipping(self, min_value, max_value):
        self.estimator.set_constant_gradient_clipping(min_value, max_value)
        return self

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, **kwargs):
        """Train (reference `KerasNet.fit`, `Topology.scala:336-481`).

        `x` may be numpy array(s) (+ `y`), an `ArrayDataset`, or any
        object with the FeatureSet protocol (`num_samples` +
        `iter_batches`)."""
        return self.estimator.train(
            x, y, batch_size=batch_size, nb_epoch=nb_epoch,
            validation_data=validation_data, **kwargs)

    def evaluate(self, x, y=None, batch_size: int = 32):
        """(reference `Topology.scala:489-540`)"""
        return self.estimator.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: int = 32, distributed: bool = True):
        """(reference `Predictable`, `pipeline/api/Predictor.scala:203`;
        `distributed` kept for API parity — execution is always sharded
        over the mesh)."""
        del distributed
        return self.estimator.predict(x, batch_size=batch_size)

    def predict_classes(self, x, batch_size: int = 32,
                        zero_based_label: bool = True):
        probs = self.predict(x, batch_size=batch_size)
        classes = np.argmax(probs, axis=-1)
        return classes if zero_based_label else classes + 1

    # -- persistence (reference `Topology.scala:754-775` saveModel /
    #    Net.load; weights-only analog of BigDL checkpoint files) ----------
    def save_weights(self, path: str):
        params = self.estimator.params if getattr(
            self, "_estimator", None) is not None and \
            self.estimator.params is not None else None
        if params is None:
            raise RuntimeError("no parameters to save; fit or init first")
        flat = {}
        for kp, leaf in jax.tree_util.tree_leaves_with_path(params):
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                           for p in kp)
            flat[key] = np.asarray(leaf)
        np.savez(path, **flat)

    def load_weights(self, path: str):
        import jax.tree_util as jtu
        data = np.load(path)
        est = self.estimator
        if est.params is None:
            est._ensure_initialized()
        leaves_with_path = jtu.tree_leaves_with_path(est.params)
        new_leaves = []
        for kp, leaf in leaves_with_path:
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                           for p in kp)
            if key not in data:
                raise KeyError(f"weight {key} missing from {path}")
            saved = data[key]
            if tuple(saved.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"shape mismatch for {key}: saved {saved.shape} vs "
                    f"model {leaf.shape}")
            new_leaves.append(saved)
        treedef = jtu.tree_structure(est.params)
        est.params = jax.device_put(
            jtu.tree_unflatten(treedef, new_leaves))
        est._train_step = None
        return self

    def get_weights(self) -> "list[np.ndarray]":
        """Flat list of weight arrays in deterministic (sorted-path)
        order — the reference's `getWeights` (`Topology.scala`/
        `KerasNet.get_weights`). Pair with :meth:`set_weights`."""
        est = self.estimator
        if est.params is None:
            est._ensure_initialized()
        return [np.asarray(leaf)
                for _, leaf in jax.tree_util.tree_leaves_with_path(
                    est.params)]

    def copy_weights_from(self, other: "KerasNet",
                          strict: bool = False) -> "KerasNet":
        """Copy weights from another net BY LAYER NAME (the
        transfer-learning carry-over of the reference's
        `NetUtils.scala:47-140` surgery): layers present in both nets
        take `other`'s weights, the rest keep their own.
        ``strict=True`` requires every layer of this net to match."""
        src_est, dst_est = other.estimator, self.estimator
        if src_est.params is None:
            src_est._ensure_initialized()
        if dst_est.params is None:
            dst_est._ensure_initialized()
        src = src_est.params
        missing = [n for n in dst_est.params if n not in src]
        if strict and missing:
            raise KeyError(f"layers missing from source: {missing}")

        from analytics_zoo_tpu.common.nncontext import logger

        def _shapes(tree):
            return [(p, tuple(leaf.shape)) for p, leaf in
                    jax.tree_util.tree_leaves_with_path(tree)]

        new_params = {}
        for name, sub in dst_est.params.items():
            if name not in src:
                new_params[name] = sub
                continue
            if _shapes(src[name]) != _shapes(sub):
                if strict:
                    raise ValueError(
                        f"layer {name!r}: source weights "
                        f"{_shapes(src[name])} incompatible with "
                        f"{_shapes(sub)}")
                logger.warning(
                    "copy_weights_from: skipping layer %r — source "
                    "shapes %s != destination %s", name,
                    _shapes(src[name]), _shapes(sub))
                new_params[name] = sub
                continue
            # dtype differences (e.g. f32 backbone -> bf16 model) cast
            # to the destination's dtype rather than skipping
            new_params[name] = jax.tree_util.tree_map(
                lambda s, d: jnp.asarray(s, d.dtype), src[name], sub)
        dst_est.params = new_params
        dst_est._train_step = None           # invalidate compiled step
        return self

    def set_weights(self, weights: "list[np.ndarray]"):
        """Inverse of :meth:`get_weights` (shape-checked)."""
        import jax.tree_util as jtu
        est = self.estimator
        if est.params is None:
            est._ensure_initialized()
        leaves = jtu.tree_leaves(est.params)
        if len(weights) != len(leaves):
            raise ValueError(
                f"expected {len(leaves)} arrays, got {len(weights)}")
        new = []
        for cur, w in zip(leaves, weights):
            w = np.asarray(w)
            if tuple(w.shape) != tuple(cur.shape):
                raise ValueError(
                    f"shape mismatch: model {cur.shape} vs {w.shape}")
            new.append(w.astype(cur.dtype))
        est.params = jax.device_put(jtu.tree_unflatten(
            jtu.tree_structure(est.params), new))
        est._train_step = None
        return self

    # -- introspection ------------------------------------------------------
    def summary(self, params: Optional[dict] = None,
                line_length: int = 76) -> str:
        """Printable per-layer summary (reference `Topology.scala:567`)."""
        rows = [("Layer (type)", "Output Shape", "Param #")]
        total = 0
        for lyr in self.layers:
            n = (lyr.param_count(params.get(lyr.name, {}))
                 if params else 0)
            total += n
            rows.append((f"{lyr.name} ({type(lyr).__name__})",
                         str(lyr.output_shape), str(n) if params else "?"))
        widths = [max(len(r[i]) for r in rows) + 2 for i in range(3)]
        lines = ["=" * line_length]
        for i, r in enumerate(rows):
            lines.append("".join(c.ljust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("-" * line_length)
        lines.append("=" * line_length)
        if params:
            lines.append(f"Total params: {total}")
        text = "\n".join(lines)
        print(text)
        return text


class Sequential(KerasNet):
    """Linear stack of layers (reference `Topology.scala:779-889`)."""

    def __init__(self, layers: Optional[Sequence[KerasLayer]] = None,
                 name: Optional[str] = None):
        super().__init__(name=name or unique_name("sequential"))
        self._layers: "list[KerasLayer]" = []
        for lyr in layers or []:
            self.add(lyr)

    @property
    def layers(self) -> "list[KerasLayer]":
        return self._layers

    def add(self, layer: KerasLayer) -> "Sequential":
        if not isinstance(layer, KerasLayer):
            raise TypeError(f"expected a KerasLayer, got {type(layer)}")
        if not self._layers and layer._given_input_shape is None and not \
                isinstance(layer, KerasNet):
            raise ValueError(
                "first layer of a Sequential needs input_shape=...")
        self._layers.append(layer)
        self._canonicalize_names(self._layers)
        return self

    def build(self, rng, input_shape: ShapeLike) -> dict:
        params = {}
        shape = input_shape
        keys = jax.random.split(rng, max(len(self._layers), 1))
        for key, lyr in zip(keys, self._layers):
            params[lyr.name] = lyr.init(key, shape)
            shape = lyr.output_shape
        return params

    def init(self, rng, input_shape: Optional[ShapeLike] = None) -> dict:
        if input_shape is None:
            if not self._layers:
                raise ValueError("empty Sequential")
            first = self._layers[0]
            input_shape = first._given_input_shape
            if input_shape is None and isinstance(first, KerasNet):
                # nested container knows its own input shape
                inner = first
                while isinstance(inner, Sequential) and inner._layers:
                    inner = inner._layers[0]
                input_shape = inner._given_input_shape
            if input_shape is None:
                raise ValueError(
                    "cannot infer input shape; give the first layer "
                    "input_shape=...")
        return super().init(rng, input_shape)

    def compute_output_shape(self, input_shape: ShapeLike) -> ShapeLike:
        shape = input_shape
        for lyr in self._layers:
            shape = lyr.compute_output_shape(shape)
        return shape

    def apply(self, params: dict, inputs, *, training: bool = False,
              rng=None):
        x = inputs
        updates: dict = {}
        for i, lyr in enumerate(self._layers):
            sub_rng = jax.random.fold_in(rng, i) if rng is not None else None
            x, upd = lyr.apply(params[lyr.name], x, training=training,
                               rng=sub_rng)
            if upd:
                updates[lyr.name] = upd
        return x, updates

    def call(self, params, inputs, *, training=False, rng=None):
        out, _ = self.apply(params, inputs, training=training, rng=rng)
        return out


class Model(KerasNet):
    """Functional graph model (reference `Topology.scala:572-658`).

    Built from `Input(...)` variables through layer calls; supports
    multi-input/multi-output and shared layers (a layer instance used at
    several nodes contributes one set of params).
    """

    def __init__(self, inputs: "Variable | Sequence[Variable]",
                 outputs: "Variable | Sequence[Variable]",
                 name: Optional[str] = None):
        super().__init__(name=name or unique_name("model"))
        self.inputs: "list[Variable]" = (
            list(inputs) if isinstance(inputs, (list, tuple)) else [inputs])
        self.outputs: "list[Variable]" = (
            list(outputs) if isinstance(outputs, (list, tuple))
            else [outputs])
        self._order = topological_order(self.outputs)
        for v in self.inputs:
            if v not in self._order:
                raise ValueError(f"input {v} is not connected to outputs")
        self._graph_layers = collect_layers(self._order)
        self._multi_out = isinstance(outputs, (list, tuple))
        # deterministic names: rename auto-named layers in graph order,
        # keeping node names in sync for new_graph/freeze_up_to lookups
        old_names = {id(lyr): lyr.name for lyr in self._graph_layers}
        self._canonicalize_names(self._graph_layers)
        for v in self._order:
            if v.layer is not None and \
                    v.name == old_names.get(id(v.layer)):
                v.name = v.layer.name

    @property
    def layers(self) -> "list[KerasLayer]":
        return self._graph_layers

    def build(self, rng, input_shape: ShapeLike) -> dict:
        del input_shape  # graph shapes come from the Input variables
        params = {}
        keys = jax.random.split(rng, max(len(self._graph_layers), 1))
        built = {}
        # walk nodes in order so every layer sees its node input shape
        for v in self._order:
            lyr = v.layer
            if lyr is None or isinstance(lyr, _InputLayer):
                continue
            if id(lyr) in built:
                continue
            if not v.parents:  # zero-input node (Parameter / Constant)
                in_shape: ShapeLike = v.shape
            else:
                in_shape = ([p.shape for p in v.parents]
                            if len(v.parents) > 1 else v.parents[0].shape)
            idx = len(built)
            params[lyr.name] = lyr.init(keys[idx], in_shape)
            built[id(lyr)] = True
        return params

    def init(self, rng, input_shape: Optional[ShapeLike] = None) -> dict:
        shape: ShapeLike = ([v.shape for v in self.inputs]
                            if len(self.inputs) > 1
                            else self.inputs[0].shape)
        return super().init(rng, input_shape or shape)

    def compute_output_shape(self, input_shape: ShapeLike) -> ShapeLike:
        shapes = [v.shape for v in self.outputs]
        return shapes if self._multi_out else shapes[0]

    def apply(self, params: dict, inputs, *, training: bool = False,
              rng=None):
        xs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
        if len(xs) != len(self.inputs):
            raise ValueError(
                f"model {self.name} expects {len(self.inputs)} inputs, "
                f"got {len(xs)}")
        values: "dict[int, Any]" = {id(v): x
                                    for v, x in zip(self.inputs, xs)}
        updates: dict = {}
        for i, v in enumerate(self._order):
            if id(v) in values:
                continue
            lyr = v.layer
            if lyr is None or isinstance(lyr, _InputLayer):
                raise ValueError(
                    f"graph input {v.name} was not fed; it must be listed "
                    "in Model(inputs=...)")
            args = [values[id(p)] for p in v.parents]
            arg = (None if not args
                   else args if len(args) > 1 else args[0])
            sub_rng = jax.random.fold_in(rng, i) if rng is not None else None
            out, upd = lyr.apply(params[lyr.name], arg, training=training,
                                 rng=sub_rng)
            if upd:
                # shared layers may emit updates at several nodes; last wins
                updates[lyr.name] = upd
            values[id(v)] = out
        outs = [values[id(v)] for v in self.outputs]
        return (outs if self._multi_out else outs[0]), updates

    def call(self, params, inputs, *, training=False, rng=None):
        out, _ = self.apply(params, inputs, training=training, rng=rng)
        return out

    def new_graph(self, output_names: "list[str]") -> "Model":
        """Sub-graph ending at the named variables (reference `GraphNet.
        newGraph`, `NetUtils.scala:47-140` — transfer-learning surgery)."""
        by_name = {v.name: v for v in self._order}
        missing = [n for n in output_names if n not in by_name]
        if missing:
            raise ValueError(f"no graph nodes named {missing}")
        outs = [by_name[n] for n in output_names]
        return Model(self.inputs, outs if len(outs) > 1 else outs[0])

    def freeze_up_to(self, *node_names: str) -> "Model":
        """Freeze every layer at or before the named nodes (reference
        `freezeUpTo`)."""
        by_name = {v.name: v for v in self._order}
        missing = [n for n in node_names if n not in by_name]
        if missing:
            raise ValueError(f"no graph nodes named {missing}")
        frontier = [by_name[n] for n in node_names]
        seen = set()
        while frontier:
            v = frontier.pop()
            if id(v) in seen:
                continue
            seen.add(id(v))
            if v.layer is not None and not isinstance(v.layer, _InputLayer):
                v.layer.trainable = False
            frontier.extend(v.parents)
        return self
