"""Layer/graph engine underneath the Keras-style API (L4 core).

The reference's layers wrap BigDL modules whose kernels bottom out in
MKL-DNN (SURVEY.md §2.4, §2.11.4). Here a layer is a *pure-functional
module*: ``build(rng, input_shape) -> params`` produces a pytree and
``apply(params, x) -> (y, state_updates)`` is a traceable JAX function.
There is no mutable forward state, so whole models jit/pjit cleanly and XLA
owns fusion and MXU tiling. Flax is deliberately not used: the Keras-1
semantics the reference exposes (shape-inference chaining, layer name
registry, `trainable` freezing, containers-as-layers) are small enough to
implement directly, and owning the engine keeps every downstream design
choice (sharding annotations, dtype policy, state threading) explicit.

Conventions:
- Shapes exclude the batch dimension (Keras-1 style, like the reference's
  `inputShape` args, e.g. `Z/pipeline/api/keras/layers/Dense.scala`).
- ``params[layer.name]`` is that layer's own pytree; non-trainable state
  (e.g. BatchNorm moving stats) lives under the reserved ``"_state"`` key
  and is updated through the second element of ``apply``'s result.
- ``Variable`` is the functional-graph handle; the autograd surface
  (`pipeline.api.autograd`) builds on the same node type (SURVEY.md §2.3
  maps the reference's symbolic `Variable` to exactly this).
"""

from __future__ import annotations

import itertools
import threading
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Shape = Tuple[int, ...]
ShapeLike = Union[Shape, List[Shape]]

_name_lock = threading.Lock()
_name_counters: "dict[str, itertools.count]" = {}


def unique_name(prefix: str) -> str:
    with _name_lock:
        counter = _name_counters.setdefault(prefix, itertools.count(1))
        return f"{prefix}_{next(counter)}"


def reset_name_registry() -> None:
    with _name_lock:
        _name_counters.clear()


def as_shape(s) -> Shape:
    if isinstance(s, int):
        return (s,)
    return tuple(int(d) for d in s)


def is_multi_shape(s) -> bool:
    return isinstance(s, list) or (
        isinstance(s, tuple) and len(s) > 0 and
        isinstance(s[0], (tuple, list)))


class KerasLayer:
    """Base class for all layers.

    Subclasses implement :meth:`build` (params creation, optional),
    :meth:`call` (forward), and :meth:`compute_output_shape`.
    """

    def __init__(self, input_shape: Optional[ShapeLike] = None,
                 name: Optional[str] = None, trainable: bool = True,
                 **kwargs):
        if kwargs:
            raise TypeError(
                f"{type(self).__name__}: unexpected kwargs {list(kwargs)}")
        self._auto_named = name is None
        self.name = name or unique_name(type(self).__name__.lower())
        self.trainable = trainable
        self._given_input_shape = (
            None if input_shape is None else
            (list(map(as_shape, input_shape))
             if is_multi_shape(input_shape) else as_shape(input_shape)))
        self._build_input_shape: Optional[ShapeLike] = None
        self._output_shape: Optional[ShapeLike] = None

    # -- framework ----------------------------------------------------------
    def build(self, rng, input_shape: ShapeLike) -> dict:
        """Create parameters for ``input_shape``; default: no params."""
        del rng, input_shape
        return {}

    def call(self, params: dict, inputs, *, training: bool = False,
             rng=None):
        raise NotImplementedError(type(self).__name__)

    def apply(self, params: dict, inputs, *, training: bool = False,
              rng=None):
        """Forward returning ``(outputs, state_updates)``.

        Only stateful layers (BatchNorm) override this; everything else
        routes through :meth:`call` with no updates.
        """
        return self.call(params, inputs, training=training, rng=rng), {}

    def compute_output_shape(self, input_shape: ShapeLike) -> ShapeLike:
        return input_shape

    # -- build bookkeeping --------------------------------------------------
    def init(self, rng, input_shape: Optional[ShapeLike] = None) -> dict:
        """Build with shape bookkeeping; returns this layer's params."""
        if input_shape is None:
            input_shape = self._given_input_shape
        if input_shape is None:
            raise ValueError(
                f"layer {self.name}: input_shape required (pass it to the "
                "constructor or to init)")
        self._build_input_shape = input_shape
        params = self.build(rng, input_shape)
        self._output_shape = self.compute_output_shape(input_shape)
        return params

    @property
    def input_shape(self) -> Optional[ShapeLike]:
        return self._build_input_shape or self._given_input_shape

    @property
    def output_shape(self) -> Optional[ShapeLike]:
        return self._output_shape

    def param_count(self, params: dict) -> int:
        return sum(int(np.prod(x.shape)) for x in
                   jax.tree_util.tree_leaves(params))

    def regularizers(self) -> "list[tuple[str, Callable]]":
        """(param_key, regularizer) pairs contributing to the train loss."""
        return []

    def regularization_loss(self, params: dict):
        loss = jnp.zeros((), jnp.float32)
        for key, reg in self.regularizers():
            if key in params:
                loss = loss + reg(params[key])
        return loss

    # -- functional API -----------------------------------------------------
    def __call__(self, x: "Variable | Sequence[Variable]") -> "Variable":
        """Apply this layer to graph variables, creating a new node."""
        parents = list(x) if isinstance(x, (list, tuple)) else [x]
        if not all(isinstance(p, Variable) for p in parents):
            raise TypeError(
                f"layer {self.name} called on non-Variable input; use "
                "Input(shape=...) to start a functional graph")
        in_shape: ShapeLike = (
            [p.shape for p in parents] if len(parents) > 1
            else parents[0].shape)
        out_shape = self.compute_output_shape(in_shape)
        if is_multi_shape(out_shape):
            # multi-output layer (e.g. BERT): one base node evaluating
            # to the list, plus one selector Variable per output
            base = Variable(shape=(), layer=self, parents=parents)
            return [_TupleSelect(i)(base, shape=as_shape(s))
                    for i, s in enumerate(out_shape)]
        return Variable(shape=as_shape(out_shape), layer=self,
                        parents=parents)

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name})"


class _TupleSelect(KerasLayer):
    """Selects the i-th element of a multi-output layer's result."""

    def __init__(self, index: int, name: Optional[str] = None):
        super().__init__(name=name)
        self.index = int(index)

    def call(self, params, inputs, *, training=False, rng=None):
        return inputs[self.index]

    def __call__(self, base: "Variable", shape: Optional[Shape] = None
                 ) -> "Variable":
        return Variable(shape=shape or (), layer=self, parents=[base])


class _InputLayer(KerasLayer):
    """Placeholder node for functional graphs (Keras `Input`)."""

    def __init__(self, shape: Shape, name: Optional[str] = None):
        super().__init__(input_shape=shape, name=name or unique_name("input"))
        self._output_shape = as_shape(shape)

    def call(self, params, inputs, *, training=False, rng=None):
        return inputs

    def compute_output_shape(self, input_shape):
        return input_shape


class Variable:
    """A node in the functional graph.

    Holds the symbolic shape (batch dim excluded) plus the producing layer
    and parent variables. Arithmetic operator overloads are installed by
    `pipeline.api.autograd` (mirrors reference `autograd/math.scala:354-594`
    where `Variable` ops lazily build graph nodes).
    """

    __slots__ = ("shape", "layer", "parents", "name")

    def __init__(self, shape: Shape, layer: Optional[KerasLayer] = None,
                 parents: Optional[List["Variable"]] = None,
                 name: Optional[str] = None):
        self.shape = as_shape(shape)
        self.layer = layer
        self.parents = parents or []
        self.name = name or (layer.name if layer is not None
                             else unique_name("var"))

    @property
    def is_input(self) -> bool:
        return isinstance(self.layer, _InputLayer) or self.layer is None

    def __repr__(self):
        return f"Variable(name={self.name}, shape={self.shape})"

    # operator overloads — implementations provided by autograd (lazy import
    # avoids an engine<->autograd cycle)
    def _ag(self):
        from analytics_zoo_tpu.pipeline.api import autograd
        return autograd

    def __add__(self, other):
        return self._ag().add(self, other)

    def __radd__(self, other):
        return self._ag().add(self, other)

    def __sub__(self, other):
        return self._ag().sub(self, other)

    def __rsub__(self, other):
        return self._ag().rsub(self, other)

    def __mul__(self, other):
        return self._ag().mul(self, other)

    def __rmul__(self, other):
        return self._ag().mul(self, other)

    def __truediv__(self, other):
        return self._ag().div(self, other)

    def __rtruediv__(self, other):
        return self._ag().rdiv(self, other)

    def __neg__(self):
        return self._ag().neg(self)

    def __pow__(self, p):
        return self._ag().pow(self, p)

    def __getitem__(self, idx):
        return self._ag().slice_var(self, idx)

    def squeeze(self, dim=None):
        return self._ag().squeeze(self, dim)

    def expand_dims(self, axis):
        return self._ag().expand_dims(self, axis)


def Input(shape: ShapeLike, name: Optional[str] = None) -> Variable:
    """Create a functional-graph input placeholder.

    `shape` excludes the batch dimension, matching the reference's
    `Input(inputShape=...)` (`Z/pipeline/api/keras/models/Topology.scala`).
    """
    layer = _InputLayer(as_shape(shape), name=name)
    return Variable(shape=as_shape(shape), layer=layer, parents=[])


def topological_order(outputs: Sequence[Variable]) -> List[Variable]:
    """Topo-sort the graph feeding ``outputs`` (inputs first)."""
    order: List[Variable] = []
    seen: set = set()

    def visit(v: Variable, stack: set):
        if id(v) in seen:
            return
        if id(v) in stack:
            raise ValueError("cycle detected in layer graph")
        stack.add(id(v))
        for p in v.parents:
            visit(p, stack)
        stack.discard(id(v))
        seen.add(id(v))
        order.append(v)

    for out in outputs:
        visit(out, set())
    return order


def collect_layers(order: Sequence[Variable]) -> List[KerasLayer]:
    """Unique non-input layers in topo order (shared layers appear once)."""
    seen: set = set()
    layers: List[KerasLayer] = []
    for v in order:
        lyr = v.layer
        if lyr is None or isinstance(lyr, _InputLayer):
            continue
        if id(lyr) not in seen:
            seen.add(id(lyr))
            layers.append(lyr)
    return layers
