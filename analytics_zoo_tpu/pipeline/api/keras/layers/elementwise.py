"""Elementwise / tensor-utility layers.

Reference surface: `Z/pipeline/api/keras/layers/{AddConstant,MulConstant,
CAdd,CMul,Mul,Scale,Power,Negative,Exp,Log,Sqrt,Square,Identity,
BinaryThreshold,Threshold,HardShrink,SoftShrink,HardTanh,RReLU,
GaussianSampler,GetShape,Expand,Max,ResizeBilinear,SelectTable,SplitTensor,
KerasLayerWrapper,Highway,MaxoutDense}.scala`.

All of these are trivial XLA ops that fuse into their neighbours; the few
parametrised ones (CAdd/CMul/Scale/Mul/Highway/MaxoutDense) follow the
engine's pure-functional params convention.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops import activations, initializers, regularizers
from analytics_zoo_tpu.pipeline.api.keras.engine import (
    KerasLayer, Shape, ShapeLike)


class AddConstant(KerasLayer):
    """y = x + constant (reference `layers/AddConstant.scala`)."""

    def __init__(self, constant: float, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.constant = float(constant)

    def call(self, params, x, *, training=False, rng=None):
        return x + self.constant


class MulConstant(KerasLayer):
    """y = x * constant (reference `layers/MulConstant.scala`)."""

    def __init__(self, constant: float, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.constant = float(constant)

    def call(self, params, x, *, training=False, rng=None):
        return x * self.constant


class CAdd(KerasLayer):
    """Learnable per-element bias, broadcast against the input
    (reference `layers/CAdd.scala`)."""

    def __init__(self, size: Sequence[int], b_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.size = tuple(int(d) for d in size)
        self.b_regularizer = regularizers.get(b_regularizer)

    def build(self, rng, input_shape: Shape) -> dict:
        return {"bias": jnp.zeros(self.size, jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        return x + params["bias"].astype(x.dtype)

    def regularizers(self):
        return ([("bias", self.b_regularizer)]
                if self.b_regularizer is not None else [])


class CMul(KerasLayer):
    """Learnable per-element scale, broadcast against the input
    (reference `layers/CMul.scala`)."""

    def __init__(self, size: Sequence[int], w_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.size = tuple(int(d) for d in size)
        self.w_regularizer = regularizers.get(w_regularizer)

    def build(self, rng, input_shape: Shape) -> dict:
        return {"weight": jnp.ones(self.size, jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        return x * params["weight"].astype(x.dtype)

    def regularizers(self):
        return ([("weight", self.w_regularizer)]
                if self.w_regularizer is not None else [])


class Mul(KerasLayer):
    """Single learnable scalar multiplier (reference `layers/Mul.scala`)."""

    def build(self, rng, input_shape: Shape) -> dict:
        return {"weight": jnp.ones((), jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        return x * params["weight"].astype(x.dtype)


class Scale(KerasLayer):
    """CMul followed by CAdd over `size` (reference `layers/Scale.scala`)."""

    def __init__(self, size: Sequence[int], input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.size = tuple(int(d) for d in size)

    def build(self, rng, input_shape: Shape) -> dict:
        return {"weight": jnp.ones(self.size, jnp.float32),
                "bias": jnp.zeros(self.size, jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        return (x * params["weight"].astype(x.dtype)
                + params["bias"].astype(x.dtype))


class Power(KerasLayer):
    """y = (shift + scale * x) ** power (reference `layers/Power.scala`)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.power = float(power)
        self.scale = float(scale)
        self.shift = float(shift)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.power(self.shift + self.scale * x, self.power)


class Negative(KerasLayer):
    """y = -x (reference `layers/Negative.scala`)."""

    def call(self, params, x, *, training=False, rng=None):
        return -x


class Exp(KerasLayer):
    """y = exp(x) (reference `layers/Exp.scala`)."""

    def call(self, params, x, *, training=False, rng=None):
        return jnp.exp(x)


class Log(KerasLayer):
    """y = log(x) (reference `layers/Log.scala`)."""

    def call(self, params, x, *, training=False, rng=None):
        return jnp.log(x)


class Sqrt(KerasLayer):
    """y = sqrt(x) (reference `layers/Sqrt.scala`)."""

    def call(self, params, x, *, training=False, rng=None):
        return jnp.sqrt(x)


class Square(KerasLayer):
    """y = x^2 (reference `layers/Square.scala`)."""

    def call(self, params, x, *, training=False, rng=None):
        return jnp.square(x)


class Identity(KerasLayer):
    """y = x (reference `layers/Identity.scala`)."""

    def call(self, params, x, *, training=False, rng=None):
        return x


class BinaryThreshold(KerasLayer):
    """y = 1 if x > th else 0 (reference `layers/BinaryThreshold.scala`)."""

    def __init__(self, value: float = 1e-6, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.value = float(value)

    def call(self, params, x, *, training=False, rng=None):
        return (x > self.value).astype(x.dtype)


class Threshold(KerasLayer):
    """y = x if x > th else `value` (reference `layers/Threshold.scala`)."""

    def __init__(self, th: float = 1e-6, value: float = 0.0,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.th = float(th)
        self.value = float(value)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x > self.th, x, jnp.asarray(self.value, x.dtype))


class HardShrink(KerasLayer):
    """y = x if |x| > lambda else 0 (reference `layers/HardShrink.scala`)."""

    def __init__(self, value: float = 0.5, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.value = float(value)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(jnp.abs(x) > self.value, x, jnp.zeros_like(x))


class SoftShrink(KerasLayer):
    """Soft shrinkage (reference `layers/SoftShrink.scala`)."""

    def __init__(self, value: float = 0.5, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.value = float(value)

    def call(self, params, x, *, training=False, rng=None):
        lam = self.value
        return jnp.where(x > lam, x - lam,
                         jnp.where(x < -lam, x + lam, jnp.zeros_like(x)))


class HardTanh(KerasLayer):
    """Clip to [min_value, max_value] (reference `layers/HardTanh.scala`)."""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.clip(x, self.min_value, self.max_value)


class RReLU(KerasLayer):
    """Randomized leaky ReLU (reference `layers/RReLU.scala`): training
    draws the negative slope uniformly from [lower, upper]; inference uses
    the mean slope."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.lower = float(lower)
        self.upper = float(upper)

    def call(self, params, x, *, training=False, rng=None):
        if training and rng is not None:
            slope = jax.random.uniform(rng, x.shape, x.dtype,
                                       self.lower, self.upper)
        else:
            slope = jnp.asarray((self.lower + self.upper) / 2.0, x.dtype)
        return jnp.where(x >= 0, x, x * slope)


class GaussianSampler(KerasLayer):
    """VAE reparameterisation sampler over inputs [mean, log_var]
    (reference `layers/GaussianSampler.scala`): y = mean +
    exp(log_var / 2) * eps in training; deterministic mean at inference."""

    def call(self, params, inputs, *, training=False, rng=None):
        mean, log_var = inputs
        if not training or rng is None:
            return mean
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(log_var * 0.5) * eps

    def compute_output_shape(self, input_shape: ShapeLike) -> Shape:
        return tuple(input_shape[0])


class GetShape(KerasLayer):
    """Returns the (static) input shape as an int array, batch included
    (reference `layers/GetShape.scala`)."""

    def call(self, params, x, *, training=False, rng=None):
        shape_vec = jnp.asarray(x.shape, jnp.int32)
        # batched per-sample copies keep the engine's (B, ...) contract
        return jnp.broadcast_to(shape_vec, (x.shape[0], shape_vec.size))

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return (len(input_shape) + 1,)


class Expand(KerasLayer):
    """Broadcast size-1 dims up to `tgt_sizes` (batch included, -1 keeps
    a dim; reference `layers/Expand.scala`)."""

    def __init__(self, tgt_sizes: Sequence[int], input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.tgt_sizes = tuple(int(d) for d in tgt_sizes)

    def _target(self, shape):
        return tuple(s if t == -1 else t
                     for s, t in zip(shape, self.tgt_sizes))

    def call(self, params, x, *, training=False, rng=None):
        return jnp.broadcast_to(x, self._target(x.shape))

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        # tgt_sizes includes the batch dim; drop it for the symbolic shape
        return self._target((None,) + tuple(input_shape))[1:]


class Max(KerasLayer):
    """Max over a 1-indexed non-batch dim (reference `layers/Max.scala`);
    `return_value=False` returns argmax indices instead."""

    def __init__(self, dim: int, return_value: bool = True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dim = int(dim)
        self.return_value = bool(return_value)

    def call(self, params, x, *, training=False, rng=None):
        if self.return_value:
            return jnp.max(x, axis=self.dim)
        return jnp.argmax(x, axis=self.dim).astype(jnp.int32)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        shape = list(input_shape)
        del shape[self.dim - 1]
        return tuple(shape)


def nearest_round(pos, mode: str):
    """ONNX Resize nearest_mode rounding (one source of truth for the
    align-corners and asymmetric paths); unknown modes raise."""
    import numpy as _np
    if mode == "floor":
        return _np.floor(pos)
    if mode == "ceil":
        return _np.ceil(pos)
    if mode == "round_prefer_ceil":
        return _np.floor(_np.asarray(pos) + 0.5)
    if mode == "round_prefer_floor":
        return _np.ceil(_np.asarray(pos) - 0.5)
    raise NotImplementedError(f"Resize nearest_mode {mode!r}")


def align_corners_resize(x, sizes, method: str = "linear",
                         nearest_mode: str = "round_prefer_floor"):
    """Corner-aligned resize to `sizes` (full-rank tuple): output
    pixel i samples input at i*(in-1)/(out-1) — torch/ONNX
    align_corners semantics, no half-pixel shift, point sampling on
    downscale (antialias off). Shared by ResizeBilinear and the ONNX
    Resize op. Degenerate axes: in==1 replicates the single pixel;
    out==1 samples corner 0. "nearest" uses exact integer gathers
    (scale_and_translate rejects nearest)."""
    sizes = tuple(int(v) for v in sizes)
    if method == "nearest":
        import numpy as _np
        for ax, (insz, outsz) in enumerate(zip(x.shape, sizes)):
            if insz == outsz:
                continue
            pos = _np.arange(outsz) * ((insz - 1) /
                                       max(outsz - 1, 1))
            src = nearest_round(pos, nearest_mode)
            idx = _np.clip(src.astype(_np.int32), 0, insz - 1)
            x = jnp.take(x, jnp.asarray(idx), axis=ax)
        return x
    if method not in ("linear",):
        # jax's cubic kernel is Keys a=-0.5; ONNX defaults
        # cubic_coeff_a=-0.75 — silently wrong values, so refuse
        raise NotImplementedError(
            f"align_corners resize supports linear/nearest, not "
            f"{method!r} (cubic coefficient mismatch vs ONNX)")
    axes, scales, trans, bcast = [], [], [], []
    for ax, (insz, outsz) in enumerate(zip(x.shape, sizes)):
        if insz == outsz:
            continue
        if insz == 1:
            bcast.append(ax)      # replicate after the resampling
            continue
        axes.append(ax)
        k = (outsz - 1) / (insz - 1) if outsz > 1 else 1.0
        scales.append(k)
        trans.append(0.5 - 0.5 * k)
    if axes:
        mid = list(x.shape)
        for ax in axes:
            mid[ax] = sizes[ax]
        x = jax.image.scale_and_translate(
            x, tuple(mid), tuple(axes),
            jnp.asarray(scales, jnp.float32),
            jnp.asarray(trans, jnp.float32), method=method,
            antialias=False)
    for ax in bcast:
        x = jnp.repeat(x, sizes[ax], axis=ax)
    return x


class ResizeBilinear(KerasLayer):
    """Bilinear spatial resize (reference `layers/ResizeBilinear.scala`).

    NHWC by default (`dim_ordering="tf"`); XLA lowers `jax.image.resize`
    to gather/dot ops that stay on-device.
    """

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, dim_ordering: str = "tf",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_height = int(output_height)
        self.output_width = int(output_width)
        self.align_corners = bool(align_corners)
        if dim_ordering not in ("tf", "th"):
            raise ValueError("dim_ordering must be 'tf' or 'th'")
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        h, w = self.output_height, self.output_width
        if self.dim_ordering == "tf":
            out_shape = (x.shape[0], h, w, x.shape[3])
            sp = (1, 2)
        else:
            out_shape = (x.shape[0], x.shape[1], h, w)
            sp = (2, 3)
        if not self.align_corners:
            return jax.image.resize(x, out_shape, method="bilinear")
        return align_corners_resize(x, out_shape, method="linear")

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        h, w = self.output_height, self.output_width
        if self.dim_ordering == "tf":
            return (h, w, input_shape[2])
        return (input_shape[0], h, w)


class SelectTable(KerasLayer):
    """Select the index-th tensor from a multi-tensor input (reference
    `layers/SelectTable.scala`; 0-indexed like the Python reference API)."""

    def __init__(self, index: int, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.index = int(index)

    def call(self, params, inputs, *, training=False, rng=None):
        return inputs[self.index]

    def compute_output_shape(self, input_shape: ShapeLike) -> Shape:
        return tuple(input_shape[self.index])


class SplitTensor(KerasLayer):
    """Split along a 1-indexed non-batch dim into `num` equal slices
    (reference `layers/SplitTensor.scala`). Multi-output layer."""

    def __init__(self, dimension: int, num: int, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dimension = int(dimension)
        self.num = int(num)

    def call(self, params, x, *, training=False, rng=None):
        return [jnp.asarray(s) for s in
                jnp.split(x, self.num, axis=self.dimension)]

    def compute_output_shape(self, input_shape: Shape) -> ShapeLike:
        shape = list(input_shape)
        d = self.dimension - 1
        if shape[d] % self.num != 0:
            raise ValueError(
                f"{self.name}: dim {self.dimension} size {shape[d]} not "
                f"divisible by {self.num}")
        shape[d] //= self.num
        return [tuple(shape) for _ in range(self.num)]


class KerasLayerWrapper(KerasLayer):
    """Lift an arbitrary traceable function (params-free) into a layer
    (reference `layers/KerasLayerWrapper.scala`, which lifts any BigDL
    module). `output_shape_fn` maps input shape -> output shape; identity
    when omitted."""

    def __init__(self, fn: Callable, output_shape_fn: Optional[Callable] =
                 None, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.fn = fn
        self.output_shape_fn = output_shape_fn

    def call(self, params, x, *, training=False, rng=None):
        return self.fn(x)

    def compute_output_shape(self, input_shape: ShapeLike) -> ShapeLike:
        if self.output_shape_fn is not None:
            return self.output_shape_fn(input_shape)
        return input_shape


class Highway(KerasLayer):
    """Highway dense block: y = t * h(x) + (1 - t) * x
    (reference `layers/Highway.scala`)."""

    def __init__(self, activation=None, w_regularizer=None,
                 b_regularizer=None, bias: bool = True, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.activation = activations.get(activation) or activations.linear
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.bias = bias

    def build(self, rng, input_shape: Shape) -> dict:
        dim = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        init = initializers.get("glorot_uniform")
        params = {"kernel": init(k1, (dim, dim)),
                  "gate_kernel": init(k2, (dim, dim))}
        if self.bias:
            params["bias"] = jnp.zeros((dim,), jnp.float32)
            # gate bias at -1 so untrained highways mostly carry the input
            params["gate_bias"] = -jnp.ones((dim,), jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        h = x @ params["kernel"].astype(x.dtype)
        t = x @ params["gate_kernel"].astype(x.dtype)
        if self.bias:
            h = h + params["bias"].astype(x.dtype)
            t = t + params["gate_bias"].astype(x.dtype)
        t = jax.nn.sigmoid(t)
        return t * self.activation(h) + (1.0 - t) * x

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out += [("kernel", self.w_regularizer),
                    ("gate_kernel", self.w_regularizer)]
        if self.b_regularizer is not None and self.bias:
            out += [("bias", self.b_regularizer),
                    ("gate_bias", self.b_regularizer)]
        return out


class MaxoutDense(KerasLayer):
    """Dense with maxout over `nb_feature` linear pieces
    (reference `layers/MaxoutDense.scala`)."""

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 w_regularizer=None, b_regularizer=None, bias: bool = True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_dim = int(output_dim)
        self.nb_feature = int(nb_feature)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.bias = bias

    def build(self, rng, input_shape: Shape) -> dict:
        in_dim = input_shape[-1]
        init = initializers.get("glorot_uniform")
        k, _ = jax.random.split(rng)
        params = {"kernel": init(
            k, (self.nb_feature, in_dim, self.output_dim))}
        if self.bias:
            params["bias"] = jnp.zeros(
                (self.nb_feature, self.output_dim), jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        # (B, I) @ (F, I, O) -> (B, F, O); one batched MXU matmul
        y = jnp.einsum("bi,fio->bfo", x, params["kernel"].astype(x.dtype))
        if self.bias:
            y = y + params["bias"].astype(y.dtype)
        return jnp.max(y, axis=1)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return tuple(input_shape[:-1]) + (self.output_dim,)

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("kernel", self.w_regularizer))
        if self.b_regularizer is not None and self.bias:
            out.append(("bias", self.b_regularizer))
        return out
