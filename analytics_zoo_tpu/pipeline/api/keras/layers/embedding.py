"""Embedding layers.

Reference surface: `Z/pipeline/api/keras/layers/{Embedding,WordEmbedding,
SparseEmbedding}.scala`. `WordEmbedding` loads pretrained GloVe-style
vectors and is frozen by default (`WordEmbedding.scala:49-134`).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.ops import initializers, regularizers
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape


class Embedding(KerasLayer):
    """Trainable index→vector lookup (reference `layers/Embedding.scala`).

    Input: int ids of shape (seq,) → output (seq, output_dim). The gather
    is a `jnp.take` which XLA lowers to an efficient dynamic-gather; on
    TPU big embedding tables stay in HBM and can be sharded over the
    "vocab" logical axis (see parallel.mesh.FSDP_RULES).
    """

    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 w_regularizer=None, input_shape=None, name=None,
                 pad_zero: bool = False, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.kernel_init = initializers.get(init)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.pad_zero = pad_zero  # reserve row 0 as all-zero padding

    def build(self, rng, input_shape: Shape) -> dict:
        table = self.kernel_init(rng, (self.input_dim, self.output_dim))
        if self.pad_zero:
            table = table.at[0].set(0.0)
        return {"embeddings": table}

    def call(self, params, x, *, training=False, rng=None):
        ids = x.astype(jnp.int32)
        return jnp.take(params["embeddings"], ids, axis=0)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return tuple(input_shape) + (self.output_dim,)

    def regularizers(self):
        if self.w_regularizer is not None:
            return [("embeddings", self.w_regularizer)]
        return []


class WordEmbedding(KerasLayer):
    """Pretrained word embeddings, frozen by default
    (reference `layers/WordEmbedding.scala:49-134`).

    Construct with a numpy weight table, or via
    :meth:`from_glove` with a GloVe text file + word index.
    """

    def __init__(self, weights: np.ndarray, trainable: bool = False,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name,
                         trainable=trainable, **kwargs)
        self.weights = np.asarray(weights, np.float32)
        self.input_dim, self.output_dim = self.weights.shape

    @staticmethod
    def from_glove(glove_path: str, word_index: "dict[str, int]",
                   embedding_dim: Optional[int] = None,
                   trainable: bool = False, input_shape=None,
                   name=None) -> "WordEmbedding":
        """Build a table from a GloVe `word v1 v2 ...` text file; row 0 is
        the all-zero padding/OOV vector (mirrors `WordEmbedding.scala`'s
        GloVe loading)."""
        vectors: "dict[str, np.ndarray]" = {}
        dim = embedding_dim
        with open(glove_path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip().split(" ")
                word = parts[0]
                if word not in word_index:
                    continue
                vec = np.asarray(parts[1:], np.float32)
                if dim is None:
                    dim = vec.shape[0]
                vectors[word] = vec
        if dim is None:
            raise ValueError(f"no usable vectors found in {glove_path}")
        max_idx = max(word_index.values())
        table = np.zeros((max_idx + 1, dim), np.float32)
        for word, idx in word_index.items():
            if word in vectors:
                table[idx] = vectors[word]
        return WordEmbedding(table, trainable=trainable,
                             input_shape=input_shape, name=name)

    def build(self, rng, input_shape: Shape) -> dict:
        return {"embeddings": jnp.asarray(self.weights)}

    def call(self, params, x, *, training=False, rng=None):
        return jnp.take(params["embeddings"], x.astype(jnp.int32), axis=0)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return tuple(input_shape) + (self.output_dim,)
