"""Core layers: Dense, Activation, Dropout, reshape family.

Reference surface: `Z/pipeline/api/keras/layers/{Dense,Activation,Dropout,
Flatten,Reshape,Permute,RepeatVector,Masking,Squeeze,ExpandDim,Narrow,
Select}.scala`. Kernels are jnp/XLA ops — matmuls hit the MXU; elementwise
ops fuse into neighbors.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.ops import activations, initializers, regularizers
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape


class Dense(KerasLayer):
    """Fully-connected layer, applied over the last axis.

    (reference `layers/Dense.scala`; golden-tested like `DenseSpec.scala`.)
    """

    def __init__(self, output_dim: int, init="glorot_uniform",
                 activation=None, w_regularizer=None, b_regularizer=None,
                 bias: bool = True, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_dim = int(output_dim)
        self.kernel_init = initializers.get(init)
        self.activation = activations.get(activation)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.bias = bias

    def build(self, rng, input_shape: Shape) -> dict:
        in_dim = input_shape[-1]
        k_key, _ = jax.random.split(rng)
        params = {"kernel": self.kernel_init(k_key, (in_dim, self.output_dim))}
        if self.bias:
            params["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        y = x @ params["kernel"].astype(x.dtype)
        if self.bias:
            y = y + params["bias"].astype(y.dtype)
        if self.activation is not None:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return tuple(input_shape[:-1]) + (self.output_dim,)

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("kernel", self.w_regularizer))
        if self.b_regularizer is not None:
            out.append(("bias", self.b_regularizer))
        return out


class Activation(KerasLayer):
    """Standalone activation layer (reference `layers/Activation.scala`)."""

    def __init__(self, activation, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.activation = activations.get(activation) or activations.linear

    def call(self, params, x, *, training=False, rng=None):
        return self.activation(x)


class Dropout(KerasLayer):
    """Inverted dropout (reference `layers/Dropout.scala`)."""

    def __init__(self, p: float, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.p = float(p)

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: dropout needs an rng in "
                             "training mode")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, jnp.zeros_like(x))


class Flatten(KerasLayer):
    """Flatten all non-batch dims (reference `layers/Flatten.scala`)."""

    def call(self, params, x, *, training=False, rng=None):
        return x.reshape((x.shape[0], -1))

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return (int(np.prod(input_shape)),)


class Reshape(KerasLayer):
    """Reshape non-batch dims; one dim may be -1
    (reference `layers/Reshape.scala`)."""

    def __init__(self, target_shape, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.target_shape = tuple(int(d) for d in target_shape)

    def _resolve(self, input_shape: Shape) -> Shape:
        total = int(np.prod(input_shape))
        tgt = list(self.target_shape)
        if -1 in tgt:
            i = tgt.index(-1)
            known = int(np.prod([d for d in tgt if d != -1]))
            if known == 0 or total % known != 0:
                raise ValueError(
                    f"{self.name}: cannot reshape {input_shape} to "
                    f"{self.target_shape}")
            tgt[i] = total // known
        return tuple(tgt)

    def call(self, params, x, *, training=False, rng=None):
        return x.reshape((x.shape[0],) + self._resolve(tuple(x.shape[1:])))

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return self._resolve(input_shape)


class Permute(KerasLayer):
    """Permute non-batch dims; dims are 1-indexed like Keras
    (reference `layers/Permute.scala`)."""

    def __init__(self, dims, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dims = tuple(int(d) for d in dims)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.transpose(x, (0,) + self.dims)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return tuple(input_shape[d - 1] for d in self.dims)


class RepeatVector(KerasLayer):
    """(F,) -> (n, F) (reference `layers/RepeatVector.scala`)."""

    def __init__(self, n: int, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.n = int(n)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.repeat(x[:, None, :], self.n, axis=1)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return (self.n, input_shape[0])


class Squeeze(KerasLayer):
    """Remove a size-1 non-batch dim; 1-indexed over non-batch dims
    (reference `layers/Squeeze.scala`)."""

    def __init__(self, dim: int, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dim = int(dim)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.squeeze(x, axis=self.dim)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        shape = list(input_shape)
        if shape[self.dim - 1] != 1:
            raise ValueError(f"{self.name}: dim {self.dim} of {input_shape} "
                             "is not 1")
        del shape[self.dim - 1]
        return tuple(shape)


class ExpandDim(KerasLayer):
    """Insert a size-1 dim at a non-batch position
    (reference `layers/ExpandDim.scala`)."""

    def __init__(self, dim: int, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dim = int(dim)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.expand_dims(x, axis=self.dim)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        shape = list(input_shape)
        shape.insert(self.dim - 1, 1)
        return tuple(shape)


class Narrow(KerasLayer):
    """Slice `length` elements from `offset` along a dim (1-indexed
    non-batch dims; reference `layers/Narrow.scala`)."""

    def __init__(self, dim: int, offset: int, length: int = 1,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dim = int(dim)
        self.offset = int(offset)
        self.length = int(length)

    def call(self, params, x, *, training=False, rng=None):
        return jax.lax.slice_in_dim(x, self.offset,
                                    self.offset + self.length,
                                    axis=self.dim)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        shape = list(input_shape)
        shape[self.dim - 1] = self.length
        return tuple(shape)


class Select(KerasLayer):
    """Select index along a dim, removing it (reference
    `layers/Select.scala`)."""

    def __init__(self, dim: int, index: int, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dim = int(dim)
        self.index = int(index)

    def call(self, params, x, *, training=False, rng=None):
        return jax.lax.index_in_dim(x, self.index, axis=self.dim,
                                    keepdims=False)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        shape = list(input_shape)
        del shape[self.dim - 1]
        return tuple(shape)


class Masking(KerasLayer):
    """Zero timesteps equal to mask_value (reference
    `layers/Masking.scala`). Downstream layers see zeros (no mask
    propagation — JAX models handle masking explicitly)."""

    def __init__(self, mask_value: float = 0.0, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.mask_value = float(mask_value)

    def call(self, params, x, *, training=False, rng=None):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, x, jnp.zeros_like(x))
