"""Transformer layers: MultiHeadAttention, TransformerLayer (GPT-style),
BERT.

Reference surface: `Z/pipeline/api/keras/layers/TransformerLayer.scala:50`
(input [batch, seqLen, 2] = token+position ids, post-LN blocks,
`bidirectional` flag) and `BERT.scala:53-110` (4 inputs: ids, segment
ids, position ids, attention mask; pooled first-token output;
`output_all_block`).

TPU-first redesign:
- all N blocks share ONE traced program: per-block params are stacked on
  a leading axis and the depth loop is a `lax.scan` — compile time and
  HLO size are O(1) in depth (the reference unrolls per block);
- attention runs in f32 softmax over bf16 QK^T on the MXU
  (`ops.attention`), or sequence-parallel ring attention over a mesh
  axis when `sequence_parallel_axis` is set (long-context path the
  reference lacks);
- weights init normal(0, initializer_range) like the reference.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops.attention import (dot_product_attention,
                                             resolve_attention_impl)
from analytics_zoo_tpu.pipeline.api.keras.engine import (
    KerasLayer, Shape, ShapeLike)


def _normal(rng, shape, stddev):
    return jax.random.normal(rng, shape, jnp.float32) * stddev


def _layer_norm(x, g, b, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return y * g.astype(y.dtype) + b.astype(y.dtype)


def _dropout(x, p, rng, training):
    if not training or p <= 0.0 or rng is None:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


class MultiHeadAttention(KerasLayer):
    """Self-attention layer (the per-block attention of the reference's
    TransformerLayer, exposed standalone)."""

    def __init__(self, hidden_size: int, n_head: int,
                 attn_p_drop: float = 0.1, resid_p_drop: float = 0.1,
                 causal: bool = False, initializer_range: float = 0.02,
                 sequence_parallel_axis: Optional[str] = None,
                 sequence_parallel_mode: str = "ring",
                 attention_impl: Optional[str] = None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if hidden_size % n_head:
            raise ValueError("hidden_size must divide by n_head")
        from analytics_zoo_tpu.parallel import get_sp_attention
        get_sp_attention(sequence_parallel_mode)  # validate early
        # None → ZOO_TPU_ATTENTION env (default "auto": the Pallas
        # flash kernel on TPU past the crossover, else XLA dense);
        # "flash"/"xla" force one path (ops/flash_attention.py)
        if attention_impl is not None:
            resolve_attention_impl(attention_impl)  # validate early
        self.attention_impl = attention_impl
        self.hidden_size = int(hidden_size)
        self.n_head = int(n_head)
        self.attn_p_drop = float(attn_p_drop)
        self.resid_p_drop = float(resid_p_drop)
        self.causal = causal
        self.initializer_range = float(initializer_range)
        self.sequence_parallel_axis = sequence_parallel_axis
        self.sequence_parallel_mode = sequence_parallel_mode

    def build(self, rng, input_shape: Shape) -> dict:
        h = self.hidden_size
        k1, k2 = jax.random.split(rng)
        return {
            "qkv_kernel": _normal(k1, (h, 3 * h), self.initializer_range),
            "qkv_bias": jnp.zeros((3 * h,), jnp.float32),
            "out_kernel": _normal(k2, (h, h), self.initializer_range),
            "out_bias": jnp.zeros((h,), jnp.float32),
        }

    def _attend(self, q, k, v, mask):
        if self.sequence_parallel_axis:
            if mask is not None:
                raise NotImplementedError(
                    "attention masks are not supported under sequence "
                    "parallelism (causal masking is); drop padding or "
                    "unset sequence_parallel_axis")
            from analytics_zoo_tpu.common.nncontext import get_nncontext
            from analytics_zoo_tpu.parallel import get_sp_attention
            sp = get_sp_attention(self.sequence_parallel_mode)
            return sp(q, k, v, get_nncontext().mesh,
                      axis=self.sequence_parallel_axis,
                      causal=self.causal, impl=self.attention_impl)
        return dot_product_attention(q, k, v, mask=mask,
                                     causal=self.causal,
                                     impl=self.attention_impl)

    def call(self, params, x, *, training=False, rng=None, mask=None):
        b, t, h = x.shape
        nh, hd = self.n_head, h // self.n_head
        qkv = x @ params["qkv_kernel"].astype(x.dtype) + \
            params["qkv_bias"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, nh, hd)
        k = k.reshape(b, t, nh, hd)
        v = v.reshape(b, t, nh, hd)
        out = self._attend(q, k, v, mask).reshape(b, t, h)
        out = out @ params["out_kernel"].astype(out.dtype) + \
            params["out_bias"].astype(out.dtype)
        if rng is not None:
            out = _dropout(out, self.resid_p_drop, rng, training)
        return out

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return input_shape


class TransformerLayer(KerasLayer):
    """GPT-style decoder stack (reference `TransformerLayer.scala:50`).

    Input: (seq_len,) int token ids (positions are implicit 0..T-1 —
    covers the reference's [seqLen, 2] token+position input, which is
    also accepted). Output: (seq_len, hidden_size), or a list of every
    block's output when `output_all_block`.
    """

    def __init__(self, n_block: int = 12, hidden_size: int = 768,
                 n_head: int = 12, seq_len: int = 512,
                 vocab: int = 40990, intermediate_size: int = 0,
                 hidden_p_drop: float = 0.1, attn_p_drop: float = 0.1,
                 initializer_range: float = 0.02,
                 bidirectional: bool = False,
                 output_all_block: bool = False,
                 embed_p_drop: float = 0.1,
                 sequence_parallel_axis: Optional[str] = None,
                 sequence_parallel_mode: str = "ring",
                 attention_impl: Optional[str] = None,
                 remat: bool = False,
                 pipeline_parallel_axis: Optional[str] = None,
                 pipeline_microbatches: Optional[int] = None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape or (seq_len,),
                         name=name, **kwargs)
        if hidden_size % n_head:
            raise ValueError("hidden_size must divide by n_head")
        if pipeline_parallel_axis and sequence_parallel_axis:
            raise ValueError(
                "pipeline_parallel_axis and sequence_parallel_axis "
                "cannot combine (nested shard_map); pick one")
        if pipeline_parallel_axis and output_all_block:
            raise ValueError(
                "output_all_block is unavailable under pipeline "
                "parallelism (only the final stage's output exists)")
        self.pipeline_parallel_axis = pipeline_parallel_axis
        self.pipeline_microbatches = pipeline_microbatches
        from analytics_zoo_tpu.parallel import get_sp_attention
        get_sp_attention(sequence_parallel_mode)  # validate early
        self.sequence_parallel_mode = sequence_parallel_mode
        if attention_impl is not None:
            resolve_attention_impl(attention_impl)  # validate early
        self.attention_impl = attention_impl
        self.remat = bool(remat)
        self.n_block = int(n_block)
        self.hidden_size = int(hidden_size)
        self.n_head = int(n_head)
        self.seq_len = int(seq_len)
        self.vocab = int(vocab)
        self.intermediate_size = int(intermediate_size) or \
            4 * self.hidden_size
        self.hidden_p_drop = float(hidden_p_drop)
        self.attn_p_drop = float(attn_p_drop)
        self.initializer_range = float(initializer_range)
        self.bidirectional = bidirectional
        self.output_all_block = output_all_block
        self.embed_p_drop = float(embed_p_drop)
        self.sequence_parallel_axis = sequence_parallel_axis

    # -- params -------------------------------------------------------------
    def _build_blocks(self, rng) -> dict:
        """Per-block params stacked on a leading n_block axis."""
        h, m, n = self.hidden_size, self.intermediate_size, self.n_block
        ks = jax.random.split(rng, 4)
        r = self.initializer_range
        return {
            "qkv_kernel": _normal(ks[0], (n, h, 3 * h), r),
            "qkv_bias": jnp.zeros((n, 3 * h), jnp.float32),
            "attn_out_kernel": _normal(ks[1], (n, h, h), r),
            "attn_out_bias": jnp.zeros((n, h), jnp.float32),
            "ln1_g": jnp.ones((n, h), jnp.float32),
            "ln1_b": jnp.zeros((n, h), jnp.float32),
            "mlp_in_kernel": _normal(ks[2], (n, h, m), r),
            "mlp_in_bias": jnp.zeros((n, m), jnp.float32),
            "mlp_out_kernel": _normal(ks[3], (n, m, h), r),
            "mlp_out_bias": jnp.zeros((n, h), jnp.float32),
            "ln2_g": jnp.ones((n, h), jnp.float32),
            "ln2_b": jnp.zeros((n, h), jnp.float32),
        }

    def build(self, rng, input_shape: ShapeLike) -> dict:
        k_embed, k_pos, k_blocks = jax.random.split(rng, 3)
        r = self.initializer_range
        return {
            "tok_embed": _normal(k_embed, (self.vocab, self.hidden_size),
                                 r),
            "pos_embed": _normal(k_pos, (self.seq_len, self.hidden_size),
                                 r),
            "blocks": self._build_blocks(k_blocks),
        }

    # -- forward ------------------------------------------------------------
    def _split_qkv(self, p, x):
        """(…, H) → q, k, v with heads split — the projection half of
        a block, shared by the full forward and the cached decode path
        so both trace the exact same matmul."""
        nh = self.n_head
        hd = self.hidden_size // nh
        qkv = x @ p["qkv_kernel"].astype(x.dtype) + \
            p["qkv_bias"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = x.shape[:-1] + (nh, hd)
        return q.reshape(shp), k.reshape(shp), v.reshape(shp)

    def _block_tail(self, p, x, attn, r1=None, r2=None,
                    training=False):
        """Out-projection + residual/LN + MLP half of a block (every
        op after attention) — the single copy run by the full forward
        AND the decode step, so the paged-cache path is numerically
        the training graph, not a reimplementation of it. Shape-
        agnostic over leading dims ((B, T, H) or (S, H))."""
        attn = attn @ p["attn_out_kernel"].astype(x.dtype) + \
            p["attn_out_bias"].astype(x.dtype)
        attn = _dropout(attn, self.hidden_p_drop, r1, training)
        x = _layer_norm(x + attn, p["ln1_g"], p["ln1_b"])
        mlp = jax.nn.gelu(x @ p["mlp_in_kernel"].astype(x.dtype) +
                          p["mlp_in_bias"].astype(x.dtype))
        mlp = mlp @ p["mlp_out_kernel"].astype(x.dtype) + \
            p["mlp_out_bias"].astype(x.dtype)
        mlp = _dropout(mlp, self.hidden_p_drop, r2, training)
        return _layer_norm(x + mlp, p["ln2_g"], p["ln2_b"])

    def _embed(self, params, x):
        if x.ndim == 3:  # reference layout (B, T, 2): token + position
            tok_ids = x[..., 0].astype(jnp.int32)
            pos_ids = x[..., 1].astype(jnp.int32)
            pos = jnp.take(params["pos_embed"], pos_ids, axis=0)
        else:
            tok_ids = x.astype(jnp.int32)
            pos = params["pos_embed"][None, :tok_ids.shape[1]]
        return jnp.take(params["tok_embed"], tok_ids, axis=0) + pos

    def _run_blocks(self, params, h0, mask, training, rng):
        causal = not self.bidirectional
        sp_axis = self.sequence_parallel_axis
        n = self.n_block
        rngs = (jax.random.split(rng, n) if rng is not None
                else jnp.zeros((n, 2), jnp.uint32))

        def block_body(x, p, blk_rng, mask):
            b, t, hsz = x.shape
            r1 = r2 = r3 = None
            if rng is not None:
                key = jax.random.wrap_key_data(blk_rng) if \
                    blk_rng.dtype == jnp.uint32 else blk_rng
                r1, r2, r3 = jax.random.split(key, 3)
            q, k, v = self._split_qkv(p, x)
            if sp_axis:
                if mask is not None:
                    raise NotImplementedError(
                        "attention masks are not supported under "
                        "sequence parallelism (causal masking is); "
                        "drop padding or unset sequence_parallel_axis")
                from analytics_zoo_tpu.common.nncontext import \
                    get_nncontext
                from analytics_zoo_tpu.parallel import get_sp_attention
                sp = get_sp_attention(self.sequence_parallel_mode)
                attn = sp(q, k, v, get_nncontext().mesh,
                          axis=sp_axis, causal=causal,
                          impl=self.attention_impl)
            else:
                attn = dot_product_attention(q, k, v, mask=mask,
                                             causal=causal,
                                             impl=self.attention_impl)
            attn = attn.reshape(b, t, hsz)
            return self._block_tail(p, x, attn, r1, r2, training)

        if rng is not None:
            rngs_data = jax.vmap(jax.random.key_data)(rngs)
        else:
            rngs_data = rngs
        if self.remat:
            # per-block rematerialization: the backward recomputes each
            # block's activations instead of keeping all n_block of
            # them live — O(1)-in-depth activation memory for ~1/3
            # extra FLOPs (the TPU HBM lever for deep/long-context
            # training; composes with the scan's O(1) compile time)
            block_body = jax.checkpoint(block_body)

        if self.pipeline_parallel_axis:
            final = self._run_blocks_gpipe(params, h0, mask,
                                           rngs_data, block_body)
            return final, None

        def block(x, inputs):
            p, blk_rng = inputs
            out = block_body(x, p, blk_rng, mask)
            return out, out

        final, all_blocks = jax.lax.scan(
            block, h0, (params["blocks"], rngs_data))
        return final, all_blocks

    def _run_blocks_gpipe(self, params, h0, mask, rngs_data,
                          block_body):
        """GPipe the block stack over the mesh's
        ``pipeline_parallel_axis``: ``n_block/S`` consecutive blocks
        per stage, microbatches rotating via ppermute
        (`parallel/pipeline.py`). Per-microbatch dropout keys are
        derived by folding the microbatch index into each block's key
        (the sequential path draws ONE key per block for the whole
        batch, so training randomness differs — inference and no-
        dropout training match exactly)."""
        from analytics_zoo_tpu.common.nncontext import get_nncontext
        from analytics_zoo_tpu.parallel.pipeline import gpipe_apply

        axis = self.pipeline_parallel_axis
        mesh = get_nncontext().mesh
        if axis not in mesh.shape:
            raise ValueError(
                f"pipeline_parallel_axis {axis!r} not in mesh axes "
                f"{tuple(mesh.shape)}")
        s = mesh.shape[axis]
        n = self.n_block
        if n % s:
            raise ValueError(
                f"n_block {n} must divide by the {axis!r} axis size "
                f"{s}")
        nb = n // s
        stage_params = {
            "blocks": jax.tree_util.tree_map(
                lambda a: a.reshape((s, nb) + a.shape[1:]),
                params["blocks"]),
            "rngs": rngs_data.reshape((s, nb) + rngs_data.shape[1:]),
        }
        m = self.pipeline_microbatches or s
        # per-sample masks (batch-leading, e.g. BERT's (B,1,1,T))
        # ride per microbatch; broadcastable masks ((1,1,T,T), (T,T))
        # are microbatch-independent and go to every stage whole
        margs, bargs = [], []
        if mask is not None:
            # a (1,1,T,T) broadcast mask with batch==1 must not be
            # classified per-sample (it would be split over
            # microbatches); only a >1 leading dim matching the batch
            # is genuinely per-sample
            per_sample = (mask.ndim == 4 and mask.shape[0] > 1
                          and mask.shape[0] == h0.shape[0])
            (margs if per_sample else bargs).append(mask)

        def stage(sp, h, mb_idx, *rest):
            mask_mb = rest[0] if rest else None

            def inner(x, inp):
                p, blk_rng = inp
                # distinct dropout per microbatch: fold mb_idx in
                blk_rng = jax.random.key_data(jax.random.fold_in(
                    jax.random.wrap_key_data(blk_rng), mb_idx))
                out = block_body(x, p, blk_rng, mask_mb)
                return out, None

            h, _ = jax.lax.scan(inner, h,
                                (sp["blocks"], sp["rngs"]))
            return h

        return gpipe_apply(stage, stage_params, h0, mesh=mesh,
                           axis=axis, microbatches=m,
                           microbatched_args=margs,
                           broadcast_args=bargs,
                           pass_mb_index=True)

    def call(self, params, x, *, training=False, rng=None, mask=None):
        r_embed = None
        if rng is not None:
            rng, r_embed = jax.random.split(rng)
        h0 = self._embed(params, x)
        h0 = _dropout(h0, self.embed_p_drop, r_embed, training)
        final, all_blocks = self._run_blocks(params, h0, mask, training,
                                             rng)
        if self.output_all_block:
            return [all_blocks[i] for i in range(self.n_block)]
        return final

    def compute_output_shape(self, input_shape: ShapeLike):
        t = (input_shape[0] if not is_multi(input_shape)
             else input_shape[0][0])
        shape = (t, self.hidden_size)
        if self.output_all_block:
            return [shape] * self.n_block
        return shape

    # -- decode fast path ---------------------------------------------------
    # Autoregressive generation with a paged KV cache (ops/kv_cache):
    # `prefill` runs the prompt once and caches every block's K/V;
    # `decode_step` extends every slot by ONE token against the cache
    # (O(T) per token instead of the naive O(T²) re-forward);
    # `forward_chunk` extends every slot by a BOUNDED chunk of C
    # tokens at a per-slot offset — the shared primitive under
    # chunked prefill (C-token slices of a long prompt interleaved
    # with decode iterations) and speculative verify (score C drafted
    # tokens in one pass); and `generate` wires prefill + decode_step
    # into a lax.while_loop whose shapes are static in (slots, pages)
    # — the whole loop compiles once and is AOT-warmable. Logits are
    # tied to `tok_embed` (h @ tok_embedᵀ), the weight-tying the
    # reference's LM head uses. Int8 caches carry per-row scale pools
    # (`ops.kv_cache`): writes quantize, attention dequantizes at the
    # gather — this layer only threads the scale arrays through.
    # Inference-only: no dropout, no sequence/pipeline parallelism.

    def init_kv_cache(self, max_slots: int, max_context: int,
                      page_size: int = 16, dtype=None):
        """A fresh paged cache sized for this stack: one page pool
        per block, identity page table (see `ops.kv_cache`)."""
        from analytics_zoo_tpu.ops import kv_cache as kvc
        return kvc.init_cache(
            self.n_block, int(max_slots), int(max_context),
            self.n_head, self.hidden_size // self.n_head,
            page_size=int(page_size), dtype=dtype or jnp.float32)

    def prefill(self, params, cache, token_ids, prompt_lens):
        """Run the (right-padded) prompts once, writing every block's
        K/V into the cache, and return ``(cache', logits)`` with
        logits taken at each slot's last real prompt position.

        token_ids: (S, T) int; prompt_lens: (S,) int32 — slots with
        ``prompt_lens == 0`` are untouched (their pages, seq_lens and
        neighbours' state are preserved), which is what lets the
        continuous batcher admit into a live batch. Causality makes
        right-padding safe: pad positions sit after every real token,
        so they influence nothing — their K/V rows are dropped at the
        scatter and masked at gather anyway."""
        from analytics_zoo_tpu.ops import kv_cache as kvc
        s, t = token_ids.shape
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
        h0 = self._embed(params, token_ids)
        causal = not self.bidirectional

        def block(x, p):
            q, k, v = self._split_qkv(p, x)
            attn = dot_product_attention(q, k, v, causal=causal,
                                         impl=self.attention_impl)
            attn = attn.reshape(s, t, self.hidden_size)
            return self._block_tail(p, x, attn), (k, v)

        final, (k_all, v_all) = jax.lax.scan(block, h0,
                                             params["blocks"])
        cache = self._write_prompt_all(cache, k_all, v_all,
                                       prompt_lens)
        cache = cache._replace(
            seq_lens=jnp.where(prompt_lens > 0, prompt_lens,
                               cache.seq_lens))
        last = final[jnp.arange(s), jnp.maximum(prompt_lens - 1, 0)]
        logits = last @ params["tok_embed"].astype(last.dtype).T
        return cache, logits

    def _write_prompt_all(self, cache, k_all, v_all, total_lens,
                          start=None):
        """vmap the per-layer prompt scatter over the block stack
        (k_all/v_all: (L, S, T, nh, hd)); quantized caches thread
        their scale pools through the same coordinates. Returns the
        cache with pages (and scales) replaced — ``seq_lens`` is the
        caller's to update."""
        from analytics_zoo_tpu.ops import kv_cache as kvc
        if cache.quantized:
            write = jax.vmap(
                lambda kp, vp, ks, vs, k, v: kvc.write_prompt_layer(
                    kp, vp, cache.page_table, total_lens, k, v,
                    start=start, k_scales=ks, v_scales=vs))
            kp, vp, ks, vs = write(cache.k_pages, cache.v_pages,
                                   cache.k_scales, cache.v_scales,
                                   k_all, v_all)
            return cache._replace(k_pages=kp, v_pages=vp,
                                  k_scales=ks, v_scales=vs)
        write = jax.vmap(
            lambda kp, vp, k, v: kvc.write_prompt_layer(
                kp, vp, cache.page_table, total_lens, k, v,
                start=start))
        kp, vp = write(cache.k_pages, cache.v_pages, k_all, v_all)
        return cache._replace(k_pages=kp, v_pages=vp)

    def decode_step(self, params, cache, token_ids, active=None):
        """One decode step for every slot: consume ``token_ids`` (S,)
        — each slot's previously sampled token — at position
        ``cache.seq_lens[s]``, append its K/V, attend over the cache,
        and return ``(cache', logits (S, V))``. Slots with
        ``active == False`` are frozen: nothing is written, their
        seq_lens do not advance, and (because inactive scatters are
        dropped) their pages cannot be perturbed by neighbours.
        Shape-static — safe inside while_loop and as ONE compiled
        program under continuous batching."""
        from analytics_zoo_tpu.ops import kv_cache as kvc
        from analytics_zoo_tpu.ops.attention import decode_attention
        s = token_ids.shape[0]
        if active is None:
            active = cache.seq_lens > 0
        pos = jnp.clip(cache.seq_lens, 0, self.seq_len - 1)
        x = jnp.take(params["tok_embed"],
                     token_ids.astype(jnp.int32), axis=0) + \
            jnp.take(params["pos_embed"], pos, axis=0)
        t_max = cache.max_context
        table = cache.page_table
        seq_lens = cache.seq_lens
        lens_after = seq_lens + active.astype(jnp.int32)

        def block(x, xs):
            p, kp, vp, ks, vs = xs
            q, k_new, v_new = self._split_qkv(p, x)
            if ks is None:
                kp, vp = kvc.append_layer(
                    kp, vp, table, seq_lens, k_new, v_new,
                    active=active)
                sk = sv = None
            else:
                kp, vp, ks, vs = kvc.append_layer(
                    kp, vp, table, seq_lens, k_new, v_new,
                    active=active, k_scales=ks, v_scales=vs)
                sk = kvc.gather_layer(ks, table, t_max)
                sv = kvc.gather_layer(vs, table, t_max)
            k_ctx = kvc.gather_layer(kp, table, t_max)
            v_ctx = kvc.gather_layer(vp, table, t_max)
            if ks is None:
                k_ctx = k_ctx.astype(x.dtype)
                v_ctx = v_ctx.astype(x.dtype)
            attn = decode_attention(q, k_ctx, v_ctx, lens_after,
                                    impl=self.attention_impl,
                                    k_scales=sk, v_scales=sv)
            attn = attn.reshape(s, self.hidden_size)
            return self._block_tail(p, x, attn), (kp, vp, ks, vs)

        final, (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
            block, x, (params["blocks"], cache.k_pages,
                       cache.v_pages, cache.k_scales,
                       cache.v_scales))
        cache = cache._replace(k_pages=k_pages, v_pages=v_pages,
                               k_scales=k_scales, v_scales=v_scales,
                               seq_lens=lens_after)
        logits = final @ params["tok_embed"].astype(final.dtype).T
        return cache, logits

    def forward_chunk(self, params, cache, token_ids, starts, n_new,
                      all_logits: bool = False):
        """Consume a bounded CHUNK of new tokens per slot against the
        cache — `decode_step` generalized from 1 to C tokens, with a
        per-slot write offset.

        token_ids: (S, C) int — each slot's next tokens, left-aligned
        and right-padded; starts: (S,) int32 — the absolute position
        the chunk begins at (== the slot's current cached length);
        n_new: (S,) int32 — how many of the C rows are real for each
        slot (0 = slot untouched: nothing written, seq_lens frozen,
        and — because inactive scatters drop — neighbours cannot be
        perturbed). Every block writes the chunk's K/V into the pages
        FIRST, then attends over the gathered cache with the mask
        ``key_pos <= start + j`` (`ops.attention.chunk_attention`),
        so intra-chunk causality and cache validity are one rule and
        the math is the training graph's.

        Returns ``(cache', logits)``: logits (S, V) at each slot's
        LAST real chunk position (chunked prefill — sample the first
        token when the final chunk lands), or (S, C, V) at every
        chunk position when ``all_logits`` (speculative verify —
        score every draft). ``seq_lens`` advances to
        ``starts + n_new`` for touched slots. Shape-static in (S, C);
        safe to AOT-compile once per chunk width.
        """
        from analytics_zoo_tpu.ops import kv_cache as kvc
        from analytics_zoo_tpu.ops.attention import chunk_attention
        s, c = token_ids.shape
        starts = jnp.asarray(starts, jnp.int32)
        n_new = jnp.asarray(n_new, jnp.int32)
        total = starts + n_new
        q_pos = starts[:, None] + jnp.arange(c, dtype=jnp.int32)[None]
        pos_ids = jnp.clip(q_pos, 0, self.seq_len - 1)
        x = jnp.take(params["tok_embed"],
                     token_ids.astype(jnp.int32), axis=0) + \
            jnp.take(params["pos_embed"], pos_ids, axis=0)
        t_max = cache.max_context
        table = cache.page_table

        def block(x, xs):
            p, kp, vp, ks, vs = xs
            q, k_new, v_new = self._split_qkv(p, x)
            if ks is None:
                kp, vp = kvc.write_prompt_layer(
                    kp, vp, table, total, k_new, v_new, start=starts)
                sk = sv = None
            else:
                kp, vp, ks, vs = kvc.write_prompt_layer(
                    kp, vp, table, total, k_new, v_new, start=starts,
                    k_scales=ks, v_scales=vs)
                sk = kvc.gather_layer(ks, table, t_max)
                sv = kvc.gather_layer(vs, table, t_max)
            k_ctx = kvc.gather_layer(kp, table, t_max)
            v_ctx = kvc.gather_layer(vp, table, t_max)
            if ks is None:
                k_ctx = k_ctx.astype(x.dtype)
                v_ctx = v_ctx.astype(x.dtype)
            attn = chunk_attention(q, k_ctx, v_ctx, q_pos,
                                   k_scales=sk, v_scales=sv)
            attn = attn.reshape(s, c, self.hidden_size)
            return self._block_tail(p, x, attn), (kp, vp, ks, vs)

        final, (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
            block, x, (params["blocks"], cache.k_pages,
                       cache.v_pages, cache.k_scales,
                       cache.v_scales))
        cache = cache._replace(
            k_pages=k_pages, v_pages=v_pages,
            k_scales=k_scales, v_scales=v_scales,
            seq_lens=jnp.where(n_new > 0, total, cache.seq_lens))
        embed_t = params["tok_embed"].astype(final.dtype).T
        if all_logits:
            return cache, final @ embed_t
        last = final[jnp.arange(s),
                     jnp.clip(n_new - 1, 0, c - 1)]
        return cache, last @ embed_t

    def generate(self, params, prompts, prompt_lens=None,
                 max_new_tokens: int = 32, *, temperature=0.0,
                 top_k: int = 0, eos_id=None, rng=None,
                 page_size: int = 16, cache_dtype=None):
        """Compiled autoregressive generation: prefill + a
        `lax.while_loop` of decode steps over (cache, token buffer,
        done-mask). Greedy when ``temperature <= 0`` (per-slot —
        temperature may be a (S,) vector), else softmax sampling with
        optional static ``top_k`` truncation. Stops early when every
        slot has emitted ``eos_id``.

        prompts: (S, T) int, right-padded to ``prompt_lens``.
        Returns ``(tokens (S, T + max_new_tokens), lengths (S,))`` —
        per slot, ``tokens[s, :lengths[s]]`` is prompt + generation
        (contiguous even when the prompt was padded). Shapes are
        static in (S, T, max_new_tokens): wrap in `jax.jit` (or AOT
        `.lower().compile()`) and the whole loop is one program."""
        from analytics_zoo_tpu.ops.sampling import sample_tokens
        prompts = jnp.asarray(prompts, jnp.int32)
        s, tp = prompts.shape
        if prompt_lens is None:
            prompt_lens = jnp.full((s,), tp, jnp.int32)
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
        if rng is None:
            rng = jax.random.key(0)
        max_new = int(max_new_tokens)
        total = tp + max_new
        cache = self.init_kv_cache(s, total, page_size=page_size,
                                   dtype=cache_dtype)
        cache, logits = self.prefill(params, cache, prompts,
                                     prompt_lens)
        temp = jnp.broadcast_to(
            jnp.asarray(temperature, jnp.float32), (s,))
        buf = jnp.zeros((s, total), jnp.int32)
        buf = buf.at[:, :tp].set(prompts)
        tok = sample_tokens(jax.random.fold_in(rng, 0), logits, temp,
                            top_k)
        buf = buf.at[jnp.arange(s), prompt_lens].set(tok)
        done = (tok == eos_id) if eos_id is not None else \
            jnp.zeros((s,), jnp.bool_)
        n_new = jnp.ones((s,), jnp.int32)

        def cond(st):
            _, _, _, done, _, i = st
            return jnp.logical_and(i < max_new,
                                   jnp.logical_not(jnp.all(done)))

        def body(st):
            cache, buf, tok, done, n_new, i = st
            active = jnp.logical_not(done)
            cache, logits = self.decode_step(params, cache, tok,
                                             active=active)
            nxt = sample_tokens(jax.random.fold_in(rng, i), logits,
                                temp, top_k)
            pos = jnp.clip(prompt_lens + i, 0, total - 1)
            cur = buf[jnp.arange(s), pos]
            buf = buf.at[jnp.arange(s), pos].set(
                jnp.where(active, nxt, cur))
            n_new2 = n_new + active.astype(jnp.int32)
            if eos_id is not None:
                done = jnp.logical_or(
                    done, jnp.logical_and(active, nxt == eos_id))
            tok = jnp.where(active, nxt, tok)
            return (cache, buf, tok, done, n_new2, i + 1)

        st = (cache, buf, tok, done, n_new, jnp.asarray(1, jnp.int32))
        _, buf, _, _, n_new, _ = jax.lax.while_loop(cond, body, st)
        return buf, prompt_lens + n_new


def is_multi(s):
    return isinstance(s, list) or (isinstance(s, tuple) and s and
                                   isinstance(s[0], (tuple, list)))


class BERT(TransformerLayer):
    """BERT encoder (reference `BERT.scala:53-110`).

    Inputs: a list of 4 arrays — `[token_ids (B, T), token_type_ids
    (B, T), position_ids (B, T), attention_mask (B, T)]` (reference
    input contract). Output: `[sequence_output(s), pooled_output]` —
    per-block sequence outputs when `output_all_block`, else the last
    block's, plus the tanh-Dense pooled first token.
    """

    def __init__(self, vocab: int = 40990, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12, seq_len: int = 512,
                 intermediate_size: int = 3072,
                 hidden_p_drop: float = 0.1, attn_p_drop: float = 0.1,
                 initializer_range: float = 0.02,
                 output_all_block: bool = True,
                 n_token_types: int = 2,
                 sequence_parallel_axis: Optional[str] = None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(
            n_block=n_block, hidden_size=hidden_size, n_head=n_head,
            seq_len=seq_len, vocab=vocab,
            intermediate_size=intermediate_size,
            hidden_p_drop=hidden_p_drop, attn_p_drop=attn_p_drop,
            initializer_range=initializer_range, bidirectional=True,
            output_all_block=output_all_block,
            sequence_parallel_axis=sequence_parallel_axis,
            input_shape=input_shape or [(seq_len,)] * 4,
            name=name, **kwargs)
        self.n_token_types = int(n_token_types)

    def build(self, rng, input_shape: ShapeLike) -> dict:
        k1, k2 = jax.random.split(rng)
        params = super().build(k1, input_shape)
        r = self.initializer_range
        k_type, k_pool = jax.random.split(k2)
        params["type_embed"] = _normal(
            k_type, (self.n_token_types, self.hidden_size), r)
        params["embed_ln_g"] = jnp.ones((self.hidden_size,), jnp.float32)
        params["embed_ln_b"] = jnp.zeros((self.hidden_size,), jnp.float32)
        params["pooler_kernel"] = _normal(
            k_pool, (self.hidden_size, self.hidden_size), r)
        params["pooler_bias"] = jnp.zeros((self.hidden_size,),
                                          jnp.float32)
        return params

    def call(self, params, inputs, *, training=False, rng=None):
        token_ids, token_type_ids, position_ids, attn_mask = inputs
        tok = jnp.take(params["tok_embed"],
                       token_ids.astype(jnp.int32), axis=0)
        pos = jnp.take(params["pos_embed"],
                       position_ids.astype(jnp.int32), axis=0)
        typ = jnp.take(params["type_embed"],
                       token_type_ids.astype(jnp.int32), axis=0)
        h0 = _layer_norm(tok + pos + typ, params["embed_ln_g"],
                         params["embed_ln_b"])
        r_embed = None
        if rng is not None:
            rng, r_embed = jax.random.split(rng)
        h0 = _dropout(h0, self.embed_p_drop, r_embed, training)
        # (B, 1, 1, T) multiplicative mask → attention bias semantics of
        # the reference's `(-mask + 1) * -10000`
        mask = attn_mask[:, None, None, :]
        final, all_blocks = self._run_blocks(params, h0, mask, training,
                                             rng)
        pooled = jnp.tanh(
            final[:, 0] @ params["pooler_kernel"].astype(final.dtype) +
            params["pooler_bias"].astype(final.dtype))
        if self.output_all_block:
            outs = [all_blocks[i] for i in range(self.n_block)]
        else:
            outs = [final]
        return outs + [pooled]

    def compute_output_shape(self, input_shape: ShapeLike):
        t = input_shape[0][0]
        seq_shape = (t, self.hidden_size)
        n = self.n_block if self.output_all_block else 1
        return [seq_shape] * n + [(self.hidden_size,)]
