"""Convolutional LSTM layers.

Reference surface: `Z/pipeline/api/keras/layers/{ConvLSTM2D,ConvLSTM3D}.scala`
(BigDL ConvLSTMPeephole without peepholes by default; gate order i,f,c,o,
inner activation hard_sigmoid — same Keras-1 semantics as `LSTM`).

TPU-first: input-to-gate convolutions for ALL timesteps are hoisted out of
the scan as one big (B·T) conv (maximal MXU utilisation); the scan body only
does the hidden-to-gate conv. Layout is channels-last (NHWC), the native
TPU conv layout, instead of the reference's CHANNEL_FIRST default.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops import activations, initializers, regularizers
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import (
    _conv_out_len, _norm_tuple)


class _ConvLSTMND(KerasLayer):
    ndim = 2  # spatial dims

    def __init__(self, nb_filter: int, nb_kernel, activation="tanh",
                 inner_activation="hard_sigmoid", border_mode: str = "same",
                 subsample=1, return_sequences: bool = False,
                 go_backwards: bool = False, w_regularizer=None,
                 u_regularizer=None, b_regularizer=None, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        n = self.ndim
        if border_mode not in ("same", "valid"):
            raise ValueError("border_mode must be same|valid")
        self.nb_filter = int(nb_filter)
        self.nb_kernel = _norm_tuple(nb_kernel, n, "nb_kernel")
        self.subsample = _norm_tuple(subsample, n, "subsample")
        self.border_mode = border_mode
        self.activation = activations.get(activation)
        self.inner_activation = activations.get(inner_activation)
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.w_regularizer = regularizers.get(w_regularizer)
        self.u_regularizer = regularizers.get(u_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)

    def _dn(self):
        n = self.ndim
        sp = "DHW"[3 - n:]
        io = ("N" + sp + "C", sp + "IO", "N" + sp + "C")
        return jax.lax.conv_dimension_numbers(
            (1,) * (n + 2), (1,) * (n + 2), io)

    def build(self, rng, input_shape: Shape) -> dict:
        # input_shape: (T, *spatial, C)
        in_ch = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        # glorot for both kernels — orthogonal init is 2D-only, and for
        # conv-shaped recurrent kernels glorot's flattened fan behaves
        # equivalently
        init = initializers.get("glorot_uniform")
        w_shape = self.nb_kernel + (in_ch, 4 * self.nb_filter)
        u_shape = self.nb_kernel + (self.nb_filter, 4 * self.nb_filter)
        return {"kernel": init(k1, w_shape),
                "recurrent": init(k2, u_shape),
                "bias": jnp.zeros((4 * self.nb_filter,), jnp.float32)}

    def _conv(self, x, kernel, strides, padding):
        return jax.lax.conv_general_dilated(
            x, kernel.astype(x.dtype), window_strides=strides,
            padding=padding, dimension_numbers=self._dn())

    def _out_spatial(self, spatial) -> Tuple[int, ...]:
        return tuple(_conv_out_len(s, k, st, self.border_mode)
                     for s, k, st in zip(spatial, self.nb_kernel,
                                         self.subsample))

    def call(self, params, x, *, training=False, rng=None):
        # x: (B, T, *spatial, C)
        if self.go_backwards:
            x = jnp.flip(x, axis=1)
        b, t = x.shape[0], x.shape[1]
        n = self.ndim
        flat = x.reshape((b * t,) + x.shape[2:])
        zx = self._conv(flat, params["kernel"], self.subsample,
                        self.border_mode.upper())
        zx = zx + params["bias"].astype(zx.dtype)
        out_sp = zx.shape[1:1 + n]
        zx = zx.reshape((b, t) + zx.shape[1:])
        zx_t = jnp.swapaxes(zx, 0, 1)  # (T, B, *sp, 4F)

        h0 = jnp.zeros((b,) + out_sp + (self.nb_filter,), x.dtype)
        c0 = jnp.zeros_like(h0)
        u = params["recurrent"]

        def scan_fn(carry, z):
            h, c = carry
            gates = z + self._conv(h, u, (1,) * n, "SAME")
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = self.inner_activation(i)
            f = self.inner_activation(f)
            g = self.activation(g)
            o = self.inner_activation(o)
            c_new = f * c + i * g
            h_new = o * self.activation(c_new)
            return (h_new, c_new), h_new

        (_, _), outs = jax.lax.scan(scan_fn, (h0, c0), zx_t)
        outs = jnp.swapaxes(outs, 0, 1)  # (B, T, *sp, F)
        if self.return_sequences:
            return outs
        return outs[:, -1]

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        t = input_shape[0]
        out_sp = self._out_spatial(input_shape[1:1 + self.ndim])
        core = out_sp + (self.nb_filter,)
        if self.return_sequences:
            return (t,) + core
        return core

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("kernel", self.w_regularizer))
        if self.u_regularizer is not None:
            out.append(("recurrent", self.u_regularizer))
        if self.b_regularizer is not None:
            out.append(("bias", self.b_regularizer))
        return out


class ConvLSTM2D(_ConvLSTMND):
    """2D convolutional LSTM (reference `layers/ConvLSTM2D.scala`).
    Input (B, T, H, W, C)."""

    ndim = 2


class ConvLSTM3D(_ConvLSTMND):
    """3D convolutional LSTM (reference `layers/ConvLSTM3D.scala`).
    Input (B, T, D, H, W, C)."""

    ndim = 3
