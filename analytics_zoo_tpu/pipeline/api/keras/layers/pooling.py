"""Pooling layers (max/avg × 1/2/3D, plus global variants).

Reference surface: `Z/pipeline/api/keras/layers/{MaxPooling1D,MaxPooling2D,
MaxPooling3D,AveragePooling1D,AveragePooling2D,AveragePooling3D,
GlobalMaxPooling1D,...}.scala`. All lower to `lax.reduce_window`, which XLA
fuses with adjacent convs.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.ops import pool_grad
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import (
    _conv_out_len, _norm_tuple)


class _PoolND(KerasLayer):
    ndim = 2
    mode = "max"  # or "avg"

    def __init__(self, pool_size=2, strides=None, border_mode="valid",
                 dim_ordering="tf", input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        n = self.ndim
        self.pool_size = _norm_tuple(pool_size, n, "pool_size")
        self.strides = (self.pool_size if strides is None
                        else _norm_tuple(strides, n, "strides"))
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, "
                             f"got {border_mode}")
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering

    def _window(self):
        if self.dim_ordering == "tf":
            return (1,) + self.pool_size + (1,), (1,) + self.strides + (1,)
        return (1, 1) + self.pool_size, (1, 1) + self.strides

    def call(self, params, x, *, training=False, rng=None):
        window, strides = self._window()
        if self.mode == "max":
            # NHWC float 2-D max pools route through the mask-based
            # custom VJP (ops.pool_grad): the select_and_scatter that
            # jax's transpose rule emits is a sequential window scan
            # on TPU; the mask backward is dense element-wise work.
            # ZOO_TPU_MAXPOOL_MASK_BWD=0 reverts (trace-time).
            if (self.ndim == 2 and self.dim_ordering == "tf"
                    and jnp.issubdtype(x.dtype, jnp.floating)
                    and pool_grad.mask_bwd_enabled()):
                return pool_grad.maxpool2d(
                    x, self.pool_size, self.strides,
                    self.border_mode)
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
                else jnp.iinfo(x.dtype).min
            return jax.lax.reduce_window(
                x, init, jax.lax.max, window, strides,
                self.border_mode.upper())
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, window, strides, self.border_mode.upper())
        if self.border_mode == "valid":
            return summed / float(np.prod(self.pool_size))
        # "same": divide by actual window size at the edges
        counts = jax.lax.reduce_window(
            jnp.ones_like(x), 0.0, jax.lax.add, window, strides, "SAME")
        return summed / counts

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        n = self.ndim
        if self.dim_ordering == "tf":
            spatial = input_shape[:n]
            ch = input_shape[n:]
            out_sp = tuple(_conv_out_len(s, k, st, self.border_mode)
                           for s, k, st in zip(spatial, self.pool_size,
                                               self.strides))
            return out_sp + ch
        ch = input_shape[:1]
        spatial = input_shape[1:1 + n]
        out_sp = tuple(_conv_out_len(s, k, st, self.border_mode)
                       for s, k, st in zip(spatial, self.pool_size,
                                           self.strides))
        return ch + out_sp


class MaxPooling1D(_PoolND):
    ndim, mode = 1, "max"

    def __init__(self, pool_length=2, stride=None, **kwargs):
        kwargs.setdefault("strides", stride)
        super().__init__(pool_size=pool_length, **kwargs)


class AveragePooling1D(_PoolND):
    ndim, mode = 1, "avg"

    def __init__(self, pool_length=2, stride=None, **kwargs):
        kwargs.setdefault("strides", stride)
        super().__init__(pool_size=pool_length, **kwargs)


class MaxPooling2D(_PoolND):
    ndim, mode = 2, "max"


class AveragePooling2D(_PoolND):
    ndim, mode = 2, "avg"


class MaxPooling3D(_PoolND):
    ndim, mode = 3, "max"


class AveragePooling3D(_PoolND):
    ndim, mode = 3, "avg"


class _GlobalPoolND(KerasLayer):
    ndim = 2
    mode = "max"

    def __init__(self, dim_ordering="tf", input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.dim_ordering = dim_ordering

    def _axes(self):
        if self.dim_ordering == "tf":
            return tuple(range(1, 1 + self.ndim))
        return tuple(range(2, 2 + self.ndim))

    def call(self, params, x, *, training=False, rng=None):
        if self.mode == "max":
            return jnp.max(x, axis=self._axes())
        return jnp.mean(x, axis=self._axes())

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        if self.dim_ordering == "tf":
            return (input_shape[-1],)
        return (input_shape[0],)


class GlobalMaxPooling1D(_GlobalPoolND):
    ndim, mode = 1, "max"


class GlobalAveragePooling1D(_GlobalPoolND):
    ndim, mode = 1, "avg"


class GlobalMaxPooling2D(_GlobalPoolND):
    ndim, mode = 2, "max"


class GlobalAveragePooling2D(_GlobalPoolND):
    ndim, mode = 2, "avg"


class GlobalMaxPooling3D(_GlobalPoolND):
    ndim, mode = 3, "max"


class GlobalAveragePooling3D(_GlobalPoolND):
    ndim, mode = 3, "avg"
