"""Convolution layers.

Reference surface: `Z/pipeline/api/keras/layers/{Convolution1D,Convolution2D,
Convolution3D,AtrousConvolution2D,SeparableConvolution2D,Deconvolution2D,
Cropping1D,Cropping2D,ZeroPadding1D,ZeroPadding2D,UpSampling1D,UpSampling2D,
UpSampling3D}.scala`.

TPU-first divergence: default data layout is channels-last (NHWC) — the
native TPU conv layout — instead of the reference's theano-style "th"
(NCHW) default. `dim_ordering="th"` is still accepted and handled by
transposing the lax conv dimension-numbers, not the data.
All convs lower to `lax.conv_general_dilated`, which XLA maps onto the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops import (activations, conv_grad,
                                   initializers, regularizers)
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape


def _norm_tuple(v, n, name):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) != n:
        raise ValueError(f"{name} must have length {n}, got {v}")
    return v


def _conv_out_len(length, k, stride, border_mode, dilation=1):
    eff_k = (k - 1) * dilation + 1
    if border_mode == "same":
        return -(-length // stride)
    return -(-(length - eff_k + 1) // stride)


class _ConvND(KerasLayer):
    """Shared N-dim conv implementation (N = 1, 2, 3)."""

    ndim = 2  # spatial dims

    def __init__(self, nb_filter: int, kernel_size, init="glorot_uniform",
                 activation=None, border_mode: str = "valid",
                 subsample=1, dilation=1, dim_ordering: str = "tf",
                 w_regularizer=None, b_regularizer=None, bias: bool = True,
                 groups: int = 1, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, "
                             f"got {border_mode}")
        self.groups = int(groups)
        if self.groups < 1 or int(nb_filter) % self.groups:
            raise ValueError(
                f"nb_filter {nb_filter} must divide by groups "
                f"{groups}")
        if dim_ordering not in ("tf", "th"):
            raise ValueError("dim_ordering must be 'tf' (channels-last) or "
                             "'th' (channels-first)")
        n = self.ndim
        self.nb_filter = int(nb_filter)
        self.kernel_size = _norm_tuple(kernel_size, n, "kernel_size")
        self.subsample = _norm_tuple(subsample, n, "subsample")
        self.dilation = _norm_tuple(dilation, n, "dilation")
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering
        self.kernel_init = initializers.get(init)
        self.activation = activations.get(activation)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.bias = bias

    # dimension numbers for lax (batch included at runtime)
    def _dn(self):
        n = self.ndim
        sp = "DHW"[3 - n:]
        if self.dim_ordering == "tf":
            io = ("N" + sp + "C", sp + "IO", "N" + sp + "C")
        else:
            io = ("NC" + sp, sp + "IO", "NC" + sp)
        return jax.lax.conv_dimension_numbers(
            (1,) * (n + 2), (1,) * (n + 2), io)

    def _in_channels(self, input_shape: Shape) -> int:
        return (input_shape[-1] if self.dim_ordering == "tf"
                else input_shape[0])

    def build(self, rng, input_shape: Shape) -> dict:
        in_ch = self._in_channels(input_shape)
        if in_ch % self.groups:
            raise ValueError(
                f"input channels {in_ch} must divide by groups "
                f"{self.groups}")
        k_key, _ = jax.random.split(rng)
        w_shape = self.kernel_size + (in_ch // self.groups,
                                      self.nb_filter)
        params = {"kernel": self.kernel_init(k_key, w_shape)}
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return params

    def _convolve(self, x, kernel):
        # strided NHWC 2-D convs route through ops.conv_grad.conv2d:
        # same forward, but the backward is gated between jax's
        # transpose rule and the phase decomposition (which never
        # materializes a dilated operand — the executed-FLOPs lever;
        # ZOO_TPU_PHASE_BWD, trace-time)
        if (self.ndim == 2 and self.groups == 1
                and self.dilation == (1, 1)
                and self.dim_ordering == "tf"
                and max(self.subsample) > 1):
            return conv_grad.conv2d(
                x, kernel.astype(x.dtype), stride=self.subsample,
                padding=self.border_mode)
        return jax.lax.conv_general_dilated(
            x, kernel.astype(x.dtype),
            window_strides=self.subsample,
            padding=self.border_mode.upper(),
            rhs_dilation=self.dilation,
            feature_group_count=self.groups,
            dimension_numbers=self._dn())

    def call(self, params, x, *, training=False, rng=None):
        y = self._convolve(x, params["kernel"])
        if self.bias:
            b = params["bias"].astype(y.dtype)
            if self.dim_ordering == "tf":
                y = y + b
            else:
                y = y + b.reshape((1, -1) + (1,) * self.ndim)
        if self.activation is not None:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        n = self.ndim
        if self.dim_ordering == "tf":
            spatial = input_shape[:n]
        else:
            spatial = input_shape[1:1 + n]
        out_sp = tuple(
            _conv_out_len(s, k, st, self.border_mode, d)
            for s, k, st, d in zip(spatial, self.kernel_size,
                                   self.subsample, self.dilation))
        if self.dim_ordering == "tf":
            return out_sp + (self.nb_filter,)
        return (self.nb_filter,) + out_sp

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("kernel", self.w_regularizer))
        if self.b_regularizer is not None:
            out.append(("bias", self.b_regularizer))
        return out


class Convolution1D(_ConvND):
    """1D conv over (steps, input_dim) (reference
    `layers/Convolution1D.scala`)."""

    ndim = 1

    def __init__(self, nb_filter: int, filter_length: int, **kwargs):
        kwargs.setdefault("subsample", kwargs.pop("subsample_length", 1))
        super().__init__(nb_filter, filter_length, **kwargs)


class Convolution2D(_ConvND):
    """2D conv (reference `layers/Convolution2D.scala`)."""

    ndim = 2

    def __init__(self, nb_filter: int, nb_row: int, nb_col: Optional[int] =
                 None, **kwargs):
        if nb_col is None:
            kernel = nb_row
        else:
            kernel = (nb_row, nb_col)
        super().__init__(nb_filter, kernel, **kwargs)


class Convolution3D(_ConvND):
    """3D conv (reference `layers/Convolution3D.scala`)."""

    ndim = 3

    def __init__(self, nb_filter: int, kernel_dim1: int,
                 kernel_dim2: Optional[int] = None,
                 kernel_dim3: Optional[int] = None, **kwargs):
        if kernel_dim2 is None:
            kernel = kernel_dim1
        else:
            kernel = (kernel_dim1, kernel_dim2, kernel_dim3)
        super().__init__(nb_filter, kernel, **kwargs)


class AtrousConvolution2D(Convolution2D):
    """Dilated 2D conv (reference `layers/AtrousConvolution2D.scala`)."""

    def __init__(self, nb_filter, nb_row, nb_col=None, atrous_rate=(1, 1),
                 **kwargs):
        kwargs["dilation"] = atrous_rate
        super().__init__(nb_filter, nb_row, nb_col, **kwargs)


class DepthwiseConvolution2D(KerasLayer):
    """Depthwise 2D conv (MobileNet building block; the reference reaches
    it through BigDL's `SpatialSeparableConvolution` used by
    `SeparableConvolution2D.scala`). Implemented with
    ``feature_group_count=in_channels`` so XLA lowers it to a grouped conv
    on the MXU."""

    def __init__(self, nb_row: int, nb_col=None, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample=(1, 1),
                 depth_multiplier=1, dim_ordering="tf", w_regularizer=None,
                 b_regularizer=None, bias=True, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, "
                             f"got {border_mode}")
        if dim_ordering not in ("tf", "th"):
            raise ValueError("dim_ordering must be 'tf' or 'th'")
        self.kernel_size = (_norm_tuple(nb_row, 1, "nb_row")[0],
                            _norm_tuple(nb_col if nb_col is not None
                                        else nb_row, 1, "nb_col")[0])
        self.subsample = _norm_tuple(subsample, 2, "subsample")
        self.depth_multiplier = int(depth_multiplier)
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering
        self.kernel_init = initializers.get(init)
        self.activation = activations.get(activation)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.bias = bias

    def _in_channels(self, input_shape):
        return (input_shape[-1] if self.dim_ordering == "tf"
                else input_shape[0])

    def _out_channels(self, in_ch):
        return in_ch * self.depth_multiplier

    def _dn(self):
        io = (("NHWC", "HWIO", "NHWC") if self.dim_ordering == "tf"
              else ("NCHW", "HWIO", "NCHW"))
        return jax.lax.conv_dimension_numbers((1, 1, 1, 1), (1, 1, 1, 1), io)

    def _depthwise(self, x, params):
        """The shared grouped-conv stage."""
        in_ch = self._in_channels(tuple(x.shape[1:]))
        return jax.lax.conv_general_dilated(
            x, params["depthwise"].astype(x.dtype),
            window_strides=self.subsample,
            padding=self.border_mode.upper(),
            feature_group_count=in_ch,
            dimension_numbers=self._dn())

    def _bias_act(self, y, params):
        if self.bias:
            b = params["bias"].astype(y.dtype)
            y = y + (b if self.dim_ordering == "tf"
                     else b.reshape((1, -1, 1, 1)))
        if self.activation is not None:
            y = self.activation(y)
        return y

    def build(self, rng, input_shape):
        in_ch = self._in_channels(input_shape)
        k1, _ = jax.random.split(rng)
        out_ch = self._out_channels(in_ch)
        params = {"depthwise": self.kernel_init(
            k1, self.kernel_size + (1, in_ch * self.depth_multiplier))}
        if self.bias:
            params["bias"] = jnp.zeros((out_ch,), jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        return self._bias_act(self._depthwise(x, params), params)

    def compute_output_shape(self, input_shape):
        out_ch = self._out_channels(self._in_channels(input_shape))
        spatial = (input_shape[:2] if self.dim_ordering == "tf"
                   else input_shape[1:3])
        out_sp = tuple(_conv_out_len(s, k, st, self.border_mode)
                       for s, k, st in zip(spatial, self.kernel_size,
                                           self.subsample))
        if self.dim_ordering == "tf":
            return out_sp + (out_ch,)
        return (out_ch,) + out_sp

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("depthwise", self.w_regularizer))
        if self.b_regularizer is not None:
            out.append(("bias", self.b_regularizer))
        return out


class SeparableConvolution2D(DepthwiseConvolution2D):
    """Depthwise-separable 2D conv (reference
    `layers/SeparableConvolution2D.scala`): the depthwise stage of
    `DepthwiseConvolution2D` followed by a 1x1 pointwise conv — both
    MXU-friendly."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col=None, **kwargs):
        super().__init__(nb_row, nb_col, **kwargs)
        self.nb_filter = int(nb_filter)

    def _out_channels(self, in_ch):
        return self.nb_filter

    def build(self, rng, input_shape):
        in_ch = self._in_channels(input_shape)
        k1, k2 = jax.random.split(rng)
        params = {
            "depthwise": self.kernel_init(
                k1, self.kernel_size + (1, in_ch * self.depth_multiplier)),
            "pointwise": self.kernel_init(
                k2, (1, 1, in_ch * self.depth_multiplier, self.nb_filter)),
        }
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        y = self._depthwise(x, params)
        y = jax.lax.conv_general_dilated(
            y, params["pointwise"].astype(y.dtype),
            window_strides=(1, 1), padding="VALID",
            dimension_numbers=self._dn())
        return self._bias_act(y, params)

    def regularizers(self):
        out = super().regularizers()
        if self.w_regularizer is not None:
            out.insert(1, ("pointwise", self.w_regularizer))
        return out


class Deconvolution2D(KerasLayer):
    """Transposed 2D conv (reference `layers/Deconvolution2D.scala`)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col=None,
                 init="glorot_uniform", activation=None,
                 border_mode="valid", subsample=(1, 1), dim_ordering="tf",
                 w_regularizer=None, b_regularizer=None, bias=True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(nb_row),
                            int(nb_col if nb_col is not None else nb_row))
        self.subsample = _norm_tuple(subsample, 2, "subsample")
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering
        self.kernel_init = initializers.get(init)
        self.activation = activations.get(activation)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.bias = bias

    def _in_channels(self, input_shape):
        return (input_shape[-1] if self.dim_ordering == "tf"
                else input_shape[0])

    def build(self, rng, input_shape):
        in_ch = self._in_channels(input_shape)
        k_key, _ = jax.random.split(rng)
        # kernel layout (H, W, out, in) + transpose_kernel=True matches the
        # gradient-of-conv semantics of Keras/torch deconvolution
        params = {"kernel": self.kernel_init(
            k_key, self.kernel_size + (self.nb_filter, in_ch))}
        if self.bias:
            params["bias"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        io = (("NHWC", "HWIO", "NHWC") if self.dim_ordering == "tf"
              else ("NCHW", "HWIO", "NCHW"))
        y = jax.lax.conv_transpose(
            x, params["kernel"].astype(x.dtype),
            strides=self.subsample,
            padding=self.border_mode.upper(),
            dimension_numbers=io,
            transpose_kernel=True)
        if self.bias:
            b = params["bias"].astype(y.dtype)
            y = y + (b if self.dim_ordering == "tf"
                     else b.reshape((1, -1, 1, 1)))
        if self.activation is not None:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape):
        spatial = (input_shape[:2] if self.dim_ordering == "tf"
                   else input_shape[1:3])
        if self.border_mode == "same":
            out_sp = tuple(s * st for s, st in zip(spatial, self.subsample))
        else:
            out_sp = tuple(s * st + max(k - st, 0)
                           for s, st, k in zip(spatial, self.subsample,
                                               self.kernel_size))
        if self.dim_ordering == "tf":
            return out_sp + (self.nb_filter,)
        return (self.nb_filter,) + out_sp


class ZeroPadding1D(KerasLayer):
    """(reference `layers/ZeroPadding1D.scala`)"""

    def __init__(self, padding=1, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.padding = _norm_tuple(padding, 2, "padding") \
            if not isinstance(padding, int) else (padding, padding)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.pad(x, ((0, 0), self.padding, (0, 0)))

    def compute_output_shape(self, input_shape):
        return (input_shape[0] + sum(self.padding),) + tuple(input_shape[1:])


class ZeroPadding2D(KerasLayer):
    """(reference `layers/ZeroPadding2D.scala`)

    ``value`` (default 0) sets the pad constant — e.g. ``-inf`` when a
    torch padded MaxPool2d is imported, whose implicit padding must
    never win the max (torch pads with -inf, not 0)."""

    def __init__(self, padding=(1, 1), dim_ordering="tf", input_shape=None,
                 name=None, value=0.0, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if (isinstance(padding, (tuple, list)) and len(padding) == 2
                and all(isinstance(q, (tuple, list)) and len(q) == 2
                        for q in padding)):
            # keras-2 style asymmetric form ((top, bottom), (l, r))
            self.padding = (tuple(int(v) for v in padding[0]),
                            tuple(int(v) for v in padding[1]))
        else:
            p = _norm_tuple(padding, 2, "padding")
            self.padding = ((p[0], p[0]), (p[1], p[1]))
        self.dim_ordering = dim_ordering
        self.value = value

    def call(self, params, x, *, training=False, rng=None):
        if self.dim_ordering == "tf":
            pads = ((0, 0),) + self.padding + ((0, 0),)
        else:
            pads = ((0, 0), (0, 0)) + self.padding
        val = self.value
        if val == float("-inf"):  # representable floor for the dtype
            val = jnp.finfo(x.dtype).min if jnp.issubdtype(
                x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jnp.pad(x, pads, constant_values=val)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        if self.dim_ordering == "tf":
            s[0] += sum(self.padding[0])
            s[1] += sum(self.padding[1])
        else:
            s[1] += sum(self.padding[0])
            s[2] += sum(self.padding[1])
        return tuple(s)


class Cropping1D(KerasLayer):
    """(reference `layers/Cropping1D.scala`)"""

    def __init__(self, cropping=(1, 1), input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.cropping = _norm_tuple(cropping, 2, "cropping")

    def call(self, params, x, *, training=False, rng=None):
        a, b = self.cropping
        return x[:, a:x.shape[1] - b, :]

    def compute_output_shape(self, input_shape):
        return (input_shape[0] - sum(self.cropping),) + \
            tuple(input_shape[1:])


class Cropping2D(KerasLayer):
    """(reference `layers/Cropping2D.scala`)"""

    def __init__(self, cropping=((0, 0), (0, 0)), dim_ordering="tf",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if isinstance(cropping, int):
            cropping = ((cropping, cropping), (cropping, cropping))
        self.cropping = tuple(tuple(int(v) for v in c) for c in cropping)
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        (t, b), (l, r) = self.cropping
        if self.dim_ordering == "tf":
            return x[:, t:x.shape[1] - b, l:x.shape[2] - r, :]
        return x[:, :, t:x.shape[2] - b, l:x.shape[3] - r]

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        (t, b), (l, r) = self.cropping
        if self.dim_ordering == "tf":
            s[0] -= t + b
            s[1] -= l + r
        else:
            s[1] -= t + b
            s[2] -= l + r
        return tuple(s)


class UpSampling1D(KerasLayer):
    """(reference `layers/UpSampling1D.scala`)"""

    def __init__(self, length=2, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.length = int(length)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.repeat(x, self.length, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0] * self.length,) + tuple(input_shape[1:])


class UpSampling2D(KerasLayer):
    """(reference `layers/UpSampling2D.scala`)"""

    def __init__(self, size=(2, 2), dim_ordering="tf", input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.size = _norm_tuple(size, 2, "size")
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        if self.dim_ordering == "tf":
            y = jnp.repeat(x, self.size[0], axis=1)
            return jnp.repeat(y, self.size[1], axis=2)
        y = jnp.repeat(x, self.size[0], axis=2)
        return jnp.repeat(y, self.size[1], axis=3)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        if self.dim_ordering == "tf":
            s[0] *= self.size[0]
            s[1] *= self.size[1]
        else:
            s[1] *= self.size[0]
            s[2] *= self.size[1]
        return tuple(s)


class UpSampling3D(KerasLayer):
    """(reference `layers/UpSampling3D.scala`)"""

    def __init__(self, size=(2, 2, 2), input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.size = _norm_tuple(size, 3, "size")

    def call(self, params, x, *, training=False, rng=None):
        y = x
        for i, s in enumerate(self.size):
            y = jnp.repeat(y, s, axis=i + 1)
        return y

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        for i in range(3):
            s[i] *= self.size[i]
        return tuple(s)


# Keras-2-style aliases (reference keras2 layer set, SURVEY.md §2.4)
Conv1D = Convolution1D
Conv2D = Convolution2D
Conv3D = Convolution3D
Conv2DTranspose = Deconvolution2D
SeparableConv2D = SeparableConvolution2D
