"""Merge layers (multi-input combination).

Reference surface: `Z/pipeline/api/keras/layers/Merge.scala` (modes sum,
mul, concat, ave, cos, dot, max, min) plus the keras2-style Add/Multiply/
Average/Maximum/Minimum/Concatenate aliases.
"""

from __future__ import annotations


import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import (
    KerasLayer, Shape, ShapeLike)

_MODES = ("sum", "sub", "mul", "concat", "ave", "cos", "dot", "max",
          "min")


class Merge(KerasLayer):
    def __init__(self, mode: str = "sum", concat_axis: int = -1,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if mode not in _MODES:
            raise ValueError(f"merge mode must be one of {_MODES}")
        self.mode = mode
        self.concat_axis = int(concat_axis)

    def call(self, params, inputs, *, training=False, rng=None):
        xs: "list" = list(inputs)
        if len(xs) < 2:
            raise ValueError(f"{self.name}: merge needs >= 2 inputs")
        m = self.mode
        if m == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if m == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if m == "sub":
            out = xs[0]
            for x in xs[1:]:
                out = out - x
            return out
        if m == "ave":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out / float(len(xs))
        if m == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if m == "min":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.minimum(out, x)
            return out
        if m == "concat":
            return jnp.concatenate(xs, axis=self.concat_axis)
        if m == "dot":
            # batched dot of flattened vectors → (B, 1)
            a = xs[0].reshape(xs[0].shape[0], -1)
            b = xs[1].reshape(xs[1].shape[0], -1)
            return jnp.sum(a * b, axis=-1, keepdims=True)
        # cos
        a = xs[0].reshape(xs[0].shape[0], -1)
        b = xs[1].reshape(xs[1].shape[0], -1)
        na = jnp.linalg.norm(a, axis=-1, keepdims=True)
        nb = jnp.linalg.norm(b, axis=-1, keepdims=True)
        return jnp.sum(a * b, axis=-1, keepdims=True) / \
            jnp.maximum(na * nb, 1e-12)

    def compute_output_shape(self, input_shape: ShapeLike) -> Shape:
        shapes: "list[Shape]" = [tuple(s) for s in input_shape]
        if self.mode in ("sum", "sub", "mul", "ave", "max", "min"):
            return shapes[0]
        if self.mode == "concat":
            axis = self.concat_axis
            # axis counts the batch dim (Keras convention): -1 or 1-indexed
            out = list(shapes[0])
            idx = axis - 1 if axis > 0 else len(out) + axis \
                if axis < 0 else 0
            out[idx] = sum(s[idx] for s in shapes)
            return tuple(out)
        return (1,)  # dot / cos


def merge(inputs, mode="sum", concat_axis=-1, name=None):
    """Functional helper: ``merge([a, b], mode="concat")``."""
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(inputs)


class _MergeAlias(Merge):
    _mode = "sum"

    def __init__(self, input_shape=None, name=None, **kwargs):
        super().__init__(mode=self._mode, input_shape=input_shape,
                         name=name, **kwargs)


class Add(_MergeAlias):
    _mode = "sum"


class Multiply(_MergeAlias):
    _mode = "mul"


class Average(_MergeAlias):
    _mode = "ave"


class Maximum(_MergeAlias):
    _mode = "max"


class Minimum(_MergeAlias):
    _mode = "min"


class Concatenate(Merge):
    def __init__(self, axis=-1, input_shape=None, name=None, **kwargs):
        super().__init__(mode="concat", concat_axis=axis,
                         input_shape=input_shape, name=name, **kwargs)


class Dot(Merge):
    def __init__(self, input_shape=None, name=None, **kwargs):
        super().__init__(mode="dot", input_shape=input_shape, name=name,
                         **kwargs)
