"""The Keras-1-style layer library (reference: 116 layer files under
`Z/pipeline/api/keras/layers/` — SURVEY.md §2.4)."""

from analytics_zoo_tpu.pipeline.api.keras.layers.core import (
    Dense, Activation, Dropout, Flatten, Reshape, Permute, RepeatVector,
    Squeeze, ExpandDim, Narrow, Select, Masking)
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import (
    Convolution1D, Convolution2D, Convolution3D, AtrousConvolution2D,
    SeparableConvolution2D, DepthwiseConvolution2D, Deconvolution2D,
    ZeroPadding1D, ZeroPadding2D,
    Cropping1D, Cropping2D, UpSampling1D, UpSampling2D, UpSampling3D,
    Conv1D, Conv2D, Conv3D, Conv2DTranspose, SeparableConv2D)
from analytics_zoo_tpu.pipeline.api.keras.layers.pooling import (
    MaxPooling1D, MaxPooling2D, MaxPooling3D,
    AveragePooling1D, AveragePooling2D, AveragePooling3D,
    GlobalMaxPooling1D, GlobalMaxPooling2D, GlobalMaxPooling3D,
    GlobalAveragePooling1D, GlobalAveragePooling2D, GlobalAveragePooling3D)
from analytics_zoo_tpu.pipeline.api.keras.layers.normalization import (
    BatchNormalization, LayerNormalization, WithinChannelLRN2D)
from analytics_zoo_tpu.pipeline.api.keras.layers.embedding import (
    Embedding, WordEmbedding)
from analytics_zoo_tpu.pipeline.api.keras.layers.recurrent import (
    SimpleRNN, LSTM, GRU, Bidirectional, TimeDistributed)
from analytics_zoo_tpu.pipeline.api.keras.layers.merge import (
    Merge, merge, Add, Multiply, Average, Maximum, Minimum, Concatenate,
    Dot)
from analytics_zoo_tpu.pipeline.api.keras.layers.advanced_activations \
    import (LeakyReLU, ELU, ThresholdedReLU, PReLU, SReLU, Softmax)
from analytics_zoo_tpu.pipeline.api.keras.layers.noise import (
    GaussianNoise, GaussianDropout, SpatialDropout1D, SpatialDropout2D,
    SpatialDropout3D)
from analytics_zoo_tpu.pipeline.api.keras.layers.moe import MoE
from analytics_zoo_tpu.pipeline.api.keras.layers.transformer import (
    MultiHeadAttention, TransformerLayer, BERT)
from analytics_zoo_tpu.pipeline.api.keras.layers.elementwise import (
    AddConstant, MulConstant, CAdd, CMul, Mul, Scale, Power, Negative,
    Exp, Log, Sqrt, Square, Identity, BinaryThreshold, Threshold,
    HardShrink, SoftShrink, HardTanh, RReLU, GaussianSampler, GetShape,
    Expand, Max, ResizeBilinear, SelectTable, SplitTensor,
    KerasLayerWrapper, Highway, MaxoutDense)
from analytics_zoo_tpu.pipeline.api.keras.layers.local_conv import (
    LocallyConnected1D, LocallyConnected2D, AtrousConvolution1D,
    ShareConvolution2D, ZeroPadding3D, Cropping3D)
from analytics_zoo_tpu.pipeline.api.keras.layers.convlstm import (
    ConvLSTM2D, ConvLSTM3D)
from analytics_zoo_tpu.pipeline.api.keras.layers.sparse import (
    SparseEmbedding, SparseDense)

__all__ = [
    # core
    "Dense", "Activation", "Dropout", "Flatten", "Reshape", "Permute",
    "RepeatVector", "Squeeze", "ExpandDim", "Narrow", "Select", "Masking",
    # conv
    "Convolution1D", "Convolution2D", "Convolution3D",
    "AtrousConvolution2D", "SeparableConvolution2D",
    "DepthwiseConvolution2D", "Deconvolution2D",
    "ZeroPadding1D", "ZeroPadding2D", "Cropping1D", "Cropping2D",
    "UpSampling1D", "UpSampling2D", "UpSampling3D",
    "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose", "SeparableConv2D",
    # pooling
    "MaxPooling1D", "MaxPooling2D", "MaxPooling3D",
    "AveragePooling1D", "AveragePooling2D", "AveragePooling3D",
    "GlobalMaxPooling1D", "GlobalMaxPooling2D", "GlobalMaxPooling3D",
    "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalAveragePooling3D",
    # norm
    "BatchNormalization", "LayerNormalization", "WithinChannelLRN2D",
    # embedding
    "Embedding", "WordEmbedding",
    # recurrent
    "SimpleRNN", "LSTM", "GRU", "Bidirectional", "TimeDistributed",
    # merge
    "Merge", "merge", "Add", "Multiply", "Average", "Maximum", "Minimum",
    "Concatenate", "Dot",
    # advanced activations
    "LeakyReLU", "ELU", "ThresholdedReLU", "PReLU", "SReLU", "Softmax",
    # noise
    "GaussianNoise", "GaussianDropout", "SpatialDropout1D",
    "SpatialDropout2D", "SpatialDropout3D",
    # transformer
    "MultiHeadAttention", "TransformerLayer", "MoE", "BERT",
    # elementwise / tensor utilities
    "AddConstant", "MulConstant", "CAdd", "CMul", "Mul", "Scale", "Power",
    "Negative", "Exp", "Log", "Sqrt", "Square", "Identity",
    "BinaryThreshold", "Threshold", "HardShrink", "SoftShrink", "HardTanh",
    "RReLU", "GaussianSampler", "GetShape", "Expand", "Max",
    "ResizeBilinear", "SelectTable", "SplitTensor", "KerasLayerWrapper",
    "Highway", "MaxoutDense",
    # locally-connected / conv extras
    "LocallyConnected1D", "LocallyConnected2D", "AtrousConvolution1D",
    "ShareConvolution2D", "ZeroPadding3D", "Cropping3D",
    # conv-lstm
    "ConvLSTM2D", "ConvLSTM3D",
    # sparse
    "SparseEmbedding", "SparseDense",
]
