"""Advanced activation layers.

Reference surface: `Z/pipeline/api/keras/layers/{LeakyReLU,ELU,PReLU,SReLU,
ThresholdedReLU}.scala` + Softmax.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape


class LeakyReLU(KerasLayer):
    def __init__(self, alpha: float = 0.3, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.alpha = float(alpha)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x >= 0, x, self.alpha * x)


class ELU(KerasLayer):
    def __init__(self, alpha: float = 1.0, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.alpha = float(alpha)

    def call(self, params, x, *, training=False, rng=None):
        return jax.nn.elu(x, alpha=self.alpha)


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta: float = 1.0, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.theta = float(theta)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x > self.theta, x, jnp.zeros_like(x))


class PReLU(KerasLayer):
    """Learnable leak, one alpha per feature (trailing axis)."""

    def __init__(self, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)

    def build(self, rng, input_shape: Shape) -> dict:
        return {"alpha": jnp.full((input_shape[-1],), 0.25, jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        a = params["alpha"].astype(x.dtype)
        return jnp.where(x >= 0, x, a * x)


class SReLU(KerasLayer):
    """S-shaped ReLU with learnable thresholds/slopes
    (reference `layers/SReLU.scala`)."""

    def __init__(self, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)

    def build(self, rng, input_shape: Shape) -> dict:
        n = input_shape[-1]
        return {
            "t_right": jnp.ones((n,), jnp.float32),
            "a_right": jnp.ones((n,), jnp.float32),
            "t_left": jnp.zeros((n,), jnp.float32),
            "a_left": jnp.zeros((n,), jnp.float32),
        }

    def call(self, params, x, *, training=False, rng=None):
        tr = params["t_right"].astype(x.dtype)
        ar = params["a_right"].astype(x.dtype)
        tl = params["t_left"].astype(x.dtype)
        al = params["a_left"].astype(x.dtype)
        y_right = tr + ar * (x - tr)
        y_left = tl + al * (x - tl)
        return jnp.where(x >= tr, y_right, jnp.where(x <= tl, y_left, x))


class Softmax(KerasLayer):
    def call(self, params, x, *, training=False, rng=None):
        return jax.nn.softmax(x, axis=-1)
