"""Locally-connected and remaining conv-family layers.

Reference surface: `Z/pipeline/api/keras/layers/{LocallyConnected1D,
LocallyConnected2D,AtrousConvolution1D,ShareConvolution2D,Cropping3D,
ZeroPadding3D}.scala`.

Locally-connected layers (unshared kernels) are expressed as
patch-extraction (`lax.conv_general_dilated_patches`) followed by one
batched einsum over per-position weights — a single large MXU contraction
instead of the reference's per-position MKL gemm loop.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops import activations, initializers, regularizers
from analytics_zoo_tpu.pipeline.api.keras.engine import (
    KerasLayer, Shape)
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import (
    Convolution1D, Convolution2D, _conv_out_len, _norm_tuple)


class LocallyConnected1D(KerasLayer):
    """1D conv with unshared (per-position) kernels
    (reference `layers/LocallyConnected1D.scala`)."""

    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 subsample_length: int = 1, w_regularizer=None,
                 b_regularizer=None, bias: bool = True, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.nb_filter = int(nb_filter)
        self.filter_length = int(filter_length)
        self.subsample_length = int(subsample_length)
        self.activation = activations.get(activation)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.bias = bias

    def _out_len(self, steps: int) -> int:
        return _conv_out_len(steps, self.filter_length,
                             self.subsample_length, "valid")

    def build(self, rng, input_shape: Shape) -> dict:
        steps, in_ch = input_shape
        out_len = self._out_len(steps)
        init = initializers.get("glorot_uniform")
        k, _ = jax.random.split(rng)
        params = {"kernel": init(
            k, (out_len, self.filter_length * in_ch, self.nb_filter))}
        if self.bias:
            params["bias"] = jnp.zeros((out_len, self.nb_filter),
                                       jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        # x: (B, L, C) -> patches (B, out_len, k*C)
        patches = jax.lax.conv_general_dilated_patches(
            x, (self.filter_length,), (self.subsample_length,), "VALID",
            dimension_numbers=jax.lax.conv_dimension_numbers(
                x.shape, (1, 1, 1), ("NWC", "WIO", "NWC")))
        y = jnp.einsum("blp,lpf->blf", patches,
                       params["kernel"].astype(x.dtype))
        if self.bias:
            y = y + params["bias"].astype(y.dtype)
        if self.activation is not None:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return (self._out_len(input_shape[0]), self.nb_filter)

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("kernel", self.w_regularizer))
        if self.b_regularizer is not None and self.bias:
            out.append(("bias", self.b_regularizer))
        return out


class LocallyConnected2D(KerasLayer):
    """2D conv with unshared kernels
    (reference `layers/LocallyConnected2D.scala`). Channels-last."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, border_mode: str = "valid", subsample=1,
                 w_regularizer=None, b_regularizer=None, bias: bool = True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if border_mode != "valid":
            raise ValueError("LocallyConnected2D only supports "
                             "border_mode='valid' (as the reference)")
        self.nb_filter = int(nb_filter)
        self.nb_row = int(nb_row)
        self.nb_col = int(nb_col)
        self.subsample = _norm_tuple(subsample, 2, "subsample")
        self.activation = activations.get(activation)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.bias = bias

    def _out_hw(self, input_shape: Shape) -> Tuple[int, int]:
        h = _conv_out_len(input_shape[0], self.nb_row,
                          self.subsample[0], "valid")
        w = _conv_out_len(input_shape[1], self.nb_col,
                          self.subsample[1], "valid")
        return h, w

    def build(self, rng, input_shape: Shape) -> dict:
        in_ch = input_shape[2]
        oh, ow = self._out_hw(input_shape)
        init = initializers.get("glorot_uniform")
        k, _ = jax.random.split(rng)
        patch = self.nb_row * self.nb_col * in_ch
        params = {"kernel": init(
            k, (oh * ow, patch, self.nb_filter))}
        if self.bias:
            params["bias"] = jnp.zeros((oh * ow, self.nb_filter),
                                       jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        b, h, w, c = x.shape
        oh, ow = self._out_hw((h, w, c))
        patches = jax.lax.conv_general_dilated_patches(
            x, (self.nb_row, self.nb_col), self.subsample, "VALID",
            dimension_numbers=jax.lax.conv_dimension_numbers(
                x.shape, (1, 1, 1, 1), ("NHWC", "HWIO", "NHWC")))
        patches = patches.reshape(b, oh * ow, -1)
        y = jnp.einsum("blp,lpf->blf", patches,
                       params["kernel"].astype(x.dtype))
        if self.bias:
            y = y + params["bias"].astype(y.dtype)
        y = y.reshape(b, oh, ow, self.nb_filter)
        if self.activation is not None:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        oh, ow = self._out_hw(input_shape)
        return (oh, ow, self.nb_filter)

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("kernel", self.w_regularizer))
        if self.b_regularizer is not None and self.bias:
            out.append(("bias", self.b_regularizer))
        return out


class AtrousConvolution1D(Convolution1D):
    """Dilated 1D conv (reference `layers/AtrousConvolution1D.scala`)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 init="glorot_uniform", activation=None,
                 subsample_length: int = 1, atrous_rate: int = 1,
                 w_regularizer=None, b_regularizer=None, bias: bool = True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(nb_filter, filter_length, init=init,
                         activation=activation,
                         subsample_length=subsample_length,
                         w_regularizer=w_regularizer,
                         b_regularizer=b_regularizer, bias=bias,
                         input_shape=input_shape, name=name, **kwargs)
        self.dilation = (int(atrous_rate),)


class ShareConvolution2D(Convolution2D):
    """Conv2D with explicit pad_h/pad_w (reference
    `layers/ShareConvolution2D.scala` — BigDL's weight-sharing variant;
    on TPU all convs share weights, so only the padding semantics differ)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 init="glorot_uniform", activation=None, subsample=1,
                 pad_h: int = 0, pad_w: int = 0, w_regularizer=None,
                 b_regularizer=None, bias: bool = True, input_shape=None,
                 name=None, **kwargs):
        if kwargs.get("border_mode", "valid") != "valid":
            raise ValueError("ShareConvolution2D pads via pad_h/pad_w "
                             "only (like the reference); border_mode is "
                             "not supported")
        if kwargs.get("dim_ordering", "tf") != "tf":
            raise ValueError("ShareConvolution2D supports channels-last "
                             "(dim_ordering='tf') only")
        super().__init__(nb_filter, nb_row, nb_col, init=init,
                         activation=activation, subsample=subsample,
                         w_regularizer=w_regularizer,
                         b_regularizer=b_regularizer, bias=bias,
                         input_shape=input_shape, name=name, **kwargs)
        self.pad_h = int(pad_h)
        self.pad_w = int(pad_w)

    def _convolve(self, x, kernel):
        return jax.lax.conv_general_dilated(
            x, kernel.astype(x.dtype),
            window_strides=self.subsample,
            padding=((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
            rhs_dilation=self.dilation,
            dimension_numbers=self._dn())

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        h, w = input_shape[:2]
        oh = (h + 2 * self.pad_h - self.kernel_size[0]) \
            // self.subsample[0] + 1
        ow = (w + 2 * self.pad_w - self.kernel_size[1]) \
            // self.subsample[1] + 1
        return (oh, ow, self.nb_filter)


class ZeroPadding3D(KerasLayer):
    """Symmetric zero-pad of the 3 spatial dims (channels-last;
    reference `layers/ZeroPadding3D.scala`)."""

    def __init__(self, padding=(1, 1, 1), input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.padding = _norm_tuple(padding, 3, "padding")

    def call(self, params, x, *, training=False, rng=None):
        p = self.padding
        return jnp.pad(x, ((0, 0), (p[0], p[0]), (p[1], p[1]),
                           (p[2], p[2]), (0, 0)))

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        p = self.padding
        d, h, w, c = input_shape
        return (d + 2 * p[0], h + 2 * p[1], w + 2 * p[2], c)


class Cropping3D(KerasLayer):
    """Crop the 3 spatial dims (channels-last;
    reference `layers/Cropping3D.scala`)."""

    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)), input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if isinstance(cropping, int):
            cropping = ((cropping, cropping),) * 3
        self.cropping = tuple(
            (int(a), int(b)) for a, b in cropping)

    def call(self, params, x, *, training=False, rng=None):
        (d0, d1), (h0, h1), (w0, w1) = self.cropping
        return x[:, d0:x.shape[1] - d1, h0:x.shape[2] - h1,
                 w0:x.shape[3] - w1, :]

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        (d0, d1), (h0, h1), (w0, w1) = self.cropping
        d, h, w, c = input_shape
        return (d - d0 - d1, h - h0 - h1, w - w0 - w1, c)
