"""Sparse-input layers.

Reference surface: `Z/pipeline/api/keras/layers/{SparseDense,
SparseEmbedding}.scala` (BigDL `SparseLinear`/`LookupTableSparse` wrappers).

TPU-first divergence: XLA has no sparse tensors — the idiomatic encoding of
a batch of variable-length id lists is a dense padded (B, L) int array with
a pad id < 0, turned into gathers + masked reductions (static shapes, no
host round-trips). That is exactly what these layers consume.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops import activations, initializers, regularizers
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape


class SparseEmbedding(KerasLayer):
    """Embedding over padded id lists with sum/mean/sqrtn combining
    (reference `layers/SparseEmbedding.scala`). Input (B, L) ids, pad < 0;
    output (B, output_dim)."""

    def __init__(self, input_dim: int, output_dim: int,
                 combiner: str = "sum", max_norm: float = -1.0,
                 init="uniform", w_regularizer=None, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError("combiner must be sum|mean|sqrtn")
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.combiner = combiner
        self.max_norm = float(max_norm)
        self.kernel_init = initializers.get(init)
        self.w_regularizer = regularizers.get(w_regularizer)

    def build(self, rng, input_shape: Shape) -> dict:
        k, _ = jax.random.split(rng)
        return {"embeddings": self.kernel_init(
            k, (self.input_dim, self.output_dim))}

    def call(self, params, ids, *, training=False, rng=None):
        table = params["embeddings"]
        ids = ids.astype(jnp.int32)
        mask = (ids >= 0).astype(table.dtype)  # (B, L)
        vecs = table[jnp.clip(ids, 0, self.input_dim - 1)]  # (B, L, D)
        if self.max_norm > 0:
            # renormalise only the gathered rows: O(B*L*D), not O(V*D)
            norms = jnp.linalg.norm(vecs, axis=-1, keepdims=True)
            vecs = vecs * jnp.minimum(1.0, self.max_norm /
                                      jnp.maximum(norms, 1e-12))
        vecs = vecs * mask[..., None]
        total = jnp.sum(vecs, axis=1)
        count = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
        if self.combiner == "mean":
            return total / count
        if self.combiner == "sqrtn":
            return total / jnp.sqrt(count)
        return total

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return (self.output_dim,)

    def regularizers(self):
        return ([("embeddings", self.w_regularizer)]
                if self.w_regularizer is not None else [])


class SparseDense(KerasLayer):
    """Dense over a (possibly mostly-zero) input (reference
    `layers/SparseDense.scala`). On TPU the dense matmul IS the fast path —
    a gather-based sparse gemm would leave the MXU idle — so this is a
    Dense with the reference's arg surface (backward_start/backward_length
    are accepted for API parity; XLA's autodiff handles the backward)."""

    def __init__(self, output_dim: int, init="glorot_uniform",
                 activation=None, w_regularizer=None, b_regularizer=None,
                 backward_start: int = -1, backward_length: int = -1,
                 bias: bool = True, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_dim = int(output_dim)
        self.kernel_init = initializers.get(init)
        self.activation = activations.get(activation)
        self.w_regularizer = regularizers.get(w_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)
        self.backward_start = int(backward_start)
        self.backward_length = int(backward_length)
        self.bias = bias

    def build(self, rng, input_shape: Shape) -> dict:
        k, _ = jax.random.split(rng)
        params = {"kernel": self.kernel_init(
            k, (input_shape[-1], self.output_dim))}
        if self.bias:
            params["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        y = x @ params["kernel"].astype(x.dtype)
        if self.bias:
            y = y + params["bias"].astype(y.dtype)
        if self.activation is not None:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return tuple(input_shape[:-1]) + (self.output_dim,)

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("kernel", self.w_regularizer))
        if self.b_regularizer is not None and self.bias:
            out.append(("bias", self.b_regularizer))
        return out
