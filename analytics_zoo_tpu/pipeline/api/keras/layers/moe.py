"""Mixture-of-Experts FFN with expert parallelism.

Absent from the reference (like ring/Ulysses sequence parallelism —
SURVEY.md §2.10 lists EP as "NO"); first-class here because expert
parallelism is one of the shardings a TPU-native framework must scale
(round goals: dp/tp/sp/ep). Design is the XLA-friendly Switch
Transformer formulation:

- router: tokens → softmax over n_experts, top-1 gate;
- capacity: each expert takes at most ``capacity_factor · T/E`` tokens
  (overflow dropped — keeps every shape static for the compiler);
- dispatch/combine are one-hot einsums, NOT gathers — under a mesh
  with an ``expert`` axis and expert-stacked params sharded on it,
  GSPMD lowers them to all-to-alls over ICI;
- expert FFNs are ONE stacked einsum (E, d, h): no per-expert Python
  loop, one MXU-dense contraction.

Aux load-balancing loss (Switch eq. 4) is exposed via
``regularization_loss`` so the Estimator adds it automatically.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops import activations, initializers
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape


class MoE(KerasLayer):
    """Switch-style top-1 MoE FFN over (B, T, d) inputs.

    Params carry a leading expert axis; pass ``expert_axis="expert"``
    (with that axis in the mesh) to shard experts across devices —
    dispatch/combine become all-to-alls (expert parallelism).
    """

    # consumed by shard_params_ep: these params have a stacked leading
    # expert dim (routers and other layers replicate under EP)
    expert_stacked_params = ("w_in", "b_in", "w_out", "b_out")

    def __init__(self, n_experts: int, hidden_dim: int,
                 capacity_factor: float = 1.25,
                 activation="gelu", aux_loss_weight: float = 0.01,
                 init="glorot_uniform",
                 expert_axis: Optional[str] = None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.n_experts = int(n_experts)
        self.hidden_dim = int(hidden_dim)
        self.capacity_factor = float(capacity_factor)
        self.activation = activations.get(activation)
        self.aux_loss_weight = float(aux_loss_weight)
        self.kernel_init = initializers.get(init)
        self.expert_axis = expert_axis
        self._last_aux = None

    def build(self, rng, input_shape: Shape) -> dict:
        d = input_shape[-1]
        e, h = self.n_experts, self.hidden_dim
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            "router_kernel": self.kernel_init(k1, (d, e)),
            "w_in": self.kernel_init(k2, (e, d, h)),
            "b_in": jnp.zeros((e, h), jnp.float32),
            "w_out": self.kernel_init(k3, (e, h, d)),
            "b_out": jnp.zeros((e, d), jnp.float32),
        }

    def _maybe_shard(self, x, spec_axes):
        """Annotate expert-stacked intermediates so GSPMD keeps the
        expert dim on the expert axis (all-to-all at the boundaries)."""
        if not self.expert_axis:
            return x
        from analytics_zoo_tpu.common.nncontext import get_nncontext
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = get_nncontext().mesh
        if self.expert_axis not in mesh.axis_names:
            return x
        spec = [self.expert_axis if a == "E" else None
                for a in spec_axes]
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))

    def call(self, params, x, *, training=False, rng=None):
        b, t, d = x.shape
        e = self.n_experts
        cap = max(int(self.capacity_factor * t / e), 1)

        logits = x @ params["router_kernel"].astype(x.dtype)  # (B,T,E)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        gate = jnp.max(probs, axis=-1)                        # (B,T)
        expert_idx = jnp.argmax(probs, axis=-1)               # (B,T)

        # position of each token within its expert's queue
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        pos = jnp.cumsum(onehot, axis=1) * onehot              # (B,T,E)
        within_cap = (pos <= cap) & (onehot > 0)
        # dispatch tensor (B, T, E, C): token t → slot pos-1 of expert
        slot = jax.nn.one_hot(
            (pos - 1).astype(jnp.int32), cap, dtype=jnp.float32)
        dispatch = within_cap[..., None].astype(jnp.float32) * slot

        # (B,T,E,C) × (B,T,d) → (E, B, C, d): the all-to-all boundary.
        # Routing stats stay f32; the expert FFN — the layer's dominant
        # FLOPs — runs in the compute dtype (bf16 under the mixed
        # policy) so EP keeps the MXU 2x rate.
        cdt = x.dtype
        xe = jnp.einsum("btec,btd->ebcd", dispatch.astype(cdt), x)
        xe = self._maybe_shard(xe, "E***")
        h = jnp.einsum("ebcd,edh->ebch", xe,
                       params["w_in"].astype(cdt)) + \
            params["b_in"].astype(cdt)[:, None, None, :]
        h = self.activation(h) if self.activation else h
        ye = jnp.einsum("ebch,ehd->ebcd", h,
                        params["w_out"].astype(cdt)) + \
            params["b_out"].astype(cdt)[:, None, None, :]
        ye = self._maybe_shard(ye, "E***")

        combine = (dispatch * gate[..., None, None]).astype(cdt)
        y = jnp.einsum("btec,ebcd->btd", combine, ye)

        # Switch aux loss: E · Σ_e fraction_tokens_e · mean_prob_e
        frac = jnp.mean(onehot, axis=(0, 1))
        mean_p = jnp.mean(probs, axis=(0, 1))
        self._last_aux = e * jnp.sum(frac * mean_p)
        return y.astype(x.dtype)

    def regularization_loss(self, params) -> jnp.ndarray:
        # consume-once: the aux value is a tracer from the forward
        # trace; the Estimator reads it inside the SAME trace right
        # after apply(). An eager/out-of-trace read (leaked tracer)
        # falls back to 0 instead of crashing.
        aux, self._last_aux = self._last_aux, None
        if aux is None or self.aux_loss_weight == 0.0:
            return jnp.zeros((), jnp.float32)
        try:
            return self.aux_loss_weight * aux
        except Exception:
            from analytics_zoo_tpu.common.nncontext import logger
            logger.warning(
                "MoE aux loss dropped: regularization_loss was called "
                "outside the trace that ran forward (custom training "
                "loops must compute it in the same jit as apply)")
            return jnp.zeros((), jnp.float32)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return input_shape
