"""Normalization layers.

Reference surface: `Z/pipeline/api/keras/layers/BatchNormalization.scala`
(+ the internal LayerNorm used by `TransformerLayer.scala`/`BERT.scala`).

BatchNormalization is the one stateful layer in the framework: moving
mean/var live in ``params["_state"]`` and training-mode forward returns
their update through ``apply``'s second result (see engine.py contract).
Under pjit the batch statistics are computed over the *global* batch —
XLA inserts the cross-device all-reduce for the mean/var automatically
because the reduction crosses the sharded batch axis. This replaces the
reference's per-replica local statistics (BigDL replicas each normalize
their slice), and is strictly more accurate (syncBN semantics).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape


def bn_batch_stats(ssum, ssq, count, state, momentum):
    """Batch mean/var from moving-mean-SHIFTED sums ``Σ(x−mm)`` /
    ``Σ(x−mm)²`` plus the moving-average update — the single copy of
    the scheme, shared by :class:`BatchNormalization` and the fused
    ResNet bottleneck (`models/.../resnet.py`). The shift keeps
    E[x²]−E[x]² from cancelling when |mean| ≫ std; the moving mean is
    stop-gradded (it is frozen state, not a differentiable input)."""
    mm = jax.lax.stop_gradient(state["moving_mean"])
    d_mean = ssum / count
    d_sq = ssq / count
    mean = d_mean + mm
    var = jnp.maximum(d_sq - jnp.square(d_mean), 0.0)
    m = momentum
    updates = {"_state": {
        "moving_mean": m * state["moving_mean"] + (1 - m) * mean,
        "moving_var": m * state["moving_var"] + (1 - m) * var,
    }}
    return mean, var, updates


def bn_fold(mean, var, gamma, beta, epsilon):
    """Fold ``(x−mean)·rsqrt(var+eps)·γ+β`` into per-channel
    ``(scale, shift)`` for a single FMA apply (γ/β may be None)."""
    inv = jax.lax.rsqrt(var + epsilon)
    scale = inv * gamma if gamma is not None else inv
    shift = -mean * scale
    if beta is not None:
        shift = shift + beta
    return scale, shift


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 beta_init="zero", gamma_init="one", dim_ordering="tf",
                 center: bool = True, scale: bool = True,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.epsilon = float(epsilon)
        self.momentum = float(momentum)
        self.center = center
        self.scale = scale
        self.dim_ordering = dim_ordering

    def _feature_axis(self, ndim_with_batch: int) -> int:
        # channels-last ("tf") normalizes the trailing axis; "th" axis 1
        return (ndim_with_batch - 1) if self.dim_ordering == "tf" else 1

    def _num_features(self, input_shape: Shape) -> int:
        return (input_shape[-1] if self.dim_ordering == "tf"
                else input_shape[0])

    def build(self, rng, input_shape: Shape) -> dict:
        n = self._num_features(input_shape)
        params = {}
        if self.scale:
            params["gamma"] = jnp.ones((n,), jnp.float32)
        if self.center:
            params["beta"] = jnp.zeros((n,), jnp.float32)
        params["_state"] = {
            "moving_mean": jnp.zeros((n,), jnp.float32),
            "moving_var": jnp.ones((n,), jnp.float32),
        }
        return params

    def _reshape_stat(self, stat, x):
        axis = self._feature_axis(x.ndim)
        shape = [1] * x.ndim
        shape[axis] = stat.shape[0]
        return stat.reshape(shape)

    def apply(self, params, x, *, training=False, rng=None):
        axis = self._feature_axis(x.ndim)
        reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
        state = params["_state"]
        if training:
            # single pass over x: both reductions fuse into one
            # multi-output kernel reading x once (profiling showed BN
            # reductions, not convs, dominate the ResNet-50 step)
            shift0 = self._reshape_stat(
                jax.lax.stop_gradient(state["moving_mean"]), x)
            xf = x.astype(jnp.float32) - shift0
            count = float(np.prod([x.shape[a] for a in reduce_axes]))
            mean, var, updates = bn_batch_stats(
                jnp.sum(xf, axis=reduce_axes),
                jnp.sum(jnp.square(xf), axis=reduce_axes),
                count, state, self.momentum)
        else:
            mean, var = state["moving_mean"], state["moving_var"]
            updates = {}
        # fold (x-mean)*inv*gamma+beta into one per-element FMA: the
        # per-channel scale/shift vectors are computed in f32 off the
        # hot path, so the activation tensor is read once, written once
        scale, shift = bn_fold(
            mean, var, params["gamma"] if self.scale else None,
            params["beta"] if self.center else None, self.epsilon)
        y = x * self._reshape_stat(scale, x).astype(x.dtype) + \
            self._reshape_stat(shift, x).astype(x.dtype)
        return y, updates

    def call(self, params, x, *, training=False, rng=None):
        y, _ = self.apply(params, x, training=training, rng=rng)
        return y


class LayerNormalization(KerasLayer):
    """LayerNorm over the trailing axis (the internal norm of the
    reference's `TransformerLayer.scala`/`BERT.scala`)."""

    def __init__(self, epsilon: float = 1e-5, center: bool = True,
                 scale: bool = True, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.epsilon = float(epsilon)
        self.center = center
        self.scale = scale

    def build(self, rng, input_shape: Shape) -> dict:
        n = input_shape[-1]
        params = {}
        if self.scale:
            params["gamma"] = jnp.ones((n,), jnp.float32)
        if self.center:
            params["beta"] = jnp.zeros((n,), jnp.float32)
        return params

    def call(self, params, x, *, training=False, rng=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        if self.scale:
            y = y * params["gamma"].astype(y.dtype)
        if self.center:
            y = y + params["beta"].astype(y.dtype)
        return y


class WithinChannelLRN2D(KerasLayer):
    """Local response normalization within channels (reference
    `layers/WithinChannelLRN2D.scala`)."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.size = int(size)
        self.alpha = float(alpha)
        self.beta = float(beta)

    def call(self, params, x, *, training=False, rng=None):
        # NHWC: average x^2 over a size×size spatial window
        sq = jnp.square(x)
        window = (1, self.size, self.size, 1)
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, window, (1, 1, 1, 1), "SAME")
        counts = jax.lax.reduce_window(
            jnp.ones_like(x), 0.0, jax.lax.add, window, (1, 1, 1, 1),
            "SAME")
        denom = (1.0 + self.alpha * summed / counts) ** self.beta
        return x / denom
