"""Noise layers (reference `Z/pipeline/api/keras/layers/{GaussianNoise,
GaussianDropout,SpatialDropout1D,SpatialDropout2D,SpatialDropout3D}.scala`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer


class GaussianNoise(KerasLayer):
    def __init__(self, sigma: float, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.sigma = float(sigma)

    def call(self, params, x, *, training=False, rng=None):
        if not training:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: needs rng in training mode")
        return x + self.sigma * jax.random.normal(rng, x.shape, x.dtype)


class GaussianDropout(KerasLayer):
    def __init__(self, p: float, input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.p = float(p)

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.p <= 0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: needs rng in training mode")
        stddev = (self.p / (1.0 - self.p)) ** 0.5
        return x * (1.0 + stddev *
                    jax.random.normal(rng, x.shape, x.dtype))


class _SpatialDropoutND(KerasLayer):
    """Drop whole feature maps (channels-last)."""

    ndim = 1

    def __init__(self, p: float = 0.5, dim_ordering="tf", input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.p = float(p)
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.p <= 0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: needs rng in training mode")
        keep = 1.0 - self.p
        if self.dim_ordering == "tf":
            mask_shape = (x.shape[0],) + (1,) * self.ndim + (x.shape[-1],)
        else:
            mask_shape = (x.shape[0], x.shape[1]) + (1,) * self.ndim
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, x / keep, jnp.zeros_like(x))


class SpatialDropout1D(_SpatialDropoutND):
    ndim = 1


class SpatialDropout2D(_SpatialDropoutND):
    ndim = 2


class SpatialDropout3D(_SpatialDropoutND):
    ndim = 3
