"""Recurrent layers: SimpleRNN, LSTM, GRU, Bidirectional, TimeDistributed.

Reference surface: `Z/pipeline/api/keras/layers/{SimpleRNN,LSTM,GRU,
Bidirectional,TimeDistributed}.scala` (Keras-1 semantics: gate order i,f,c,o;
default inner activation hard_sigmoid).

TPU-first: the time loop is a `lax.scan` — one compiled step reused across
timesteps, with the (B, F)×(F, 4H) input projection hoisted *out* of the
scan as a single large (B·T) matmul so the MXU sees one big GEMM instead of
T small ones. No Python loops are traced.
"""

from __future__ import annotations

import copy

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops import activations, initializers, regularizers
from analytics_zoo_tpu.pipeline.api.keras.engine import KerasLayer, Shape


class _RNNBase(KerasLayer):
    n_gates = 1

    def __init__(self, output_dim: int, activation="tanh",
                 inner_activation="hard_sigmoid", init="glorot_uniform",
                 inner_init="orthogonal", return_sequences: bool = False,
                 go_backwards: bool = False, w_regularizer=None,
                 u_regularizer=None, b_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.output_dim = int(output_dim)
        self.activation = activations.get(activation) or activations.linear
        self.inner_activation = (activations.get(inner_activation)
                                 or activations.linear)
        self.kernel_init = initializers.get(init)
        self.inner_init = initializers.get(inner_init)
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.w_regularizer = regularizers.get(w_regularizer)
        self.u_regularizer = regularizers.get(u_regularizer)
        self.b_regularizer = regularizers.get(b_regularizer)

    def build(self, rng, input_shape: Shape) -> dict:
        in_dim = input_shape[-1]
        h = self.output_dim
        k1, k2, _ = jax.random.split(rng, 3)
        # per-gate blocks concatenated on the last axis
        kernel = self.kernel_init(k1, (in_dim, h * self.n_gates))
        recurrent = jnp.concatenate(
            [self.inner_init(jax.random.fold_in(k2, g), (h, h))
             for g in range(self.n_gates)], axis=-1)
        return {
            "kernel": kernel,
            "recurrent": recurrent,
            "bias": jnp.zeros((h * self.n_gates,), jnp.float32),
        }

    def initial_state(self, batch: int, dtype):
        return jnp.zeros((batch, self.output_dim), dtype)

    def step(self, params, carry, zx):
        """One timestep: carry, precomputed input projection → new carry,
        output."""
        raise NotImplementedError

    def call_with_state(self, params, x, initial_carry=None, *,
                        training=False, rng=None):
        """Run the RNN returning (sequence outputs (B, T, H), final
        carry). `initial_carry` enables encoder→decoder state handoff
        (the reference Seq2seq `Bridge` contract)."""
        if self.go_backwards:
            x = jnp.flip(x, axis=1)
        b = x.shape[0]
        # hoist input projection out of the scan: one (B·T, F)@(F, G·H) GEMM
        zx = x @ params["kernel"].astype(x.dtype) + \
            params["bias"].astype(x.dtype)
        zx_t = jnp.swapaxes(zx, 0, 1)  # (T, B, G·H)
        carry0 = (initial_carry if initial_carry is not None
                  else self.carry_init(b, x.dtype))

        def scan_fn(carry, z):
            new_carry, out = self.step(params, carry, z)
            return new_carry, out

        final_carry, outs = jax.lax.scan(scan_fn, carry0, zx_t)
        return jnp.swapaxes(outs, 0, 1), final_carry

    def call(self, params, x, *, training=False, rng=None):
        outs, _ = self.call_with_state(params, x, training=training,
                                       rng=rng)
        if self.return_sequences:
            return outs  # (B, T, H)
        return outs[:, -1]

    def carry_init(self, batch, dtype):
        return self.initial_state(batch, dtype)

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        t = input_shape[0]
        if self.return_sequences:
            return (t, self.output_dim)
        return (self.output_dim,)

    def regularizers(self):
        out = []
        if self.w_regularizer is not None:
            out.append(("kernel", self.w_regularizer))
        if self.u_regularizer is not None:
            out.append(("recurrent", self.u_regularizer))
        if self.b_regularizer is not None:
            out.append(("bias", self.b_regularizer))
        return out


class SimpleRNN(_RNNBase):
    """Vanilla RNN (reference `layers/SimpleRNN.scala`)."""

    n_gates = 1

    def step(self, params, h, z):
        u = params["recurrent"].astype(z.dtype)
        h_new = self.activation(z + h @ u)
        return h_new, h_new


class LSTM(_RNNBase):
    """Keras-1 LSTM, gate order i, f, c, o (reference
    `layers/LSTM.scala`)."""

    n_gates = 4

    def initial_state(self, batch, dtype):
        h = jnp.zeros((batch, self.output_dim), dtype)
        c = jnp.zeros((batch, self.output_dim), dtype)
        return (h, c)

    def step(self, params, carry, z):
        h, c = carry
        u = params["recurrent"].astype(z.dtype)
        gates = z + h @ u
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = self.inner_activation(i)
        f = self.inner_activation(f)
        g = self.activation(g)
        o = self.inner_activation(o)
        c_new = f * c + i * g
        h_new = o * self.activation(c_new)
        return (h_new, c_new), h_new


class GRU(_RNNBase):
    """Keras-1 GRU, gates z, r, h (reference `layers/GRU.scala`)."""

    n_gates = 3

    def step(self, params, h, zin):
        hdim = self.output_dim
        u = params["recurrent"].astype(zin.dtype)
        u_zr, u_h = u[:, :2 * hdim], u[:, 2 * hdim:]
        z_zr, z_h = zin[:, :2 * hdim], zin[:, 2 * hdim:]
        zr = self.inner_activation(z_zr + h @ u_zr)
        z, r = jnp.split(zr, 2, axis=-1)
        hh = self.activation(z_h + (r * h) @ u_h)
        h_new = z * h + (1.0 - z) * hh
        return h_new, h_new


class Bidirectional(KerasLayer):
    """Run a recurrent layer forward and backward, merging outputs
    (reference `layers/Bidirectional.scala`)."""

    def __init__(self, layer: _RNNBase, merge_mode: str = "concat",
                 input_shape=None, name=None, **kwargs):
        super().__init__(input_shape=input_shape or
                         layer._given_input_shape, name=name, **kwargs)
        if merge_mode not in ("concat", "sum", "mul", "ave"):
            raise ValueError(f"bad merge_mode {merge_mode}")
        self.merge_mode = merge_mode
        self.forward_layer = layer
        self.backward_layer = copy.deepcopy(layer)
        self.forward_layer.go_backwards = False
        self.backward_layer.go_backwards = True
        self.backward_layer.name = layer.name + "_bw"

    def build(self, rng, input_shape: Shape) -> dict:
        k1, k2 = jax.random.split(rng)
        return {
            "forward": self.forward_layer.init(k1, input_shape),
            "backward": self.backward_layer.init(k2, input_shape),
        }

    def call(self, params, x, *, training=False, rng=None):
        fwd = self.forward_layer.call(params["forward"], x,
                                      training=training, rng=rng)
        bwd = self.backward_layer.call(params["backward"], x,
                                       training=training, rng=rng)
        if self.forward_layer.return_sequences:
            bwd = jnp.flip(bwd, axis=1)  # re-align to forward time order
        if self.merge_mode == "concat":
            return jnp.concatenate([fwd, bwd], axis=-1)
        if self.merge_mode == "sum":
            return fwd + bwd
        if self.merge_mode == "mul":
            return fwd * bwd
        return (fwd + bwd) / 2.0

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        base = self.forward_layer.compute_output_shape(input_shape)
        if self.merge_mode == "concat":
            return tuple(base[:-1]) + (base[-1] * 2,)
        return base

    def regularizers(self):
        return []

    def regularization_loss(self, params):
        return (self.forward_layer.regularization_loss(
                    params.get("forward", {})) +
                self.backward_layer.regularization_loss(
                    params.get("backward", {})))


class TimeDistributed(KerasLayer):
    """Apply a layer to every timestep (reference
    `layers/TimeDistributed.scala`). Implemented by folding time into the
    batch dim — one big batched op instead of T small ones."""

    def __init__(self, layer: KerasLayer, input_shape=None, name=None,
                 **kwargs):
        super().__init__(input_shape=input_shape, name=name, **kwargs)
        self.layer = layer

    def build(self, rng, input_shape: Shape) -> dict:
        inner_shape = tuple(input_shape[1:])
        return {"layer": self.layer.init(rng, inner_shape)}

    def call(self, params, x, *, training=False, rng=None):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y = self.layer.call(params["layer"], flat, training=training,
                            rng=rng)
        return y.reshape((b, t) + y.shape[1:])

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        inner = self.layer.compute_output_shape(tuple(input_shape[1:]))
        return (input_shape[0],) + tuple(inner)

    def regularization_loss(self, params):
        return self.layer.regularization_loss(params.get("layer", {}))
