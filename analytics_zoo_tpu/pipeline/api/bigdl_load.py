"""BigDL / zoo-Keras saved-model importer.

Reference: ``Net.loadBigDL(path)`` / ``Net.load(path)``
(`Z/pipeline/api/Net.scala:91-118`) load BigDL ``.model`` protobuf
files — including the analytics-zoo Keras-style models saved by
``KerasNet.saveModel`` (`Topology.scala:754-775`). This importer reads
the same files through the self-contained :mod:`bigdl_pb` codec and
rebuilds them as native zoo `Sequential` models (channels-first layout,
since BigDL tensors are NCHW), with weights copied in — so the
reference's own pretrained/test models predict on TPU and can be
fine-tuned natively.

Supported module set: the BigDL nn layers used by the reference's model
zoo and test fixtures (Linear, SpatialConvolution/MaxPooling/
AveragePooling/BatchNormalization, Reshape/InferReshape/View,
activations, Dropout, LookupTable, Sequential, StaticGraph with a
linear topology, and the zoo keras wrapper layers). Anything else
raises `NotImplementedError` with the module type.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.pipeline.api import bigdl_pb as pb


def _attr_int(am, key, default=None):
    v = am.get(key)
    if v is None:
        return default
    for f in ("int32Value", "int64Value"):
        x = getattr(v, f)
        if x is not None:
            return int(x)
    return default


def _attr_bool(am, key, default=None):
    v = am.get(key)
    if v is None or v.boolValue is None:
        return default
    return bool(v.boolValue)


def _attr_float(am, key, default=None):
    v = am.get(key)
    if v is None:
        return default
    for f in ("floatValue", "doubleValue"):
        x = getattr(v, f)
        if x is not None:
            return float(x)
    return default


def _attr_ints(am, key):
    v = am.get(key)
    if v is None or v.arrayValue is None:
        return None
    a = v.arrayValue
    return [int(x) for x in (a.i32 or a.i64 or [])]


def _short(module_type: str) -> str:
    return (module_type or "").split(".")[-1]


_ACTIVATION_TYPES = {
    "Tanh": "tanh", "ReLU": "relu", "Sigmoid": "sigmoid",
    "LogSoftMax": "log_softmax", "SoftMax": "softmax",
    "SoftPlus": "softplus", "ELU": "elu", "HardSigmoid": "hard_sigmoid",
    "SoftSign": "softsign",
}

_SKIP_TYPES = {"Identity", "Input", "Echo", "Contiguous"}


class _Converted:
    """One imported layer + its weight assignments (param name →
    ndarray), applied after shape inference initializes the model."""

    def __init__(self, layer, weights: Optional[Dict[str, np.ndarray]]
                 = None):
        self.layer = layer
        self.weights = weights or {}


def _find_first(module: pb.BigDLModule, type_suffix: str) \
        -> Optional[pb.BigDLModule]:
    if _short(module.moduleType) == type_suffix:
        return module
    for s in module.subModules:
        hit = _find_first(s, type_suffix)
        if hit is not None:
            return hit
    return None


def _node_name(s: pb.BigDLModule) -> str:
    """Graph-node identity: explicit name, else BigDL's default
    SimpleName + namePostfix (how unnamed nodes appear in pre/next
    lists and ``*_edges`` attrs)."""
    if s.name:
        return s.name
    return _short(s.moduleType) + (s.namePostfix or "")


def _chain_order(graph: pb.BigDLModule) -> List[pb.BigDLModule]:
    """Order a StaticGraph's submodules along their (linear) pre/next
    chain. The serialized list is reverse-topological; reconstruct from
    preModules (reference builds graphs as node(prev) chains)."""
    subs = [s for s in graph.subModules]
    starts = [s for s in subs if not list(s.preModules)]
    if len(starts) != 1:
        raise NotImplementedError(
            "only linear BigDL graphs are importable (found "
            f"{len(starts)} start nodes)")
    order = [starts[0]]
    seen = {_node_name(starts[0])}
    while len(order) < len(subs):
        nxt = [s for s in subs
               if _node_name(s) not in seen and
               list(s.preModules) == [_node_name(order[-1])]]
        if len(nxt) != 1:
            raise NotImplementedError(
                f"non-linear BigDL graph at "
                f"{_node_name(order[-1])!r} ({len(nxt)} successors)")
        order.append(nxt[0])
        seen.add(_node_name(nxt[0]))
    return order


def _convert_module(m: pb.BigDLModule, table: pb.StorageTable) \
        -> List[_Converted]:
    """BigDLModule → list of imported layers (containers flatten)."""
    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    t = _short(m.moduleType)
    am = m.attr_map()
    name = m.name or None

    # containers --------------------------------------------------------
    if t in ("Sequential", "Model"):
        out: List[_Converted] = []
        for s in m.subModules:
            out.extend(_convert_module(s, table))
        return out
    if t == "StaticGraph":
        out = []
        for s in _chain_order(m):
            out.extend(_convert_module(s, table))
        return out
    if t in _SKIP_TYPES:
        return []

    # zoo keras wrapper layers (labor tree carries the weights) ---------
    if ".keras.layers." in (m.moduleType or ""):
        return _convert_keras_wrapper(m, table)

    w = table.tensor_to_numpy(m.weight)
    b = table.tensor_to_numpy(m.bias)
    if w is None and m.parameters:
        # newer BigDL serializes weights into `parameters` (field 16)
        # instead of the deprecated weight/bias fields
        w = table.tensor_to_numpy(m.parameters[0])
        if len(m.parameters) > 1:
            b = table.tensor_to_numpy(m.parameters[1])

    if t == "Linear":
        out_dim = _attr_int(am, "outputSize", w.shape[0] if w is not None
                            else None)
        lyr = L.Dense(out_dim, bias=b is not None, name=name)
        ws = {}
        if w is not None:
            ws["kernel"] = np.ascontiguousarray(w.T)
        if b is not None:
            ws["bias"] = b
        return [_Converted(lyr, ws)]

    if t == "SpatialConvolution":
        n_out = _attr_int(am, "nOutputPlane")
        kw = _attr_int(am, "kernelW")
        kh = _attr_int(am, "kernelH")
        sw = _attr_int(am, "strideW", 1)
        sh = _attr_int(am, "strideH", 1)
        pw = _attr_int(am, "padW", 0)
        ph = _attr_int(am, "padH", 0)
        group = _attr_int(am, "nGroup", 1)
        if group != 1:
            raise NotImplementedError(
                "grouped SpatialConvolution import not supported")
        layers = []
        border = "valid"
        if pw == -1 or ph == -1:
            border = "same"  # BigDL's SAME-pad convention
        elif pw or ph:
            layers.append(_Converted(
                L.ZeroPadding2D(padding=(ph, pw), dim_ordering="th")))
        lyr = L.Convolution2D(
            n_out, (kh, kw), subsample=(sh, sw), border_mode=border,
            dim_ordering="th", bias=b is not None, name=name)
        ws = {}
        if w is not None:
            if w.ndim == 5:  # [group, out, in, kH, kW]
                w = w.reshape(w.shape[0] * w.shape[1], *w.shape[2:])
            # OIHW → HWIO (the lax kernel layout)
            ws["kernel"] = np.ascontiguousarray(
                np.transpose(w, (2, 3, 1, 0)))
        if b is not None:
            ws["bias"] = b
        layers.append(_Converted(lyr, ws))
        return layers

    if t in ("SpatialMaxPooling", "SpatialAveragePooling"):
        kw = _attr_int(am, "kW")
        kh = _attr_int(am, "kH")
        sw = _attr_int(am, "dW", kw)
        sh = _attr_int(am, "dH", kh)
        pw = _attr_int(am, "padW", 0)
        ph = _attr_int(am, "padH", 0)
        if pw or ph:
            raise NotImplementedError(
                "padded BigDL pooling import not supported (explicit "
                "-inf/zero pad semantics differ)")
        cls = (L.MaxPooling2D if t == "SpatialMaxPooling"
               else L.AveragePooling2D)
        return [_Converted(cls(pool_size=(kh, kw), strides=(sh, sw),
                               dim_ordering="th", name=name))]

    if t in ("SpatialBatchNormalization", "BatchNormalization"):
        eps = _attr_float(am, "eps", 1e-5)
        mom = _attr_float(am, "momentum", 0.1)
        lyr = L.BatchNormalization(epsilon=eps, momentum=1.0 - mom,
                                   dim_ordering="th", name=name)
        ws: Dict[str, Any] = {}
        if w is not None:
            ws["gamma"] = w
        if b is not None:
            ws["beta"] = b
        rm = table.tensor_to_numpy(
            am["runningMean"].tensorValue) if "runningMean" in am \
            else None
        rv = table.tensor_to_numpy(
            am["runningVar"].tensorValue) if "runningVar" in am else None
        state = {}
        if rm is not None:
            state["moving_mean"] = rm
        if rv is not None:
            state["moving_var"] = rv
        if state:
            ws["_state"] = state
        return [_Converted(lyr, ws)]

    if t in ("Reshape", "InferReshape"):
        size = _attr_ints(am, "size") or []
        if t == "InferReshape" and (not size or -1 in size):
            # keras-wrapper plumbing reshape — flatten-to-2D
            return [_Converted(L.Flatten(name=name))] \
                if size == [-1] or not size else \
                [_Converted(L.Reshape(tuple(size), name=name))]
        return [_Converted(L.Reshape(tuple(size), name=name))]

    if t == "View":
        size = _attr_ints(am, "size") or []
        return [_Converted(L.Reshape(tuple(size), name=name))]

    if t == "Dropout":
        p = _attr_float(am, "initP", 0.5)
        return [_Converted(L.Dropout(p, name=name))]

    if t == "LookupTable":
        n_index = _attr_int(am, "nIndex")
        n_out = _attr_int(am, "nOutput")
        lyr = L.Embedding(n_index, n_out, name=name)
        ws = {"embeddings": w} if w is not None else {}
        return [_Converted(lyr, ws)]

    if t in _ACTIVATION_TYPES:
        return [_Converted(L.Activation(_ACTIVATION_TYPES[t],
                                        name=name))]

    raise NotImplementedError(
        f"BigDL module type {m.moduleType!r} has no TPU import mapping")


def _convert_keras_wrapper(m: pb.BigDLModule, table: pb.StorageTable) \
        -> List[_Converted]:
    """zoo keras layer wrapper → native keras layer, weights harvested
    from the serialized labor subtree."""
    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    t = _short(m.moduleType)
    am = m.attr_map()
    name = m.name or None
    act = None
    if "activation" in am and am["activation"].stringValue:
        act = am["activation"].stringValue

    if t == "Dense":
        out_dim = _attr_int(am, "outputDim")
        linear = _find_first(m, "Linear")
        ws = {}
        if linear is not None:
            w = table.tensor_to_numpy(linear.weight)
            b = table.tensor_to_numpy(linear.bias)
            if w is not None:
                ws["kernel"] = np.ascontiguousarray(w.T)
            if b is not None:
                ws["bias"] = b
        lyr = L.Dense(out_dim, activation=act, bias=bool(ws.get("bias")
                      is not None), name=name)
        return [_Converted(lyr, ws)]

    if t in ("Input", "InputLayer"):
        return []

    # generic fallback: convert the labor subtree
    out: List[_Converted] = []
    for s in m.subModules:
        out.extend(_convert_module(s, table))
    if not out:
        raise NotImplementedError(
            f"zoo keras layer {m.moduleType!r} has no TPU import "
            "mapping")
    return out


def load_bigdl(path: str, input_shape: Optional[Tuple[int, ...]] = None):
    """Load a BigDL/zoo-Keras ``.model`` file into a native
    `Sequential` (reference `Net.loadBigDL`, Net.scala:91).

    ``input_shape`` (sans batch, channels-first for images) may be
    omitted when the saved model carries its own leading Reshape or an
    inputShape attr.
    """
    root = pb.load_model(path)
    table = pb.StorageTable(root)
    converted = _convert_module(root, table)
    if not converted:
        raise ValueError(f"{path}: no importable layers")

    if input_shape is None:
        input_shape = _infer_input_shape(root, converted)
    if input_shape is None:
        raise ValueError(
            "input_shape could not be inferred from the saved model; "
            "pass input_shape=")

    from analytics_zoo_tpu.pipeline.api._import_common import \
        build_sequential
    return build_sequential([(c.layer, c.weights) for c in converted],
                            input_shape, "load_bigdl")


def _infer_input_shape(root: pb.BigDLModule, converted) -> \
        Optional[Tuple[int, ...]]:
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense, \
        Reshape

    # a keras-style saved model records inputShape on its layers
    def walk(m):
        am = m.attr_map()
        v = am.get("inputShape")
        if v is not None and v.shape is not None and v.shape.shapeValue:
            return tuple(int(x) for x in v.shape.shapeValue)
        for s in m.subModules:
            r = walk(s)
            if r is not None:
                return r
        return None

    shape = walk(root)
    if shape is not None:
        return shape
    first = converted[0].layer
    # a leading Reshape pins everything downstream; feed it flat input
    if isinstance(first, Reshape):
        return (int(np.prod(first.target_shape)),)
    if isinstance(first, Dense) and "kernel" in converted[0].weights:
        return (int(converted[0].weights["kernel"].shape[0]),)
    return None
