"""ONNX graph construction helpers (``onnx.helper`` analog).

Used by tests to fabricate golden models and by users to export simple
graphs. Mirrors the surface the reference's ONNX backend tests rely on
(`P/pipeline/api/onnx/onnx_loader.py:51` ``run_node`` op tests).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.pipeline.api.onnx.onnx_pb import (
    AttributeProto,
    GraphProto,
    ModelProto,
    NodeProto,
    OperatorSetIdProto,
    TensorProto,
    TensorShapeDim,
    TensorShapeProto,
    TensorTypeProto,
    TypeProto,
    ValueInfoProto,
    numpy_to_tensor,
)

__all__ = [
    "make_attribute", "make_node", "make_graph", "make_model",
    "make_tensor", "make_tensor_value_info",
]


def make_attribute(name: str, value: Any) -> AttributeProto:
    a = AttributeProto()
    a.name = name
    if isinstance(value, bool):
        a.i, a.type = int(value), AttributeProto.INT
    elif isinstance(value, (int, np.integer)):
        a.i, a.type = int(value), AttributeProto.INT
    elif isinstance(value, (float, np.floating)):
        a.f, a.type = float(value), AttributeProto.FLOAT
    elif isinstance(value, str):
        a.s, a.type = value.encode("utf-8"), AttributeProto.STRING
    elif isinstance(value, bytes):
        a.s, a.type = value, AttributeProto.STRING
    elif isinstance(value, TensorProto):
        a.t, a.type = value, AttributeProto.TENSOR
    elif isinstance(value, GraphProto):
        a.g, a.type = value, AttributeProto.GRAPH
    elif isinstance(value, np.ndarray):
        a.t, a.type = numpy_to_tensor(value), AttributeProto.TENSOR
    elif isinstance(value, (list, tuple)):
        if not value:
            a.ints, a.type = [], AttributeProto.INTS
        elif all(isinstance(v, (int, np.integer, bool)) for v in value):
            a.ints = [int(v) for v in value]
            a.type = AttributeProto.INTS
        elif all(isinstance(v, (int, float, np.floating, np.integer))
                 for v in value):
            a.floats = [float(v) for v in value]
            a.type = AttributeProto.FLOATS
        elif all(isinstance(v, (str, bytes)) for v in value):
            a.strings = [v.encode("utf-8") if isinstance(v, str) else v
                         for v in value]
            a.type = AttributeProto.STRINGS
        else:
            raise TypeError(f"mixed attribute list for {name}: {value!r}")
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return a


def attribute_value(a: AttributeProto) -> Any:
    """Decode an AttributeProto into a plain Python value."""
    t = a.type
    if t == AttributeProto.FLOAT:
        return float(a.f)
    if t == AttributeProto.INT:
        return int(a.i)
    if t == AttributeProto.STRING:
        return (a.s or b"").decode("utf-8")
    if t == AttributeProto.TENSOR:
        return a.t
    if t == AttributeProto.GRAPH:
        return a.g
    if t == AttributeProto.FLOATS:
        return [float(v) for v in a.floats]
    if t == AttributeProto.INTS:
        return [int(v) for v in a.ints]
    if t == AttributeProto.STRINGS:
        return [v.decode("utf-8") for v in a.strings]
    if t == AttributeProto.TENSORS:
        return list(a.tensors)
    # untyped attributes (some exporters omit .type): best effort
    if a.ints:
        return [int(v) for v in a.ints]
    if a.floats:
        return [float(v) for v in a.floats]
    if a.i is not None:
        return int(a.i)
    if a.f is not None:
        return float(a.f)
    if a.s is not None:
        return a.s.decode("utf-8")
    if a.t is not None:
        return a.t
    return None


def make_node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
              name: str = "", **attrs: Any) -> NodeProto:
    n = NodeProto()
    n.op_type = op_type
    n.input = list(inputs)
    n.output = list(outputs)
    n.name = name or None
    n.attribute = [make_attribute(k, v) for k, v in sorted(attrs.items())
                   if v is not None]
    return n


def make_tensor(name: str, arr: np.ndarray) -> TensorProto:
    return numpy_to_tensor(np.asarray(arr), name)


def make_tensor_value_info(name: str, elem_type: int,
                           shape: Optional[Sequence] = None
                           ) -> ValueInfoProto:
    vi = ValueInfoProto()
    vi.name = name
    tt = TensorTypeProto()
    tt.elem_type = elem_type
    if shape is not None:
        sp = TensorShapeProto()
        for d in shape:
            dim = TensorShapeDim()
            if isinstance(d, str):
                dim.dim_param = d
            elif d is not None:
                dim.dim_value = int(d)
            sp.dim.append(dim)
        tt.shape = sp
    ty = TypeProto()
    ty.tensor_type = tt
    vi.type = ty
    return vi


def make_graph(nodes: Sequence[NodeProto], name: str,
               inputs: Sequence[ValueInfoProto],
               outputs: Sequence[ValueInfoProto],
               initializer: Sequence[TensorProto] = ()) -> GraphProto:
    g = GraphProto()
    g.node = list(nodes)
    g.name = name
    g.input = list(inputs)
    g.output = list(outputs)
    g.initializer = list(initializer)
    return g


def make_model(graph: GraphProto, opset_version: int = 13,
               producer_name: str = "analytics-zoo-tpu") -> ModelProto:
    m = ModelProto()
    m.ir_version = 8
    m.producer_name = producer_name
    m.graph = graph
    op = OperatorSetIdProto()
    op.domain = ""
    op.version = opset_version
    m.opset_import = [op]
    return m
