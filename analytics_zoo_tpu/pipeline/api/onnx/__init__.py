"""ONNX import (reference `P/pipeline/api/onnx/`): self-contained proto
codec + graph-to-XLA importer; no external ``onnx`` dependency."""

from analytics_zoo_tpu.pipeline.api.onnx import onnx_pb  # noqa: F401
from analytics_zoo_tpu.pipeline.api.onnx.onnx_pb import (  # noqa: F401
    ModelProto,
    TensorProto,
    load_model,
    save_model,
)

__all__ = ["onnx_pb", "ModelProto", "TensorProto", "load_model",
           "save_model", "OnnxLoader", "helper"]


def __getattr__(name):
    # lazy to avoid importing jax machinery for proto-only use
    import importlib
    if name == "OnnxLoader":
        mod = importlib.import_module(
            "analytics_zoo_tpu.pipeline.api.onnx.onnx_loader")
        return mod.OnnxLoader
    if name == "helper":
        return importlib.import_module(
            "analytics_zoo_tpu.pipeline.api.onnx.helper")
    raise AttributeError(name)
