"""ONNX importer: ONNX graph → jittable zoo model.

Reference analog: `P/pipeline/api/onnx/onnx_loader.py:32-72` +
`onnx/mapper/*.py` (~40 op mappers onto zoo Keras layers). The TPU-first
design differs deliberately: instead of rebuilding the graph out of
Keras layer objects, the importer produces an :class:`OnnxGraphLayer`
whose ``call`` interprets the node list with jax.numpy/lax ops — the
whole graph traces into ONE XLA program (fused, MXU-friendly), and the
float initializers become trainable parameters so imported models can
be fine-tuned with the standard `Estimator`.

`OnnxLoader.run_node` executes a single NodeProto for per-op backend
tests, mirroring the reference's ONNX backend-test hook
(`onnx_loader.py:51`).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from analytics_zoo_tpu.pipeline.api.keras.engine import (
    KerasLayer,
    as_shape,
    unique_name,
)
from analytics_zoo_tpu.pipeline.api.onnx import onnx_pb
from analytics_zoo_tpu.pipeline.api.onnx.helper import attribute_value
from analytics_zoo_tpu.pipeline.api.onnx.onnx_pb import (
    ModelProto,
    NodeProto,
    tensor_to_numpy,
)

__all__ = ["OnnxLoader", "OnnxGraphLayer", "load", "run_node"]


def _attrs(node: NodeProto) -> Dict[str, Any]:
    return {a.name: attribute_value(a) for a in node.attribute}


def _static(x) -> np.ndarray:
    """Materialize a graph value that MUST be compile-time static
    (Reshape target shape, Slice indices, ...)."""
    if isinstance(x, (np.ndarray, np.generic)):
        return np.asarray(x)
    if isinstance(x, jax.Array):
        try:
            return np.asarray(x)
        except Exception as e:  # traced value — data-dependent shape
            raise ValueError(
                "ONNX graph uses a data-dependent shape operand; XLA "
                "requires static shapes") from e
    return np.asarray(x)


# -- op registry --------------------------------------------------------------

_OPS: Dict[str, Callable] = {}


def _register(*names: str):
    def deco(fn):
        for n in names:
            _OPS[n] = fn
        return fn
    return deco


def _pair_pads(pads: Sequence[int], n_spatial: int):
    """ONNX pads [b1..bn, e1..en] → [(b1,e1)..(bn,en)]."""
    if not pads:
        return [(0, 0)] * n_spatial
    return [(int(pads[i]), int(pads[i + n_spatial]))
            for i in range(n_spatial)]


def _auto_pads(auto_pad: str, in_spatial, kernel, strides, dilations):
    out = []
    for i, (s, k, st, d) in enumerate(
            zip(in_spatial, kernel, strides, dilations)):
        eff_k = (k - 1) * d + 1
        out_dim = -(-s // st)  # ceil
        pad = max(0, (out_dim - 1) * st + eff_k - s)
        if auto_pad == "SAME_UPPER":
            out.append((pad // 2, pad - pad // 2))
        else:  # SAME_LOWER
            out.append((pad - pad // 2, pad // 2))
    return out


# elementwise / unary
_register("Add")(lambda a, i: i[0] + i[1])
_register("Sub")(lambda a, i: i[0] - i[1])
_register("Mul")(lambda a, i: i[0] * i[1])
_register("Div")(lambda a, i: i[0] / i[1])
_register("Pow")(lambda a, i: jnp.power(i[0], i[1].astype(i[0].dtype)))
_register("Sqrt")(lambda a, i: jnp.sqrt(i[0]))
_register("Exp")(lambda a, i: jnp.exp(i[0]))
_register("Log")(lambda a, i: jnp.log(i[0]))
_register("Abs")(lambda a, i: jnp.abs(i[0]))
_register("Neg")(lambda a, i: -i[0])
_register("Sign")(lambda a, i: jnp.sign(i[0]))
_register("Sin")(lambda a, i: jnp.sin(i[0]))
_register("Cos")(lambda a, i: jnp.cos(i[0]))
_register("Tan")(lambda a, i: jnp.tan(i[0]))
_register("Asin")(lambda a, i: jnp.arcsin(i[0]))
_register("Acos")(lambda a, i: jnp.arccos(i[0]))
_register("Atan")(lambda a, i: jnp.arctan(i[0]))
_register("Sinh")(lambda a, i: jnp.sinh(i[0]))
_register("Cosh")(lambda a, i: jnp.cosh(i[0]))
_register("Asinh")(lambda a, i: jnp.arcsinh(i[0]))
_register("Acosh")(lambda a, i: jnp.arccosh(i[0]))
_register("Atanh")(lambda a, i: jnp.arctanh(i[0]))
_register("Floor")(lambda a, i: jnp.floor(i[0]))
_register("Ceil")(lambda a, i: jnp.ceil(i[0]))
_register("Round")(lambda a, i: jnp.round(i[0]))
_register("Reciprocal")(lambda a, i: 1.0 / i[0])
_register("Erf")(lambda a, i: jax.scipy.special.erf(i[0]))
_register("Identity")(lambda a, i: i[0])
_register("Sum")(lambda a, i: sum(i[1:], i[0]))
_register("Max")(lambda a, i: jnp.stack(
    jnp.broadcast_arrays(*i)).max(0) if len(i) > 1 else i[0])
_register("Min")(lambda a, i: jnp.stack(
    jnp.broadcast_arrays(*i)).min(0) if len(i) > 1 else i[0])
_register("Mean")(lambda a, i: jnp.stack(
    jnp.broadcast_arrays(*i)).mean(0) if len(i) > 1 else i[0])

# comparisons / logic
_register("Equal")(lambda a, i: i[0] == i[1])
_register("Greater")(lambda a, i: i[0] > i[1])
_register("GreaterOrEqual")(lambda a, i: i[0] >= i[1])
_register("Less")(lambda a, i: i[0] < i[1])
_register("LessOrEqual")(lambda a, i: i[0] <= i[1])
_register("And")(lambda a, i: jnp.logical_and(i[0], i[1]))
_register("Or")(lambda a, i: jnp.logical_or(i[0], i[1]))
_register("Not")(lambda a, i: jnp.logical_not(i[0]))
_register("Where")(lambda a, i: jnp.where(i[0], i[1], i[2]))

# activations
_register("Relu")(lambda a, i: jax.nn.relu(i[0]))
_register("LeakyRelu")(
    lambda a, i: jax.nn.leaky_relu(i[0], a.get("alpha", 0.01)))
_register("PRelu")(lambda a, i: jnp.where(i[0] >= 0, i[0], i[1] * i[0]))
_register("Sigmoid")(lambda a, i: jax.nn.sigmoid(i[0]))
_register("HardSigmoid")(lambda a, i: jnp.clip(
    a.get("alpha", 0.2) * i[0] + a.get("beta", 0.5), 0.0, 1.0))
_register("Tanh")(lambda a, i: jnp.tanh(i[0]))
def _softmax_family(jfn):
    def fn(a, i):
        x = i[0]
        if a.get("__opset__", 13) >= 13:
            return jfn(x, a.get("axis", -1))
        # opset<13: default axis=1, flatten-to-2D coercion semantics
        axis = a.get("axis", 1) % x.ndim
        lead = int(np.prod(x.shape[:axis], dtype=np.int64)) if axis \
            else 1
        flat = x.reshape((lead, -1))
        return jfn(flat, -1).reshape(x.shape)
    return fn


_register("Softmax")(_softmax_family(jax.nn.softmax))
_register("LogSoftmax")(_softmax_family(jax.nn.log_softmax))
_register("Elu")(lambda a, i: jnp.where(
    i[0] > 0, i[0], a.get("alpha", 1.0) * (jnp.exp(i[0]) - 1)))
_register("Selu")(lambda a, i: a.get("gamma", 1.0507009873554805) * jnp.where(
    i[0] > 0, i[0],
    a.get("alpha", 1.6732632423543772) * (jnp.exp(i[0]) - 1)))
_register("Softplus")(lambda a, i: jax.nn.softplus(i[0]))
_register("Softsign")(lambda a, i: i[0] / (1 + jnp.abs(i[0])))
_register("ThresholdedRelu")(lambda a, i: jnp.where(
    i[0] > a.get("alpha", 1.0), i[0], 0.0))
_register("Gelu")(lambda a, i: jax.nn.gelu(
    i[0], approximate=a.get("approximate", "none") == "tanh"))


@_register("Clip")
def _clip(a, i):
    lo = a.get("min") if len(i) < 2 or i[1] is None else i[1]
    hi = a.get("max") if len(i) < 3 or i[2] is None else i[2]
    x = i[0]
    if lo is not None:
        x = jnp.maximum(x, lo)
    if hi is not None:
        x = jnp.minimum(x, hi)
    return x


# linear algebra
@_register("Gemm")
def _gemm(a, i):
    x, w = i[0], i[1]
    if a.get("transA", 0):
        x = x.T
    if a.get("transB", 0):
        w = w.T
    y = a.get("alpha", 1.0) * (x @ w)
    if len(i) > 2 and i[2] is not None:
        y = y + a.get("beta", 1.0) * i[2]
    return y


_register("MatMul")(lambda a, i: i[0] @ i[1])


# convolution
def _conv_core(a, x, w, preferred=None):
    """The shared NCHW conv lowering (attrs: kernel/strides/dilations/
    group/pads, SAME_* auto-pad). ``preferred`` sets the accumulator
    dtype (int32 for the quantized variants)."""
    n_sp = x.ndim - 2
    kernel = a.get("kernel_shape", list(w.shape[2:]))
    strides = a.get("strides", [1] * n_sp)
    dilations = a.get("dilations", [1] * n_sp)
    group = a.get("group", 1)
    auto_pad = a.get("auto_pad", "NOTSET")
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        padding = _auto_pads(auto_pad, x.shape[2:], kernel, strides,
                             dilations)
    elif auto_pad == "VALID":
        padding = [(0, 0)] * n_sp
    else:
        padding = _pair_pads(a.get("pads", []), n_sp)
    sp = "DHW"[-n_sp:] if n_sp <= 3 else None
    if sp is None:
        raise ValueError(f"Conv with {n_sp} spatial dims unsupported")
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, ("NC" + sp, "OI" + sp, "NC" + sp))
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=group,
        preferred_element_type=preferred)


@_register("Conv")
def _conv(a, i):
    x, w = i[0], i[1]
    y = _conv_core(a, x, w.astype(x.dtype))
    if len(i) > 2 and i[2] is not None:
        y = y + i[2].reshape((1, -1) + (1,) * (x.ndim - 2))
    return y


def _zp_sub(x, zp, channel_axis=None):
    """int32 tensor minus its zero point; a 1-D per-channel zp
    aligns on ``channel_axis``."""
    x = jnp.asarray(x).astype(jnp.int32)
    if zp is None:
        return x
    zp = jnp.asarray(zp).astype(jnp.int32)
    if channel_axis is not None:
        zp = _per_axis(zp, x.ndim, channel_axis)
    return x - zp


def _requantize(y, y_zp):
    """Round, shift by the output zero point, saturate to its dtype
    (shared by every QLinear* op)."""
    zp = jnp.asarray(y_zp)
    info = jnp.iinfo(zp.dtype)
    return jnp.clip(jnp.round(y) + zp.astype(jnp.float32),
                    info.min, info.max).astype(zp.dtype)


@_register("ConvInteger")
def _conv_integer(a, i):
    x, w = i[0], i[1]
    xz = i[2] if len(i) > 2 else None
    wz = i[3] if len(i) > 3 else None
    return _conv_core(a, _zp_sub(x, xz), _zp_sub(w, wz, 0),
                      preferred=jnp.int32)


@_register("MatMulInteger")
def _matmul_integer(a, i):
    x, w = jnp.asarray(i[0]), jnp.asarray(i[1])
    xz = i[2] if len(i) > 2 else None
    wz = i[3] if len(i) > 3 else None
    # a-side 1-D zero point is PER ROW (second-to-last axis)
    return jnp.matmul(_zp_sub(x, xz, channel_axis=x.ndim - 2),
                      _zp_sub(w, wz),
                      preferred_element_type=jnp.int32)


@_register("QLinearConv")
def _qlinear_conv(a, i):
    (x, x_scale, x_zp, w, w_scale, w_zp,
     y_scale, y_zp) = i[:8]
    bias = i[8] if len(i) > 8 and i[8] is not None else None
    acc = _conv_core(a, _zp_sub(x, x_zp), _zp_sub(w, w_zp, 0),
                     preferred=jnp.int32)
    n_sp = jnp.asarray(x).ndim - 2
    if bias is not None:   # int32 bias at scale x_scale*w_scale
        acc = acc + jnp.asarray(bias).astype(jnp.int32).reshape(
            (1, -1) + (1,) * n_sp)
    ws = _per_axis(w_scale, n_sp + 2, 1)   # per-output-channel
    y = acc.astype(jnp.float32) * (
        jnp.asarray(x_scale) * ws / jnp.asarray(y_scale))
    return _requantize(y, y_zp)


@_register("ConvTranspose")
def _conv_transpose(a, i):
    x, w = i[0], i[1]  # w: (C_in, C_out/group, kH, kW)
    n_sp = x.ndim - 2
    strides = a.get("strides", [1] * n_sp)
    dilations = a.get("dilations", [1] * n_sp)
    group = a.get("group", 1)
    out_pad = a.get("output_padding", [0] * n_sp)
    kernel = list(w.shape[2:])
    auto_pad = a.get("auto_pad", "NOTSET")
    out_shape_attr = a.get("output_shape")
    if out_shape_attr or auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        # ONNX spec: total_padding = stride*(in-1) + out_pad + eff_k - out
        target = out_shape_attr or [s * st for s, st in
                                    zip(x.shape[2:], strides)]
        pads = []
        for s, st, k, d, op, ot in zip(x.shape[2:], strides, kernel,
                                       dilations, out_pad, target):
            total = st * (s - 1) + op + (k - 1) * d + 1 - ot
            total = max(total, 0)
            if auto_pad == "SAME_LOWER":
                pads.append((total - total // 2, total // 2))
            else:
                pads.append((total // 2, total - total // 2))
    else:
        pads = _pair_pads(a.get("pads", []), n_sp)
    # gradient-of-conv formulation: lhs-dilate x by stride, convolve with
    # spatially-flipped kernel, pad so that
    # out = (in-1)*stride + eff_k - pad_b - pad_e + out_pad
    eff_k = [(k - 1) * d + 1 for k, d in zip(kernel, dilations)]
    padding = [(ek - 1 - pb, ek - 1 - pe + op)
               for ek, (pb, pe), op in zip(eff_k, pads, out_pad)]
    sp = "DHW"[-n_sp:]
    # w (I, O/g, ...) → flip spatial, swap to (O, I/g, ...) per group
    w_flipped = jnp.flip(w, axis=tuple(range(2, w.ndim)))
    if group != 1:
        ci, co_g = w.shape[0], w.shape[1]
        w_g = w_flipped.reshape((group, ci // group, co_g) + w.shape[2:])
        w_g = jnp.swapaxes(w_g, 1, 2)
        w_t = w_g.reshape((group * co_g, ci // group) + w.shape[2:])
    else:
        w_t = jnp.swapaxes(w_flipped, 0, 1)
    dn = lax.conv_dimension_numbers(
        x.shape, w_t.shape, ("NC" + sp, "OI" + sp, "NC" + sp))
    y = lax.conv_general_dilated(
        x, w_t.astype(x.dtype), window_strides=[1] * n_sp, padding=padding,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=group)
    if len(i) > 2 and i[2] is not None:
        y = y + i[2].reshape((1, -1) + (1,) * n_sp)
    return y


# pooling
from analytics_zoo_tpu.common.utils import ceil_pool_extra \
    as _ceil_extra  # shared with the torch importer


def _pool_common(a, x, reducer, init):
    n_sp = x.ndim - 2
    kernel = a["kernel_shape"]
    strides = a.get("strides", [1] * n_sp)
    dilations = a.get("dilations", [1] * n_sp)
    auto_pad = a.get("auto_pad", "NOTSET")
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        padding = _auto_pads(auto_pad, x.shape[2:], kernel, strides,
                             dilations)
    elif auto_pad == "VALID":
        padding = [(0, 0)] * n_sp
    else:
        padding = _pair_pads(a.get("pads", []), n_sp)
    if a.get("ceil_mode", 0):
        # extend the trailing padding so floor windows realize the
        # ceil output count (pad cells take `init`: -inf for max,
        # 0 for the sum/count passes); last-window rule matches
        # torch/onnxruntime (dropped when starting past input+lo pad)
        padding = [
            (lo, hi + _ceil_extra(d, (k - 1) * dl + 1, st, lo, hi))
            for d, k, st, dl, (lo, hi) in zip(
                x.shape[2:], kernel, strides, dilations, padding)]
    dims = (1, 1) + tuple(kernel)
    strd = (1, 1) + tuple(strides)
    dil = (1, 1) + tuple(dilations)
    pad = ((0, 0), (0, 0)) + tuple(padding)
    return lax.reduce_window(x, init, reducer, dims, strd, pad,
                             window_dilation=dil), padding


@_register("MaxPool")
def _maxpool(a, i):
    y, _ = _pool_common(a, i[0], lax.max, -jnp.inf)
    return y


@_register("AveragePool")
def _avgpool(a, i):
    x = i[0]
    if a.get("count_include_pad", 0) and a.get("ceil_mode", 0):
        raise NotImplementedError(
            "AveragePool ceil_mode with count_include_pad (divisor "
            "treatment of the ceil extension is runtime-ambiguous)")
    y, padding = _pool_common(a, x, lax.add, 0.0)
    if a.get("count_include_pad", 0):
        denom = float(np.prod(a["kernel_shape"]))
        return y / denom
    ones = jnp.ones(x.shape, x.dtype)
    counts, _ = _pool_common(a, ones, lax.add, 0.0)
    return y / counts


_register("GlobalAveragePool")(
    lambda a, i: i[0].mean(axis=tuple(range(2, i[0].ndim)), keepdims=True))
_register("GlobalMaxPool")(
    lambda a, i: i[0].max(axis=tuple(range(2, i[0].ndim)), keepdims=True))


# normalization
@_register("BatchNormalization")
def _batchnorm(a, i):
    x, scale, bias, mean, var = i[:5]
    eps = a.get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(x.dtype)
    return (x - mean.reshape(shape)) * inv.reshape(shape) * \
        scale.reshape(shape) + bias.reshape(shape)


@_register("InstanceNormalization")
def _instancenorm(a, i):
    x, scale, bias = i[:3]
    eps = a.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = x.mean(axes, keepdims=True)
    var = x.var(axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * lax.rsqrt(var + eps) * scale.reshape(shape) + \
        bias.reshape(shape)


@_register("LayerNormalization")
def _layernorm(a, i):
    x, scale = i[0], i[1]
    bias = i[2] if len(i) > 2 and i[2] is not None else None
    axis = a.get("axis", -1)
    eps = a.get("epsilon", 1e-5)
    axes = tuple(range(axis % x.ndim, x.ndim))
    mean = x.mean(axes, keepdims=True)
    var = x.var(axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps) * scale
    return y + bias if bias is not None else y


@_register("LRN")
def _lrn(a, i):
    x = i[0]
    size = a["size"]
    alpha, beta, bias = a.get("alpha", 1e-4), a.get("beta", 0.75), \
        a.get("bias", 1.0)
    sq = x * x
    half = (size - 1) // 2
    pad = ((0, 0), (half, size - 1 - half)) + ((0, 0),) * (x.ndim - 2)
    window = (1, size) + (1,) * (x.ndim - 2)
    acc = lax.reduce_window(sq, 0.0, lax.add, window, (1,) * x.ndim, pad)
    return x / jnp.power(bias + alpha / size * acc, beta)


# shape ops
@_register("Reshape")
def _reshape(a, i):
    shape = [int(v) for v in _static(i[1])] if len(i) > 1 else a["shape"]
    x = i[0]
    out = []
    for idx, s in enumerate(shape):
        if s == 0 and not a.get("allowzero", 0):
            out.append(x.shape[idx])
        else:
            out.append(int(s))
    return x.reshape(out)


@_register("Flatten")
def _flatten(a, i):
    axis = a.get("axis", 1)
    if axis < 0:  # ONNX: negative axis means axis + rank
        axis += i[0].ndim
    lead = int(np.prod(i[0].shape[:axis], dtype=np.int64)) if axis else 1
    return i[0].reshape((lead, -1))


_register("Transpose")(lambda a, i: jnp.transpose(
    i[0], a.get("perm") or tuple(reversed(range(i[0].ndim)))))


@_register("Squeeze")
def _squeeze(a, i):
    axes = ([int(v) for v in _static(i[1])] if len(i) > 1 and
            i[1] is not None else a.get("axes"))
    return jnp.squeeze(i[0], tuple(axes) if axes else None)


@_register("Unsqueeze")
def _unsqueeze(a, i):
    axes = ([int(v) for v in _static(i[1])] if len(i) > 1 and
            i[1] is not None else a["axes"])
    x = i[0]
    out_rank = x.ndim + len(axes)  # negative axes index the OUTPUT rank
    for ax in sorted(ax % out_rank for ax in axes):
        x = jnp.expand_dims(x, ax)
    return x


_register("Concat")(lambda a, i: jnp.concatenate(i, axis=a["axis"]))


@_register("Split")
def _split(a, i):
    x = i[0]
    axis = a.get("axis", 0)
    if len(i) > 1 and i[1] is not None:
        sizes = [int(v) for v in _static(i[1])]
    elif "split" in a:
        sizes = a["split"]
    else:
        # equal split; part count = node output count (opset<18 default),
        # injected as num_outputs by the interpreter/run_node
        n = a["num_outputs"]
        chunk = -(-x.shape[axis] // n)  # ceil; last chunk may be smaller
        sizes = [chunk] * (n - 1) + [x.shape[axis] - chunk * (n - 1)]
    offs = np.cumsum([0] + list(sizes))
    return tuple(lax.slice_in_dim(x, int(offs[k]), int(offs[k + 1]),
                                  axis=axis)
                 for k in range(len(sizes)))


@_register("Slice")
def _slice(a, i):
    x = i[0]
    if len(i) > 1:  # opset >= 10: starts/ends/axes/steps as inputs
        starts = [int(v) for v in _static(i[1])]
        ends = [int(v) for v in _static(i[2])]
        axes = ([int(v) for v in _static(i[3])]
                if len(i) > 3 and i[3] is not None
                else list(range(len(starts))))
        steps = ([int(v) for v in _static(i[4])]
                 if len(i) > 4 and i[4] is not None else [1] * len(starts))
    else:  # opset 9: attributes
        starts, ends = a["starts"], a["ends"]
        axes = a.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    slices = [slice(None)] * x.ndim
    int64_min = -(1 << 63)
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        ax = ax % x.ndim
        dim = x.shape[ax]
        if sp > 0:
            lo = max(st + dim, 0) if st < 0 else min(st, dim)
            if en >= (1 << 31) - 1:
                hi = dim
            else:
                hi = max(en + dim, 0) if en < 0 else min(en, dim)
            slices[ax] = slice(lo, hi, sp)
        else:  # negative step: stop=None when the slice runs through 0
            lo = max(st + dim, 0) if st < 0 else min(st, dim - 1)
            if en == int64_min or en + dim < 0:
                hi = None
            elif en < 0:
                hi = en + dim
            else:
                hi = min(en, dim)
            slices[ax] = slice(lo, hi, sp)
    return x[tuple(slices)]


_register("Gather")(lambda a, i: jnp.take(
    i[0], _as_index(i[1]), axis=a.get("axis", 0)))


def _as_index(v):
    return v.astype(jnp.int32) if hasattr(v, "astype") else v


@_register("GatherElements")
def _gather_elements(a, i):
    return jnp.take_along_axis(i[0], _as_index(i[1]),
                               axis=a.get("axis", 0))


@_register("Expand")
def _expand(a, i):
    target = [int(v) for v in _static(i[1])]
    x = i[0]
    # ONNX Expand is numpy-style broadcast to a mutually-broadcast shape
    shape = list(np.broadcast_shapes(tuple(x.shape), tuple(target)))
    return jnp.broadcast_to(x, shape)


@_register("Tile")
def _tile(a, i):
    return jnp.tile(i[0], [int(v) for v in _static(i[1])])


@_register("Pad")
def _pad(a, i):
    x = i[0]
    mode = a.get("mode", "constant")
    pads = ([int(v) for v in _static(i[1])] if len(i) > 1 and
            i[1] is not None else a["pads"])
    value = 0.0
    if len(i) > 2 and i[2] is not None:
        value = float(_static(i[2]))
    elif "value" in a:
        value = a["value"]
    n = x.ndim
    pairs = [(pads[k], pads[k + n]) for k in range(n)]
    # ONNX allows negative pads = cropping; jnp.pad does not
    pos = [(max(b, 0), max(e, 0)) for b, e in pairs]
    if mode == "constant":
        x = jnp.pad(x, pos, constant_values=value)
    else:
        jmode = {"reflect": "reflect", "edge": "edge", "wrap": "wrap"}[mode]
        x = jnp.pad(x, pos, mode=jmode)
    if any(b < 0 or e < 0 for b, e in pairs):
        crops = tuple(
            slice(-min(b, 0), x.shape[k] + min(e, 0))
            for k, (b, e) in enumerate(pairs))
        x = x[crops]
    return x


@_register("Shape")
def _shape(a, i):
    shape = np.asarray(i[0].shape, np.int64)
    start = a.get("start", 0)
    end = a.get("end")
    return shape[start:end]


@_register("ConstantOfShape")
def _constant_of_shape(a, i):
    shape = [int(v) for v in _static(i[0])]
    t = a.get("value")
    if t is None:
        return jnp.zeros(shape, jnp.float32)
    fill = tensor_to_numpy(t)
    return jnp.full(shape, fill.reshape(()).item(),
                    dtype=fill.dtype)


@_register("Range")
def _range(a, i):
    start, limit, delta = (_static(v).item() for v in i[:3])
    return jnp.arange(start, limit, delta)


@_register("Cast")
def _cast(a, i):
    dt = onnx_pb._ONNX_TO_DTYPE.get(a["to"])
    if dt is None:
        if a["to"] == onnx_pb.TensorProto.BFLOAT16:
            return i[0].astype(jnp.bfloat16)
        raise TypeError(f"Cast to unsupported data_type {a['to']}")
    return i[0].astype(dt)


# reductions
def _reduce(jnp_fn):
    def fn(a, i):
        axes = a.get("axes")
        if (axes is None and len(i) > 1 and i[1] is not None):
            axes = [int(v) for v in _static(i[1])]
        kd = bool(a.get("keepdims", 1))
        if axes is None and a.get("noop_with_empty_axes", 0):
            return i[0]
        return jnp_fn(i[0], axis=tuple(axes) if axes is not None else None,
                      keepdims=kd)
    return fn


_register("ReduceMean")(_reduce(jnp.mean))
_register("ReduceSum")(_reduce(jnp.sum))
_register("ReduceMax")(_reduce(jnp.max))
_register("ReduceMin")(_reduce(jnp.min))
_register("ReduceProd")(_reduce(jnp.prod))
_register("ReduceL1")(_reduce(lambda x, axis, keepdims:
                              jnp.sum(jnp.abs(x), axis=axis,
                                      keepdims=keepdims)))
_register("ReduceSumSquare")(_reduce(lambda x, axis, keepdims:
                                     jnp.sum(x * x, axis=axis,
                                             keepdims=keepdims)))
_register("ReduceLogSum")(_reduce(lambda x, axis, keepdims:
                                  jnp.log(jnp.sum(x, axis=axis,
                                                  keepdims=keepdims))))


def _rnn_common(a, i, n_gates):
    """Shared ONNX LSTM/GRU plumbing: layouts, directions, defaults.
    X (T,B,I); W (D,G*H,I); R (D,G*H,H); B (D,2*G*H) optional;
    sequence_lens unsupported (guarded); initial states optional."""
    x, w, r = i[0], i[1], i[2]
    b = i[3] if len(i) > 3 and i[3] is not None else None
    if len(i) > 4 and i[4] is not None:
        raise NotImplementedError("RNN sequence_lens")
    if len(i) > 7 and i[7] is not None:
        raise NotImplementedError("LSTM peephole weights (P)")
    for attr in ("activations", "activation_alpha",
                 "activation_beta", "clip", "input_forget"):
        if a.get(attr):
            raise NotImplementedError(f"RNN attribute {attr!r} "
                                      "(defaults only)")
    direction = a.get("direction", "forward")
    direction = direction.decode() if isinstance(direction, bytes) \
        else direction
    hidden = int(a["hidden_size"])
    dirs = w.shape[0]
    t, bsz, _ = x.shape
    if b is None:
        b = jnp.zeros((dirs, 2 * n_gates * hidden), x.dtype)
    return x, w, r, b, direction, hidden, dirs, t, bsz


def _lstm_dir(x, w, r, b, h0, c0, hidden):
    """One direction. ONNX gate order i, o, f, c."""
    wb, rb = b[: 4 * hidden], b[4 * hidden:]

    def step(carry, xt):
        h, c = carry
        g = xt @ w.T + h @ r.T + wb + rb
        i_, o_, f_, c_ = jnp.split(g, 4, axis=-1)
        i_ = jax.nn.sigmoid(i_)
        o_ = jax.nn.sigmoid(o_)
        f_ = jax.nn.sigmoid(f_)
        c2 = f_ * c + i_ * jnp.tanh(c_)
        h2 = o_ * jnp.tanh(c2)
        return (h2, c2), h2

    (hT, cT), ys = jax.lax.scan(step, (h0, c0), x)
    return ys, hT, cT


@_register("LSTM")
def _lstm(a, i):
    x, w, r, b, direction, hidden, dirs, t, bsz = \
        _rnn_common(a, i, 4)
    h0 = (i[5] if len(i) > 5 and i[5] is not None
          else jnp.zeros((dirs, bsz, hidden), x.dtype))
    c0 = (i[6] if len(i) > 6 and i[6] is not None
          else jnp.zeros((dirs, bsz, hidden), x.dtype))
    outs = []
    for d in range(dirs):
        xd = x[::-1] if (direction == "reverse" or d == 1) else x
        ys, hT, cT = _lstm_dir(xd, w[d], r[d], b[d], h0[d], c0[d],
                               hidden)
        if direction == "reverse" or d == 1:
            ys = ys[::-1]
        outs.append((ys, hT, cT))
    y = jnp.stack([o[0] for o in outs], axis=1)   # (T, D, B, H)
    y_h = jnp.stack([o[1] for o in outs], axis=0)
    y_c = jnp.stack([o[2] for o in outs], axis=0)
    return y, y_h, y_c


@_register("GRU")
def _gru(a, i):
    x, w, r, b, direction, hidden, dirs, t, bsz = \
        _rnn_common(a, i, 3)
    lbr = int(a.get("linear_before_reset", 0))
    h0 = (i[5] if len(i) > 5 and i[5] is not None
          else jnp.zeros((dirs, bsz, hidden), x.dtype))

    def gru_dir(xd, wd, rd, bd, h_init):
        wb, rb = bd[: 3 * hidden], bd[3 * hidden:]
        wz, wr_, wh = jnp.split(wd, 3, axis=0)
        rz, rr, rh = jnp.split(rd, 3, axis=0)
        wbz, wbr, wbh = jnp.split(wb, 3)
        rbz, rbr, rbh = jnp.split(rb, 3)

        def step(h, xt):
            z = jax.nn.sigmoid(xt @ wz.T + h @ rz.T + wbz + rbz)
            rt = jax.nn.sigmoid(xt @ wr_.T + h @ rr.T + wbr + rbr)
            if lbr:
                hh = jnp.tanh(xt @ wh.T + wbh + rt * (h @ rh.T + rbh))
            else:
                hh = jnp.tanh(xt @ wh.T + wbh + (rt * h) @ rh.T + rbh)
            h2 = (1 - z) * hh + z * h
            return h2, h2

        hT, ys = jax.lax.scan(step, h_init, xd)
        return ys, hT

    outs = []
    for d in range(dirs):
        xd = x[::-1] if (direction == "reverse" or d == 1) else x
        ys, hT = gru_dir(xd, w[d], r[d], b[d], h0[d])
        if direction == "reverse" or d == 1:
            ys = ys[::-1]
        outs.append((ys, hT))
    y = jnp.stack([o[0] for o in outs], axis=1)
    y_h = jnp.stack([o[1] for o in outs], axis=0)
    return y, y_h


def _per_axis(vec, ndim, axis):
    """Broadcast a per-channel scale/zero-point vector to `ndim`
    dims along `axis`; scalars (incl. an omitted zero point) pass
    through untouched."""
    vec = jnp.asarray(vec)
    if vec.ndim == 1 and vec.shape[0] > 1:
        if not -ndim <= axis < ndim:
            raise ValueError(
                f"per-channel quantization axis {axis} out of range "
                f"for rank-{ndim} input")
        shape = [1] * ndim
        shape[axis % ndim] = vec.shape[0]
        return vec.reshape(shape)
    return vec


@_register("QuantizeLinear")
def _quantize_linear(a, i):
    x = jnp.asarray(i[0])
    axis = int(a.get("axis", 1))
    scale = _per_axis(i[1], x.ndim, axis)
    zp = (jnp.asarray(i[2]) if len(i) > 2 and i[2] is not None
          else jnp.zeros((), jnp.uint8))
    zp = _per_axis(zp, x.ndim, axis)
    return _requantize(x / scale, zp)


@_register("DequantizeLinear")
def _dequantize_linear(a, i):
    x = jnp.asarray(i[0])
    axis = int(a.get("axis", 1))
    scale = _per_axis(i[1], x.ndim, axis)
    zp = (jnp.asarray(i[2]) if len(i) > 2 and i[2] is not None
          else jnp.zeros((), x.dtype))
    zp = _per_axis(zp, x.ndim, axis)
    return (x.astype(jnp.float32) - zp.astype(jnp.float32)) * scale


@_register("DynamicQuantizeLinear")
def _dynamic_quantize_linear(a, i):
    x = i[0]
    rmin = jnp.minimum(jnp.min(x), 0.0)
    rmax = jnp.maximum(jnp.max(x), 0.0)
    scale = (rmax - rmin) / 255.0
    # all-zero input: 0/0 would NaN; ORT forces a safe nonzero scale
    scale = jnp.where(scale == 0, 1.0, scale)
    zp = jnp.clip(jnp.round(-rmin / scale), 0, 255).astype(jnp.uint8)
    q = jnp.clip(jnp.round(x / scale) + zp.astype(jnp.float32),
                 0, 255).astype(jnp.uint8)
    return q, scale.astype(jnp.float32), zp


@_register("QLinearMatMul")
def _qlinear_matmul(a, i):
    (xa, a_scale, a_zp, xb, b_scale, b_zp,
     y_scale, y_zp) = i[:8]
    xa, xb = jnp.asarray(xa), jnp.asarray(xb)
    # a-side 1-D scale/zp are per ROW (second-to-last axis): align
    # them there, not against K via trailing-axis broadcast
    def a_side(v):
        v = jnp.asarray(v)
        if v.ndim == 1 and v.shape[0] > 1:
            return v.reshape(v.shape + (1,))
        return v
    af = xa.astype(jnp.int32) - a_side(a_zp).astype(jnp.int32)
    bf = xb.astype(jnp.int32) - jnp.asarray(b_zp).astype(jnp.int32)
    # numpy.matmul batching semantics + int32 MXU accumulation
    acc = jnp.matmul(af, bf, preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (
        a_side(a_scale) * jnp.asarray(b_scale)
        / jnp.asarray(y_scale))
    return _requantize(y, y_zp)


@_register("ScatterElements", "Scatter")
def _scatter_elements(a, i):
    x, idx, upd = jnp.asarray(i[0]), jnp.asarray(i[1]), \
        jnp.asarray(i[2])
    axis = int(a.get("axis", 0)) % x.ndim
    red = a.get("reduction", "none")   # attribute_value decodes str
    idx = jnp.where(idx < 0, idx + x.shape[axis], idx)
    # build full coordinates: every dim indexes itself except `axis`,
    # which uses idx (jnp.put_along_axis has no reduction modes)
    coords = list(jnp.meshgrid(
        *[jnp.arange(n) for n in idx.shape], indexing="ij"))
    coords[axis] = idx
    at = x.at[tuple(coords)]
    ops = {"none": at.set, "add": at.add, "mul": at.multiply,
           "max": at.max, "min": at.min}
    if red not in ops:
        raise NotImplementedError(f"ScatterElements reduction {red!r}")
    return ops[red](upd)


_register("Celu")(lambda a, i: jax.nn.celu(i[0],
                                           a.get("alpha", 1.0)))


@_register("LpNormalization")
def _lp_normalization(a, i):
    x = i[0]
    axis = int(a.get("axis", -1))
    p = int(a.get("p", 2))
    if p == 1:
        denom = jnp.sum(jnp.abs(x), axis=axis, keepdims=True)
    elif p == 2:
        denom = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    else:
        raise NotImplementedError(f"LpNormalization p={p}")
    return x / denom


@_register("MeanVarianceNormalization")
def _mvn(a, i):
    x = i[0]
    axes = tuple(a.get("axes", [0, 2, 3]))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=axes, keepdims=True)
    return (x - mean) / jnp.sqrt(var + 1e-9)


_register("HardSwish")(lambda a, i: i[0] * jnp.clip(
    i[0] / 6.0 + 0.5, 0.0, 1.0))
_register("Mish")(lambda a, i: i[0] * jnp.tanh(jax.nn.softplus(i[0])))
_register("IsNaN")(lambda a, i: jnp.isnan(i[0]))


@_register("IsInf")
def _isinf(a, i):
    x = i[0]
    pos = jnp.isposinf(x) if a.get("detect_positive", 1) else \
        jnp.zeros_like(x, bool)
    neg = jnp.isneginf(x) if a.get("detect_negative", 1) else \
        jnp.zeros_like(x, bool)
    return jnp.logical_or(pos, neg)


@_register("Mod")
def _mod(a, i):
    if a.get("fmod", 0):
        return jnp.fmod(i[0], i[1])
    return jnp.mod(i[0], i[1])


@_register("Shrink")
def _shrink(a, i):
    x = i[0]
    lambd = a.get("lambd", 0.5)
    bias = a.get("bias", 0.0)
    return jnp.where(x < -lambd, x + bias,
                     jnp.where(x > lambd, x - bias,
                               jnp.zeros_like(x)))


@_register("GatherND")
def _gather_nd(a, i):
    x, idx = i[0], jnp.asarray(i[1])
    b = int(a.get("batch_dims", 0))

    def one(xb, ib):
        coords = tuple(jnp.moveaxis(ib, -1, 0))
        return xb[coords]

    fn = one
    for _ in range(b):
        fn = jax.vmap(fn)
    return fn(x, idx)


@_register("ScatterND")
def _scatter_nd(a, i):
    x, idx, upd = i[0], jnp.asarray(i[1]), i[2]
    red = a.get("reduction", "none")
    red = red.decode() if isinstance(red, bytes) else red
    coords = tuple(jnp.moveaxis(idx, -1, 0))
    at = jnp.asarray(x).at[coords]
    if red == "none":
        return at.set(upd)
    if red == "add":
        return at.add(upd)
    if red == "mul":
        return at.multiply(upd)
    if red == "max":
        return at.max(upd)
    if red == "min":
        return at.min(upd)
    raise NotImplementedError(f"ScatterND reduction {red!r}")


@_register("DepthToSpace")
def _depth_to_space(a, i):
    x = i[0]
    b, c, h, w = x.shape
    bs = int(a["blocksize"])
    mode = a.get("mode", "DCR")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    if mode == "DCR":
        y = x.reshape(b, bs, bs, c // (bs * bs), h, w)
        y = y.transpose(0, 3, 4, 1, 5, 2)
    else:  # CRD
        y = x.reshape(b, c // (bs * bs), bs, bs, h, w)
        y = y.transpose(0, 1, 4, 2, 5, 3)
    return y.reshape(b, c // (bs * bs), h * bs, w * bs)


@_register("SpaceToDepth")
def _space_to_depth(a, i):
    x = i[0]
    b, c, h, w = x.shape
    bs = int(a["blocksize"])
    y = x.reshape(b, c, h // bs, bs, w // bs, bs)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return y.reshape(b, c * bs * bs, h // bs, w // bs)


@_register("OneHot")
def _onehot(a, i):
    indices, depth, values = i
    d = int(_static(depth).reshape(()))
    axis = int(a.get("axis", -1))
    off_v, on_v = _static(values)
    idx = jnp.asarray(indices)
    idx = jnp.where(idx < 0, idx + d, idx)   # ONNX negative wrap
    vdt = jnp.asarray(i[2]).dtype   # spec: output type = values type
    oh = jax.nn.one_hot(idx, d, axis=axis, dtype=vdt)
    return (oh * (on_v - off_v) + off_v).astype(vdt)


@_register("Trilu")
def _trilu(a, i):
    x = i[0]
    k = int(_static(i[1]).reshape(())) if len(i) > 1 and \
        i[1] is not None else 0
    if a.get("upper", 1):
        return jnp.triu(x, k)
    return jnp.tril(x, k)


@_register("Einsum")
def _einsum(a, i):
    eq = a["equation"]
    eq = eq.decode() if isinstance(eq, bytes) else eq
    return jnp.einsum(eq, *i)


@_register("TopK")
def _topk(a, i):
    x = i[0]
    k = int(_static(i[1]).reshape(())) if len(i) > 1 else int(a["k"])
    axis = int(a.get("axis", -1))
    largest = bool(a.get("largest", 1))
    xm = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(xm, k)
    else:
        # smallest-k via order inversion that is safe for EVERY dtype
        # (arithmetic negation wraps for unsigned ints and INT_MIN):
        # take top-k of the descending sort-rank instead
        order = jnp.argsort(xm, axis=-1)           # ascending
        idx = order[..., :k]
        vals = jnp.take_along_axis(xm, idx, axis=-1)
    # indices stay the x64-mode default int (int64 would silently
    # truncate to int32 with a warning when x64 is off — run_node's
    # documented caveat)
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx, -1, axis))


@_register("CumSum")
def _cumsum(a, i):
    axis = int(_static(i[1]).reshape(()))
    y = i[0]
    if a.get("reverse", 0):
        y = jnp.flip(y, axis)
    out = jnp.cumsum(y, axis=axis)
    if a.get("exclusive", 0):
        out = jnp.concatenate(
            [jnp.zeros_like(jnp.take(out, jnp.array([0]), axis=axis)),
             jnp.take(out, jnp.arange(out.shape[axis] - 1),
                      axis=axis)], axis=axis)
    if a.get("reverse", 0):
        out = jnp.flip(out, axis)
    return out
_register("ReduceL2")(_reduce(
    lambda x, axis, keepdims: jnp.sqrt(
        jnp.sum(x * x, axis=axis, keepdims=keepdims))))
_register("ReduceLogSumExp")(_reduce(
    lambda x, axis, keepdims: jax.scipy.special.logsumexp(
        x, axis=axis, keepdims=keepdims)))

_register("ArgMax")(lambda a, i: jnp.argmax(
    i[0], axis=a.get("axis", 0), keepdims=bool(a.get("keepdims", 1))))
_register("ArgMin")(lambda a, i: jnp.argmin(
    i[0], axis=a.get("axis", 0), keepdims=bool(a.get("keepdims", 1))))


def _resize_impl(a, i, ct, default_nearest="round_prefer_floor"):
    x = i[0]
    mode = a.get("mode", "nearest")
    sizes = None
    if len(i) >= 4 and i[3] is not None:  # Resize sizes input
        sizes = [int(v) for v in _static(i[3])]
    else:
        scales_in = None
        for cand in (i[2] if len(i) > 2 else None,
                     i[1] if len(i) > 1 else None):
            if cand is not None and np.size(_static(cand)):
                scales_in = _static(cand)
                break
        if scales_in is None:
            scales_in = np.asarray(a.get("scales"))
        # ONNX: output_dim = floor(input_dim * scale)
        sizes = [int(np.floor(s * f)) for s, f in zip(x.shape, scales_in)]
    if mode == "nearest" and ct == "asymmetric":
        # exact opset-10 Upsample / torch Upsample semantics:
        # src = f(dst / scale) per axis via integer gathers
        from analytics_zoo_tpu.pipeline.api.keras.layers. \
            elementwise import nearest_round
        nearest = a.get("nearest_mode", default_nearest)
        out = x
        for axis, (insz, outsz) in enumerate(zip(x.shape, sizes)):
            if insz == outsz:
                continue
            pos = np.arange(outsz) * (insz / outsz)
            src = nearest_round(pos, nearest)
            src = np.clip(src.astype(np.int64), 0, insz - 1)
            out = jnp.take(out, jnp.asarray(src), axis=axis)
        return out
    method = {"nearest": "nearest", "linear": "linear",
              "cubic": "cubic"}[mode]
    if ct == "align_corners":
        from analytics_zoo_tpu.pipeline.api.keras.layers.elementwise \
            import align_corners_resize
        return align_corners_resize(
            x, sizes, method=method,
            nearest_mode=a.get("nearest_mode", default_nearest))
    if ct not in ("half_pixel", "pytorch_half_pixel"):
        # silently falling back to half-pixel shifts pixels for
        # asymmetric/align_corners exports (ADVICE r1)
        raise NotImplementedError(
            f"Resize coordinate_transformation_mode={ct!r} with "
            f"mode={mode!r}: only half_pixel(/pytorch_half_pixel), "
            "align_corners, or nearest+asymmetric, are supported")
    return jax.image.resize(x, sizes, method=method)


@_register("Resize")
def _resize(a, i):
    return _resize_impl(
        a, i, a.get("coordinate_transformation_mode", "half_pixel"))


@_register("Upsample")
def _upsample(a, i):
    # opset<=10 Upsample is defined as asymmetric coordinates + floor
    return _resize_impl(a, i, "asymmetric", default_nearest="floor")


@_register("Dropout")
def _dropout(a, i, *, training=False, rng=None):
    x = i[0]
    ratio = a.get("ratio", 0.5)
    if len(i) > 1 and i[1] is not None:
        ratio = float(_static(i[1]))
    if not training or ratio <= 0.0 or rng is None:
        return x
    keep = 1.0 - ratio
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@_register("Constant")
def _constant(a, i):
    if "value" in a and a["value"] is not None:
        return tensor_to_numpy(a["value"])
    for k in ("value_float", "value_int"):
        if k in a:
            return np.asarray(a[k])
    if "value_floats" in a:
        return np.asarray(a["value_floats"], np.float32)
    if "value_ints" in a:
        return np.asarray(a["value_ints"], np.int64)
    raise ValueError("Constant node without value")


# -- graph interpreter layer --------------------------------------------------

class OnnxGraphLayer(KerasLayer):
    """A KerasLayer interpreting an ONNX GraphProto node-by-node.

    Float initializers become trainable params under ``"w"``; integer
    initializers stay as host constants (shape operands must be static
    for XLA). The interpretation happens at trace time, so under
    ``jax.jit`` the graph compiles to a single fused XLA program.
    """

    def __init__(self, graph: onnx_pb.GraphProto,
                 name: Optional[str] = None, opset: int = 13,
                 input_shape=None):
        self.graph = graph
        self.opset = int(opset)
        self._constants: Dict[str, np.ndarray] = {}
        self._param_names: List[str] = []
        for t in graph.initializer:
            arr = tensor_to_numpy(t)
            self._constants[t.name] = arr
            if np.issubdtype(arr.dtype, np.floating):
                self._param_names.append(t.name)
        init_names = set(self._constants)
        self.input_names = [vi.name for vi in graph.input
                            if vi.name not in init_names]
        self.output_names = [vi.name for vi in graph.output]
        if input_shape is not None:
            shapes: Any = input_shape
        else:
            in_shapes = [_vi_shape(vi) for vi in graph.input
                         if vi.name not in init_names]
            for vi, s in zip(self.input_names, in_shapes):
                if any(d is None for d in s[1:]):
                    raise ValueError(
                        f"ONNX input {vi!r} has symbolic non-batch "
                        f"dims {s[1:]}; pass input_shape= to "
                        "OnnxLoader.load_model with concrete shapes "
                        "(batch dim excluded)")
            multi = len(in_shapes) > 1
            shapes = [s[1:] for s in in_shapes] if multi else \
                in_shapes[0][1:]
        super().__init__(input_shape=shapes,
                         name=name or unique_name("onnxgraph"))

    def build(self, rng, input_shape):
        del rng, input_shape
        return {"w": {n: jnp.asarray(self._constants[n])
                      for n in self._param_names}}

    def compute_output_shape(self, input_shape):
        multi = len(self.input_names) > 1
        shapes = input_shape if multi else [input_shape]
        dummies = [jax.ShapeDtypeStruct((1,) + tuple(as_shape(s)),
                                        jnp.float32) for s in shapes]
        params = {"w": {n: jax.ShapeDtypeStruct(
            self._constants[n].shape, self._constants[n].dtype)
            for n in self._param_names}}
        out = jax.eval_shape(
            lambda p, xs: self._interpret(p, xs, training=False, rng=None),
            params, tuple(dummies))
        if len(self.output_names) > 1:
            return [tuple(o.shape[1:]) for o in out]
        return tuple(out[0].shape[1:])

    def call(self, params, inputs, *, training=False, rng=None):
        xs = (tuple(inputs) if isinstance(inputs, (list, tuple))
              else (inputs,))
        outs = self._interpret(params, xs, training=training, rng=rng)
        return list(outs) if len(outs) > 1 else outs[0]

    def _interpret(self, params, xs, *, training, rng):
        if len(xs) != len(self.input_names):
            raise ValueError(
                f"ONNX graph expects {len(self.input_names)} inputs "
                f"({self.input_names}), got {len(xs)}")
        env: Dict[str, Any] = dict(self._constants)
        env.update(params.get("w", {}))
        env.update(zip(self.input_names, xs))
        self._run_nodes(self.graph.node, env, training=training,
                        rng=rng)
        missing = [n for n in self.output_names if n not in env]
        if missing:
            raise ValueError(f"graph outputs never produced: {missing}")
        return tuple(env[n] for n in self.output_names)

    def _run_nodes(self, nodes, env, *, training, rng):
        """Interpret a node list into ``env`` (shared by the top graph
        and If-branch subgraphs, which see the outer scope by name —
        the ONNX subgraph capture rule)."""
        for k, node in enumerate(nodes):
            # fold the rng only for nodes that consume one (a per-node
            # threefry dispatch would be wasted work eagerly)
            sub_rng = (jax.random.fold_in(rng, k)
                       if rng is not None
                       and node.op_type in ("Dropout", "If")
                       else None)
            if node.op_type == "If":
                self._run_if(node, env, training=training,
                             rng=sub_rng)
                continue
            op = _OPS.get(node.op_type)
            if op is None:
                raise NotImplementedError(
                    f"ONNX op {node.op_type} (node {node.name or k})")
            args = [env[n] if n else None for n in node.input]
            attrs = _attrs(node)
            attrs["__opset__"] = self.opset
            if node.op_type == "Split":
                attrs.setdefault("num_outputs", len(node.output))
            if node.op_type == "Dropout":
                out = op(attrs, args, training=training, rng=sub_rng)
            else:
                out = op(attrs, args)
            if isinstance(out, tuple):
                for name, val in zip(node.output, out):
                    if name:
                        env[name] = val
            else:
                env[node.output[0]] = out

    def _run_if(self, node, env, *, training, rng):
        """ONNX If: static conditions pick a branch at trace time
        (dead branch never interpreted — free of its op requirements);
        traced conditions lower to ``lax.cond`` with both branches
        traced (the spec requires matching output shapes)."""
        attrs = {a.name: a for a in node.attribute}
        then_g = attribute_value(attrs["then_branch"])
        else_g = attribute_value(attrs["else_branch"])
        cond = env[node.input[0]]

        def run_branch(g):
            benv = dict(env)     # outer scope visible by name
            for t in g.initializer:
                benv[t.name] = tensor_to_numpy(t)
            self._run_nodes(g.node, benv, training=training, rng=rng)
            return tuple(benv[o.name] for o in g.output)

        if isinstance(cond, (bool, np.bool_, np.ndarray)) or (
                isinstance(cond, jax.Array)
                and not isinstance(cond, jax.core.Tracer)):
            outs = run_branch(
                then_g if bool(np.asarray(cond).reshape(()))
                else else_g)
        else:
            outs = jax.lax.cond(
                jnp.asarray(cond).reshape(()),
                lambda _: run_branch(then_g),
                lambda _: run_branch(else_g), None)
        for name, val in zip(node.output, outs):
            if name:
                env[name] = val


def _vi_shape(vi: onnx_pb.ValueInfoProto) -> tuple:
    """Shape from ValueInfo; symbolic (dim_param) / absent dims map to
    None (the batch slot is ignored by the caller; non-batch Nones
    require an explicit input_shape)."""
    tt = vi.type.tensor_type if vi.type else None
    if tt is None or tt.shape is None:
        raise ValueError(f"graph input {vi.name} has no shape info")
    dims = []
    for d in tt.shape.dim:
        dims.append(int(d.dim_value) if d.dim_value else None)
    return tuple(dims)


# -- public API ---------------------------------------------------------------

class OnnxLoader:
    """Reference analog of `P/pipeline/api/onnx/onnx_loader.py:32`."""

    @staticmethod
    def load_model(path_or_bytes, input_shape=None) -> "Any":
        """Load an ONNX model into a trainable zoo `Sequential`.

        ``input_shape`` (batch dim excluded; list of shapes for
        multi-input graphs) overrides the graph's declared input
        shapes — required when they contain symbolic dims."""
        model_proto = (path_or_bytes
                       if isinstance(path_or_bytes, ModelProto)
                       else onnx_pb.load_model(path_or_bytes))
        opset = 13
        for op in model_proto.opset_import:
            if not op.domain:  # default ONNX domain
                opset = int(op.version or 13)
        from analytics_zoo_tpu.pipeline.api.keras.models import Sequential
        layer = OnnxGraphLayer(model_proto.graph, opset=opset,
                               input_shape=input_shape)
        net = Sequential([layer],
                         name=model_proto.graph.name or None)
        return net

    @staticmethod
    def run_node(node: NodeProto, inputs: Sequence[np.ndarray],
                 **kwargs) -> List[np.ndarray]:
        """Execute one NodeProto on concrete inputs (backend-test hook,
        reference `onnx_loader.py:51`)."""
        op = _OPS.get(node.op_type)
        if op is None:
            raise NotImplementedError(f"ONNX op {node.op_type}")
        # keep numpy inputs as numpy: static shape/index operands must not
        # round-trip through jnp (x64 is disabled — int64 would truncate)
        args = [np.asarray(x) if isinstance(x, (list, tuple, int, float))
                else x for x in inputs]
        attrs = _attrs(node)
        attrs["__opset__"] = int(kwargs.get("opset", 13))
        if node.op_type == "Split":
            attrs.setdefault("num_outputs", len(node.output))
        if node.op_type == "Dropout":
            out = op(attrs, args, training=kwargs.get("training", False),
                     rng=kwargs.get("rng"))
        else:
            out = op(attrs, args)
        outs = out if isinstance(out, tuple) else (out,)
        return [np.asarray(o) for o in outs]

    @staticmethod
    def supported_ops() -> List[str]:
        return sorted(_OPS)


load = OnnxLoader.load_model
run_node = OnnxLoader.run_node
