"""Minimal pure-Python ONNX protobuf codec (reader + writer).

The runtime image has no ``onnx`` package, so the framework carries its
own wire-format codec for the subset of the ONNX schema the importer
needs (ModelProto / GraphProto / NodeProto / AttributeProto /
TensorProto / ValueInfoProto / TypeProto / OperatorSetIdProto). Field
numbers match the official ``onnx.proto`` so real ``.onnx`` files parse.

Reference analog: the zoo's ONNX support sits on the ``onnx`` pip
package (`P/pipeline/api/onnx/onnx_loader.py:32`); here the codec is
part of the framework itself — no external dependency, and it can both
read and write, which the test-suite uses to fabricate golden models.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Tuple

import numpy as np

# -- wire-format primitives ---------------------------------------------------

_WIRE_VARINT = 0
_WIRE_I64 = 1
_WIRE_LEN = 2
_WIRE_I32 = 5


def _write_varint(buf: bytearray, value: int) -> None:
    if value < 0:  # two's-complement 64-bit, 10 bytes
        value += 1 << 64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")
    return result, pos


def _to_signed64(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


def _skip(data: bytes, pos: int, wire: int) -> int:
    if wire == _WIRE_VARINT:
        _, pos = _read_varint(data, pos)
    elif wire == _WIRE_I64:
        pos += 8
    elif wire == _WIRE_LEN:
        n, pos = _read_varint(data, pos)
        pos += n
    elif wire == _WIRE_I32:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire}")
    return pos


# -- declarative message base -------------------------------------------------

class Message:
    """Base for schema-described messages.

    Subclasses define ``FIELDS``: {field_number: (name, kind, repeated)}
    where kind is one of ``int64``, ``float``, ``double``, ``string``,
    ``bytes``, or a Message subclass name (sub-message).
    """

    FIELDS: Dict[int, Tuple[str, str, bool]] = {}

    def __init__(self, **kwargs: Any):
        for _, (name, _, repeated) in self.FIELDS.items():
            setattr(self, name, [] if repeated else None)
        for k, v in kwargs.items():
            if not any(name == k for name, _, _ in self.FIELDS.values()):
                raise AttributeError(f"{type(self).__name__}.{k}")
            setattr(self, k, v)

    # -- encode ---------------------------------------------------------------
    def SerializeToString(self) -> bytes:
        buf = bytearray()
        for num, (name, kind, repeated) in sorted(self.FIELDS.items()):
            value = getattr(self, name)
            if value is None or (repeated and not len(value)):
                continue
            values = value if repeated else [value]
            if kind == "int64":
                if repeated:
                    # packed encoding for repeated scalars
                    packed = bytearray()
                    for v in values:
                        _write_varint(packed, int(v))
                    _write_varint(buf, _tag(num, _WIRE_LEN))
                    _write_varint(buf, len(packed))
                    buf += packed
                else:
                    _write_varint(buf, _tag(num, _WIRE_VARINT))
                    _write_varint(buf, int(values[0]))
            elif kind == "float":
                if repeated:
                    packed = b"".join(struct.pack("<f", float(v))
                                      for v in values)
                    _write_varint(buf, _tag(num, _WIRE_LEN))
                    _write_varint(buf, len(packed))
                    buf += packed
                else:
                    _write_varint(buf, _tag(num, _WIRE_I32))
                    buf += struct.pack("<f", float(values[0]))
            elif kind == "double":
                if repeated:
                    packed = b"".join(struct.pack("<d", float(v))
                                      for v in values)
                    _write_varint(buf, _tag(num, _WIRE_LEN))
                    _write_varint(buf, len(packed))
                    buf += packed
                else:
                    _write_varint(buf, _tag(num, _WIRE_I64))
                    buf += struct.pack("<d", float(values[0]))
            elif kind in ("string", "bytes"):
                for v in values:
                    raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                    _write_varint(buf, _tag(num, _WIRE_LEN))
                    _write_varint(buf, len(raw))
                    buf += raw
            else:  # sub-message
                for v in values:
                    raw = v.SerializeToString()
                    _write_varint(buf, _tag(num, _WIRE_LEN))
                    _write_varint(buf, len(raw))
                    buf += raw
        return bytes(buf)

    # -- decode ---------------------------------------------------------------
    @classmethod
    def FromString(cls, data: bytes) -> "Message":
        msg = cls()
        msg.ParseFromString(data)
        return msg

    def ParseFromString(self, data: bytes) -> None:
        pos = 0
        end = len(data)
        registry = _MESSAGE_TYPES
        while pos < end:
            key, pos = _read_varint(data, pos)
            num, wire = key >> 3, key & 7
            spec = self.FIELDS.get(num)
            if spec is None:
                pos = _skip(data, pos, wire)
                continue
            name, kind, repeated = spec
            if kind == "int64":
                if wire == _WIRE_LEN:  # packed
                    n, pos = _read_varint(data, pos)
                    stop = pos + n
                    vals = []
                    while pos < stop:
                        v, pos = _read_varint(data, pos)
                        vals.append(_to_signed64(v))
                    getattr(self, name).extend(vals) if repeated else \
                        setattr(self, name, vals[-1] if vals else None)
                else:
                    v, pos = _read_varint(data, pos)
                    v = _to_signed64(v)
                    if repeated:
                        getattr(self, name).append(v)
                    else:
                        setattr(self, name, v)
            elif kind == "float":
                if wire == _WIRE_LEN:
                    n, pos = _read_varint(data, pos)
                    vals = [struct.unpack_from("<f", data, pos + i)[0]
                            for i in range(0, n, 4)]
                    pos += n
                    if repeated:
                        getattr(self, name).extend(vals)
                    elif vals:
                        setattr(self, name, vals[-1])
                else:
                    v = struct.unpack_from("<f", data, pos)[0]
                    pos += 4
                    if repeated:
                        getattr(self, name).append(v)
                    else:
                        setattr(self, name, v)
            elif kind == "double":
                if wire == _WIRE_LEN:
                    n, pos = _read_varint(data, pos)
                    vals = [struct.unpack_from("<d", data, pos + i)[0]
                            for i in range(0, n, 8)]
                    pos += n
                    if repeated:
                        getattr(self, name).extend(vals)
                    elif vals:
                        setattr(self, name, vals[-1])
                else:
                    v = struct.unpack_from("<d", data, pos)[0]
                    pos += 8
                    if repeated:
                        getattr(self, name).append(v)
                    else:
                        setattr(self, name, v)
            elif kind in ("string", "bytes"):
                n, pos = _read_varint(data, pos)
                raw = data[pos:pos + n]
                pos += n
                v: Any = raw.decode("utf-8") if kind == "string" else raw
                if repeated:
                    getattr(self, name).append(v)
                else:
                    setattr(self, name, v)
            else:  # sub-message
                n, pos = _read_varint(data, pos)
                sub = registry[kind]()
                sub.ParseFromString(data[pos:pos + n])
                pos += n
                if repeated:
                    getattr(self, name).append(sub)
                else:
                    setattr(self, name, sub)

    def __repr__(self) -> str:
        parts = []
        for _, (name, _, repeated) in sorted(self.FIELDS.items()):
            v = getattr(self, name)
            if v is None or (repeated and not v):
                continue
            parts.append(f"{name}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"


# -- ONNX message schemas (field numbers match official onnx.proto) -----------

class OperatorSetIdProto(Message):
    FIELDS = {
        1: ("domain", "string", False),
        2: ("version", "int64", False),
    }


class TensorProto(Message):
    FIELDS = {
        1: ("dims", "int64", True),
        2: ("data_type", "int64", False),
        4: ("float_data", "float", True),
        5: ("int32_data", "int64", True),
        6: ("string_data", "bytes", True),
        7: ("int64_data", "int64", True),
        8: ("name", "string", False),
        9: ("raw_data", "bytes", False),
        10: ("double_data", "double", True),
        11: ("uint64_data", "int64", True),
        12: ("doc_string", "string", False),
    }

    # onnx.TensorProto.DataType values
    FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL = \
        1, 2, 3, 4, 5, 6, 7, 8, 9
    FLOAT16, DOUBLE, UINT32, UINT64 = 10, 11, 12, 13
    BFLOAT16 = 16


class TensorShapeDim(Message):
    FIELDS = {
        1: ("dim_value", "int64", False),
        2: ("dim_param", "string", False),
    }


class TensorShapeProto(Message):
    FIELDS = {1: ("dim", "TensorShapeDim", True)}


class TensorTypeProto(Message):
    FIELDS = {
        1: ("elem_type", "int64", False),
        2: ("shape", "TensorShapeProto", False),
    }


class TypeProto(Message):
    FIELDS = {1: ("tensor_type", "TensorTypeProto", False)}


class ValueInfoProto(Message):
    FIELDS = {
        1: ("name", "string", False),
        2: ("type", "TypeProto", False),
        3: ("doc_string", "string", False),
    }


class AttributeProto(Message):
    FIELDS = {
        1: ("name", "string", False),
        2: ("f", "float", False),
        3: ("i", "int64", False),
        4: ("s", "bytes", False),
        5: ("t", "TensorProto", False),
        6: ("g", "GraphProto", False),
        7: ("floats", "float", True),
        8: ("ints", "int64", True),
        9: ("strings", "bytes", True),
        10: ("tensors", "TensorProto", True),
        11: ("graphs", "GraphProto", True),
        13: ("doc_string", "string", False),
        20: ("type", "int64", False),
    }

    # AttributeProto.AttributeType values
    FLOAT, INT, STRING, TENSOR, GRAPH = 1, 2, 3, 4, 5
    FLOATS, INTS, STRINGS, TENSORS, GRAPHS = 6, 7, 8, 9, 10


class NodeProto(Message):
    FIELDS = {
        1: ("input", "string", True),
        2: ("output", "string", True),
        3: ("name", "string", False),
        4: ("op_type", "string", False),
        5: ("attribute", "AttributeProto", True),
        6: ("doc_string", "string", False),
        7: ("domain", "string", False),
    }


class GraphProto(Message):
    FIELDS = {
        1: ("node", "NodeProto", True),
        2: ("name", "string", False),
        5: ("initializer", "TensorProto", True),
        10: ("doc_string", "string", False),
        11: ("input", "ValueInfoProto", True),
        12: ("output", "ValueInfoProto", True),
        13: ("value_info", "ValueInfoProto", True),
    }


class StringStringEntryProto(Message):
    FIELDS = {
        1: ("key", "string", False),
        2: ("value", "string", False),
    }


class ModelProto(Message):
    FIELDS = {
        1: ("ir_version", "int64", False),
        2: ("producer_name", "string", False),
        3: ("producer_version", "string", False),
        4: ("domain", "string", False),
        5: ("model_version", "int64", False),
        6: ("doc_string", "string", False),
        7: ("graph", "GraphProto", False),
        8: ("opset_import", "OperatorSetIdProto", True),
        14: ("metadata_props", "StringStringEntryProto", True),
    }


_MESSAGE_TYPES: Dict[str, type] = {
    cls.__name__: cls for cls in (
        OperatorSetIdProto, TensorProto, TensorShapeDim, TensorShapeProto,
        TensorTypeProto, TypeProto, ValueInfoProto, AttributeProto,
        NodeProto, GraphProto, StringStringEntryProto, ModelProto)
}


# -- numpy <-> TensorProto ----------------------------------------------------

_DTYPE_TO_ONNX = {
    np.dtype(np.float32): TensorProto.FLOAT,
    np.dtype(np.float64): TensorProto.DOUBLE,
    np.dtype(np.float16): TensorProto.FLOAT16,
    np.dtype(np.int32): TensorProto.INT32,
    np.dtype(np.int64): TensorProto.INT64,
    np.dtype(np.int16): TensorProto.INT16,
    np.dtype(np.int8): TensorProto.INT8,
    np.dtype(np.uint8): TensorProto.UINT8,
    np.dtype(np.uint16): TensorProto.UINT16,
    np.dtype(np.uint32): TensorProto.UINT32,
    np.dtype(np.uint64): TensorProto.UINT64,
    np.dtype(np.bool_): TensorProto.BOOL,
}

_ONNX_TO_DTYPE = {v: k for k, v in _DTYPE_TO_ONNX.items()}


def numpy_to_tensor(arr: np.ndarray, name: str = "") -> TensorProto:
    arr = np.asarray(arr)
    if arr.dtype not in _DTYPE_TO_ONNX:
        raise TypeError(f"unsupported dtype {arr.dtype}")
    t = TensorProto()
    t.name = name or None
    t.dims = list(arr.shape)
    t.data_type = _DTYPE_TO_ONNX[arr.dtype]
    t.raw_data = np.ascontiguousarray(arr).tobytes()
    return t


def tensor_to_numpy(t: TensorProto) -> np.ndarray:
    dt = t.data_type
    shape = tuple(t.dims)
    if dt == 16:  # BFLOAT16 — stored as uint16 raw; upcast via ml_dtypes
        import ml_dtypes
        if t.raw_data:
            arr = np.frombuffer(bytes(t.raw_data), dtype=ml_dtypes.bfloat16)
        else:
            arr = np.array(
                [v for v in t.int32_data], dtype=np.uint16
            ).view(ml_dtypes.bfloat16)
        return arr.reshape(shape).astype(np.float32)
    if dt not in _ONNX_TO_DTYPE:
        raise TypeError(f"unsupported ONNX data_type {dt}")
    np_dtype = _ONNX_TO_DTYPE[dt]
    if t.raw_data:
        return np.frombuffer(bytes(t.raw_data),
                             dtype=np_dtype).reshape(shape).copy()
    if dt == TensorProto.FLOAT16:
        # non-raw fp16: int32_data holds the uint16 bit patterns
        return np.array(list(t.int32_data),
                        np.uint16).view(np.float16).reshape(shape)
    if dt == TensorProto.FLOAT:
        return np.array(list(t.float_data), np.float32).reshape(shape)
    if dt == TensorProto.DOUBLE:
        return np.array(list(t.double_data), np.float64).reshape(shape)
    if dt == TensorProto.INT64:
        return np.array(list(t.int64_data), np.int64).reshape(shape)
    if dt in (TensorProto.INT32, TensorProto.INT16, TensorProto.INT8,
              TensorProto.UINT8, TensorProto.UINT16, TensorProto.BOOL):
        return np.array(list(t.int32_data)).astype(np_dtype).reshape(shape)
    if dt in (TensorProto.UINT32, TensorProto.UINT64):
        return np.array(list(t.uint64_data)).astype(np_dtype).reshape(shape)
    raise TypeError(f"no data found in TensorProto {t.name!r}")


def load_model(path_or_bytes) -> ModelProto:
    """Parse a serialized ONNX ModelProto from path / bytes."""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    model = ModelProto()
    model.ParseFromString(data)
    if model.graph is None:
        raise ValueError("not an ONNX ModelProto (no graph)")
    return model


def save_model(model: ModelProto, path: str) -> None:
    with open(path, "wb") as f:
        f.write(model.SerializeToString())
