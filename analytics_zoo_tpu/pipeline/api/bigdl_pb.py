"""Minimal pure-Python BigDL model protobuf codec (reader + writer).

BigDL 0.x serializes modules as a ``BigDLModule`` proto tree
(`bigdl.proto` in the BigDL distribution — an external maven dep of the
reference, not vendored there). The reference loads these via
`Net.loadBigDL` / `Net.load` (`Z/pipeline/api/Net.scala:91-118`); this
codec lets the TPU framework read the same files — including the
reference's own test fixtures
(`zoo/src/test/resources/models/{bigdl,zoo_keras}`) — without Spark,
BigDL, or protobuf installed.

Field numbers match bigdl.proto, so real ``.model`` files parse. Only
the subset the importer needs is described; unknown fields are skipped
by the base codec.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from analytics_zoo_tpu.pipeline.api.onnx.onnx_pb import (
    Message, _MESSAGE_TYPES)


class BShape(Message):
    # message Shape {ShapeType shapeType=1; int32 ssize=2;
    #                repeated int32 shapeValue=3; repeated Shape shape=4}
    FIELDS = {
        1: ("shapeType", "int64", False),
        2: ("ssize", "int64", False),
        3: ("shapeValue", "int64", True),
        4: ("shape", "BShape", True),
    }


class TensorStorage(Message):
    FIELDS = {
        1: ("datatype", "int64", False),
        2: ("float_data", "float", True),
        3: ("double_data", "double", True),
        4: ("int32_data", "int64", True),
        5: ("int64_data", "int64", True),
        6: ("bool_data", "int64", True),
        7: ("string_data", "string", True),
        8: ("bytes_data", "bytes", True),
        9: ("id", "int64", False),
    }


class BigDLTensor(Message):
    FIELDS = {
        1: ("datatype", "int64", False),
        2: ("size", "int64", True),
        3: ("stride", "int64", True),
        4: ("offset", "int64", False),
        5: ("dimension", "int64", False),
        6: ("nElements", "int64", False),
        7: ("isScalar", "int64", False),
        8: ("storage", "TensorStorage", False),
        9: ("id", "int64", False),
        10: ("tensorType", "int64", False),
    }


class ArrayValue(Message):
    FIELDS = {
        1: ("size", "int64", False),
        2: ("datatype", "int64", False),
        3: ("i32", "int64", True),
        4: ("i64", "int64", True),
        5: ("flt", "float", True),
        6: ("dbl", "double", True),
        7: ("str", "string", True),
        8: ("boolean", "int64", True),
        10: ("tensor", "BigDLTensor", True),
        13: ("bigDLModule", "BigDLModule", True),
        17: ("shape", "BShape", True),
    }


class AttrValue(Message):
    FIELDS = {
        1: ("dataType", "int64", False),
        2: ("subType", "string", False),
        3: ("int32Value", "int64", False),
        4: ("int64Value", "int64", False),
        5: ("floatValue", "float", False),
        6: ("doubleValue", "double", False),
        7: ("stringValue", "string", False),
        8: ("boolValue", "int64", False),
        10: ("tensorValue", "BigDLTensor", False),
        13: ("bigDLModuleValue", "BigDLModule", False),
        14: ("nameAttrListValue", "NameAttrList", False),
        15: ("arrayValue", "ArrayValue", False),
        16: ("dataFormatValue", "int64", False),
        18: ("shape", "BShape", False),
    }


class NameAttrList(Message):
    FIELDS = {
        1: ("name", "string", False),
        2: ("attr", "AttrEntry", True),
    }

    def attr_map(self) -> "Dict[str, AttrValue]":
        return {e.key: e.value for e in self.attr}


class AttrEntry(Message):
    # map<string, AttrValue> entry
    FIELDS = {
        1: ("key", "string", False),
        2: ("value", "AttrValue", False),
    }


class BigDLModule(Message):
    FIELDS = {
        1: ("name", "string", False),
        2: ("subModules", "BigDLModule", True),
        3: ("weight", "BigDLTensor", False),
        4: ("bias", "BigDLTensor", False),
        5: ("preModules", "string", True),
        6: ("nextModules", "string", True),
        7: ("moduleType", "string", False),
        8: ("attr", "AttrEntry", True),
        9: ("version", "string", False),
        10: ("train", "int64", False),
        11: ("namePostfix", "string", False),
        12: ("id", "int64", False),
        13: ("inputShape", "BShape", True),
        14: ("outputShape", "BShape", True),
        15: ("hasParameters", "int64", False),
        16: ("parameters", "BigDLTensor", True),
    }

    def attr_map(self) -> "Dict[str, AttrValue]":
        return {e.key: e.value for e in self.attr}


_MESSAGE_TYPES.update({
    "BShape": BShape,
    "TensorStorage": TensorStorage,
    "BigDLTensor": BigDLTensor,
    "ArrayValue": ArrayValue,
    "AttrValue": AttrValue,
    "AttrEntry": AttrEntry,
    "NameAttrList": NameAttrList,
    "BigDLModule": BigDLModule,
})

# DataType enum values (bigdl.proto)
DT_INT32, DT_INT64, DT_FLOAT, DT_DOUBLE = 0, 1, 2, 3


def _storage_data(storage: Optional[TensorStorage]) -> \
        Optional[np.ndarray]:
    if storage is None:
        return None
    if storage.float_data:
        return np.asarray(storage.float_data, np.float32)
    if storage.double_data:
        return np.asarray(storage.double_data, np.float64)
    if storage.int32_data:
        return np.asarray(storage.int32_data, np.int32)
    if storage.int64_data:
        return np.asarray(storage.int64_data, np.int64)
    if storage.bytes_data:
        return np.frombuffer(b"".join(storage.bytes_data), np.uint8)
    return None


class StorageTable:
    """Tensor DATA is deduplicated per saved file: the top module's
    ``global_storage`` attr is a NameAttrList mapping str(tensorId) →
    BigDLTensor carrying the actual storage; per-layer weight/bias
    tensors reference it by their ``id`` (and carry size/stride/offset
    locally)."""

    def __init__(self, root: Optional[BigDLModule] = None):
        self._by_tid: Dict[int, np.ndarray] = {}
        self._by_sid: Dict[int, np.ndarray] = {}
        if root is not None:
            gs = root.attr_map().get("global_storage")
            nal = gs.nameAttrListValue if gs is not None else None
            if nal is not None:
                for k, v in nal.attr_map().items():
                    t = v.tensorValue
                    data = _storage_data(t.storage) if t else None
                    if data is None:
                        continue
                    try:
                        self._by_tid[int(k)] = data
                    except ValueError:
                        pass
                    if t.storage.id is not None:
                        self._by_sid[int(t.storage.id)] = data

    def tensor_to_numpy(self, t: Optional[BigDLTensor]) -> \
            Optional[np.ndarray]:
        if t is None:
            return None
        data = _storage_data(t.storage)
        if data is None and t.id is not None:
            data = self._by_tid.get(int(t.id))
        if data is None and t.storage is not None and \
                t.storage.id is not None:
            data = self._by_sid.get(int(t.storage.id))
        if data is None:
            return None
        size = [int(s) for s in t.size]
        # BigDL storageOffset is 1-based (Torch heritage)
        offset = max(int(t.offset or 0) - 1, 0)
        n = int(np.prod(size)) if size else 1
        flat = data[offset:offset + n]
        return flat.reshape(size) if size else flat.reshape(())


def load_model(path_or_bytes) -> BigDLModule:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    m = BigDLModule()
    m.ParseFromString(data)
    return m
