"""keras2 locally-connected layers (reference
`P/pipeline/api/keras2/layers/local.py`,
`Z/pipeline/api/keras2/layers/LocallyConnected1D.scala`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _norm_tuple


class LocallyConnected1D(k1.LocallyConnected1D):
    """keras2 LocallyConnected1D (reference
    `keras2/layers/LocallyConnected1D.scala`)."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 activation=None, use_bias: bool = True,
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        (k,) = _norm_tuple(kernel_size, 1, "kernel_size")
        (s,) = _norm_tuple(strides, 1, "strides")
        super().__init__(filters, k, activation=activation,
                         subsample_length=s,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class LocallyConnected2D(k1.LocallyConnected2D):
    """keras2 LocallyConnected2D."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid",
                 data_format: str = "channels_last", activation=None,
                 use_bias: bool = True, input_shape=None, name=None,
                 **kwargs):
        if data_format != "channels_last":
            raise ValueError(
                "LocallyConnected2D supports channels_last only")
        kh, kw = _norm_tuple(kernel_size, 2, "kernel_size")
        super().__init__(filters, kh, kw, activation=activation,
                         border_mode=padding,
                         subsample=_norm_tuple(strides, 2, "strides"),
                         bias=use_bias, input_shape=input_shape,
                         name=name, **kwargs)

