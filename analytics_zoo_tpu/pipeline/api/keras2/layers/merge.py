"""keras2 merge layers (reference
`P/pipeline/api/keras2/layers/merge.py`,
`Z/pipeline/api/keras2/layers/{Average,Maximum,Minimum}.scala`):
identical multi-input semantics to the keras1 merge aliases."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1

Add = k1.Add
Multiply = k1.Multiply
Average = k1.Average
Maximum = k1.Maximum
Minimum = k1.Minimum
Concatenate = k1.Concatenate
Dot = k1.Dot


class Subtract(k1.Merge):
    """keras2 Subtract: first input minus the second."""

    def __init__(self, input_shape=None, name=None, **kwargs):
        super().__init__(mode="sub", input_shape=input_shape,
                         name=name, **kwargs)
