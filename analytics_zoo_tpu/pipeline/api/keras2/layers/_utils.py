"""Shared keras2 adapter helpers."""

from __future__ import annotations


def data_format_to_dim_ordering(data_format: str) -> str:
    """Keras-2 ``data_format`` → keras1 ``dim_ordering``."""
    if data_format == "channels_first":
        return "th"
    if data_format == "channels_last":
        return "tf"
    raise ValueError(
        f"data_format must be channels_first|channels_last, "
        f"got {data_format!r}")
