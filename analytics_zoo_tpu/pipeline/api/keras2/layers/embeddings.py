"""keras2 Embedding (reference
`P/pipeline/api/keras2/layers/embeddings.py`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1


class Embedding(k1.Embedding):
    """keras2 Embedding: `embeddings_initializer`/`embeddings_regularizer`
    spellings."""

    def __init__(self, input_dim: int, output_dim: int,
                 embeddings_initializer="uniform",
                 embeddings_regularizer=None, input_shape=None,
                 name=None, **kwargs):
        super().__init__(input_dim, output_dim,
                         init=embeddings_initializer,
                         w_regularizer=embeddings_regularizer,
                         input_shape=input_shape, name=name, **kwargs)
