"""keras2 convolution layers (reference
`P/pipeline/api/keras2/layers/convolutional.py`,
`Z/pipeline/api/keras2/layers/{Conv1D,Conv2D,Cropping1D}.scala`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _norm_tuple


from analytics_zoo_tpu.pipeline.api.keras2.layers._utils import (
    data_format_to_dim_ordering as _df)


class Conv1D(k1.Convolution1D):
    """keras2 Conv1D (reference `keras2/layers/Conv1D.scala`)."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        (k,) = _norm_tuple(kernel_size, 1, "kernel_size")
        (s,) = _norm_tuple(strides, 1, "strides")
        super().__init__(filters, k, init=kernel_initializer,
                         activation=activation, border_mode=padding,
                         subsample_length=s,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class Conv2D(k1.Convolution2D):
    """keras2 Conv2D (reference `keras2/layers/Conv2D.scala`).
    Channels-last by default (TPU-native), `data_format=
    "channels_first"` maps to the keras1 "th" ordering."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid",
                 data_format: str = "channels_last", activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        kh, kw = _norm_tuple(kernel_size, 2, "kernel_size")
        super().__init__(filters, kh, kw, init=kernel_initializer,
                         activation=activation, border_mode=padding,
                         subsample=_norm_tuple(strides, 2, "strides"),
                         dim_ordering=_df(data_format),
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class Conv3D(k1.Convolution3D):
    """keras2 Conv3D."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid",
                 data_format: str = "channels_last", activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        k1_, k2_, k3_ = _norm_tuple(kernel_size, 3, "kernel_size")
        super().__init__(filters, k1_, k2_, k3_,
                         init=kernel_initializer,
                         activation=activation, border_mode=padding,
                         subsample=_norm_tuple(strides, 3, "strides"),
                         dim_ordering=_df(data_format),
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class SeparableConv2D(k1.SeparableConvolution2D):
    """keras2 SeparableConv2D."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid",
                 data_format: str = "channels_last", activation=None,
                 use_bias: bool = True, input_shape=None, name=None,
                 **kwargs):
        kh, kw = _norm_tuple(kernel_size, 2, "kernel_size")
        super().__init__(filters, kh, kw, activation=activation,
                         border_mode=padding,
                         subsample=_norm_tuple(strides, 2, "strides"),
                         dim_ordering=_df(data_format), bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class Conv2DTranspose(k1.Deconvolution2D):
    """keras2 Conv2DTranspose."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid",
                 data_format: str = "channels_last", activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 input_shape=None, name=None, **kwargs):
        kh, kw = _norm_tuple(kernel_size, 2, "kernel_size")
        super().__init__(filters, kh, kw, init=kernel_initializer,
                         activation=activation, border_mode=padding,
                         subsample=_norm_tuple(strides, 2, "strides"),
                         dim_ordering=_df(data_format), bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class Cropping1D(k1.Cropping1D):
    """keras2 Cropping1D (reference
    `keras2/layers/Cropping1D.scala`)."""


class Cropping2D(k1.Cropping2D):
    """keras2 Cropping2D (keras2 adds data_format)."""

    def __init__(self, cropping=((0, 0), (0, 0)),
                 data_format: str = "channels_last", input_shape=None,
                 name=None, **kwargs):
        super().__init__(cropping=cropping,
                         dim_ordering=_df(data_format),
                         input_shape=input_shape, name=name, **kwargs)


class UpSampling1D(k1.UpSampling1D):
    """keras2 UpSampling1D (same arg spelling)."""


class UpSampling2D(k1.UpSampling2D):
    """keras2 UpSampling2D."""

    def __init__(self, size=(2, 2), data_format: str = "channels_last",
                 input_shape=None, name=None, **kwargs):
        super().__init__(size=size, dim_ordering=_df(data_format),
                         input_shape=input_shape, name=name, **kwargs)


class ZeroPadding1D(k1.ZeroPadding1D):
    """keras2 ZeroPadding1D (same arg spelling)."""


class ZeroPadding2D(k1.ZeroPadding2D):
    """keras2 ZeroPadding2D."""

    def __init__(self, padding=(1, 1),
                 data_format: str = "channels_last", input_shape=None,
                 name=None, **kwargs):
        super().__init__(padding=padding,
                         dim_ordering=_df(data_format),
                         input_shape=input_shape, name=name, **kwargs)
