"""keras2 noise layers (reference
`P/pipeline/api/keras2/layers/noise.py`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1


class GaussianNoise(k1.GaussianNoise):
    """keras2 GaussianNoise: `stddev` spelling."""

    def __init__(self, stddev: float, input_shape=None, name=None,
                 **kwargs):
        super().__init__(sigma=stddev, input_shape=input_shape,
                         name=name, **kwargs)


class GaussianDropout(k1.GaussianDropout):
    """keras2 GaussianDropout: `rate` spelling."""

    def __init__(self, rate: float, input_shape=None, name=None,
                 **kwargs):
        super().__init__(p=rate, input_shape=input_shape, name=name,
                         **kwargs)
