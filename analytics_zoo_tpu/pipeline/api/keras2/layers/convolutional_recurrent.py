"""keras2 convolutional-recurrent layers (reference
`P/pipeline/api/keras2/layers/convolutional_recurrent.py`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _norm_tuple


class ConvLSTM2D(k1.ConvLSTM2D):
    """keras2 ConvLSTM2D: `filters`/`kernel_size` spellings."""

    def __init__(self, filters: int, kernel_size,
                 activation="tanh", recurrent_activation="hard_sigmoid",
                 return_sequences: bool = False,
                 go_backwards: bool = False, input_shape=None,
                 name=None, **kwargs):
        kh, kw = _norm_tuple(kernel_size, 2, "kernel_size")
        super().__init__(nb_filter=filters, nb_kernel=(kh, kw),
                         activation=activation,
                         inner_activation=recurrent_activation,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards,
                         input_shape=input_shape, name=name, **kwargs)
