"""keras2 core layers (reference `P/pipeline/api/keras2/layers/core.py`,
`Z/pipeline/api/keras2/layers/{Dense,Activation,Dropout,Flatten,
Softmax}.scala`): thin Keras-2 arg-name adapters over the keras1
engine."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1


class Dense(k1.Dense):
    """keras2 Dense (reference `keras2/layers/Dense.scala`)."""

    def __init__(self, units: int, activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(output_dim=units, init=kernel_initializer,
                         activation=activation,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class Activation(k1.Activation):
    """keras2 Activation (reference `keras2/layers/Activation.scala`)."""


class Dropout(k1.Dropout):
    """keras2 Dropout (reference `keras2/layers/Dropout.scala`)."""

    def __init__(self, rate: float, input_shape=None, name=None,
                 **kwargs):
        super().__init__(p=rate, input_shape=input_shape, name=name,
                         **kwargs)


class Flatten(k1.Flatten):
    """keras2 Flatten (reference `keras2/layers/Flatten.scala`)."""


class Softmax(k1.Softmax):
    """keras2 Softmax (reference `keras2/layers/Softmax.scala`)."""


class Reshape(k1.Reshape):
    """keras2 Reshape (same arg spelling as keras1)."""


class Permute(k1.Permute):
    """keras2 Permute (same arg spelling)."""


class RepeatVector(k1.RepeatVector):
    """keras2 RepeatVector (same arg spelling)."""


class Masking(k1.Masking):
    """keras2 Masking (same arg spelling)."""
