"""keras2 pooling layers (reference
`P/pipeline/api/keras2/layers/pooling.py`,
`Z/pipeline/api/keras2/layers/{MaxPooling1D,AveragePooling1D,
Global*Pooling*}.scala`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1


from analytics_zoo_tpu.pipeline.api.keras2.layers._utils import (
    data_format_to_dim_ordering as _df)


class MaxPooling1D(k1.MaxPooling1D):
    """keras2 MaxPooling1D (reference
    `keras2/layers/MaxPooling1D.scala`)."""

    def __init__(self, pool_size: int = 2, strides=None,
                 padding: str = "valid", input_shape=None, name=None,
                 **kwargs):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, input_shape=input_shape,
                         name=name, **kwargs)


class AveragePooling1D(k1.AveragePooling1D):
    """keras2 AveragePooling1D (reference
    `keras2/layers/AveragePooling1D.scala`)."""

    def __init__(self, pool_size: int = 2, strides=None,
                 padding: str = "valid", input_shape=None, name=None,
                 **kwargs):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, input_shape=input_shape,
                         name=name, **kwargs)


class MaxPooling2D(k1.MaxPooling2D):
    """keras2 MaxPooling2D."""

    def __init__(self, pool_size=2, strides=None,
                 padding: str = "valid",
                 data_format: str = "channels_last",
                 input_shape=None, name=None, **kwargs):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=padding,
                         dim_ordering=_df(data_format),
                         input_shape=input_shape, name=name, **kwargs)


class AveragePooling2D(k1.AveragePooling2D):
    """keras2 AveragePooling2D."""

    def __init__(self, pool_size=2, strides=None,
                 padding: str = "valid",
                 data_format: str = "channels_last",
                 input_shape=None, name=None, **kwargs):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=padding,
                         dim_ordering=_df(data_format),
                         input_shape=input_shape, name=name, **kwargs)


class MaxPooling3D(k1.MaxPooling3D):
    """keras2 MaxPooling3D."""

    def __init__(self, pool_size=2, strides=None,
                 padding: str = "valid",
                 data_format: str = "channels_last",
                 input_shape=None, name=None, **kwargs):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=padding,
                         dim_ordering=_df(data_format),
                         input_shape=input_shape, name=name, **kwargs)


class AveragePooling3D(k1.AveragePooling3D):
    """keras2 AveragePooling3D."""

    def __init__(self, pool_size=2, strides=None,
                 padding: str = "valid",
                 data_format: str = "channels_last",
                 input_shape=None, name=None, **kwargs):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=padding,
                         dim_ordering=_df(data_format),
                         input_shape=input_shape, name=name, **kwargs)


# global pooling: names identical in keras2 (reference
# `keras2/layers/Global{Max,Average}Pooling{1,2,3}D.scala`)
GlobalMaxPooling1D = k1.GlobalMaxPooling1D
GlobalMaxPooling2D = k1.GlobalMaxPooling2D
GlobalMaxPooling3D = k1.GlobalMaxPooling3D
GlobalAveragePooling1D = k1.GlobalAveragePooling1D
GlobalAveragePooling2D = k1.GlobalAveragePooling2D
GlobalAveragePooling3D = k1.GlobalAveragePooling3D
