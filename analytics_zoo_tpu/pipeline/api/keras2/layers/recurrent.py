"""keras2 recurrent layers (reference
`P/pipeline/api/keras2/layers/recurrent.py`): `units`/
`recurrent_activation`/`recurrent_initializer` arg spellings over the
keras1 RNN kernels (which run as one `lax.scan` XLA loop)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1


class SimpleRNN(k1.SimpleRNN):
    """keras2 SimpleRNN."""

    def __init__(self, units: int, activation="tanh",
                 kernel_initializer="glorot_uniform",
                 recurrent_initializer="orthogonal",
                 kernel_regularizer=None, recurrent_regularizer=None,
                 bias_regularizer=None,
                 return_sequences: bool = False,
                 go_backwards: bool = False, input_shape=None,
                 name=None, **kwargs):
        super().__init__(output_dim=units, activation=activation,
                         init=kernel_initializer,
                         inner_init=recurrent_initializer,
                         w_regularizer=kernel_regularizer,
                         u_regularizer=recurrent_regularizer,
                         b_regularizer=bias_regularizer,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards,
                         input_shape=input_shape, name=name, **kwargs)


class LSTM(k1.LSTM):
    """keras2 LSTM."""

    def __init__(self, units: int, activation="tanh",
                 recurrent_activation="hard_sigmoid",
                 kernel_initializer="glorot_uniform",
                 recurrent_initializer="orthogonal",
                 kernel_regularizer=None, recurrent_regularizer=None,
                 bias_regularizer=None,
                 return_sequences: bool = False,
                 go_backwards: bool = False, input_shape=None,
                 name=None, **kwargs):
        super().__init__(output_dim=units, activation=activation,
                         inner_activation=recurrent_activation,
                         init=kernel_initializer,
                         inner_init=recurrent_initializer,
                         w_regularizer=kernel_regularizer,
                         u_regularizer=recurrent_regularizer,
                         b_regularizer=bias_regularizer,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards,
                         input_shape=input_shape, name=name, **kwargs)


class GRU(k1.GRU):
    """keras2 GRU."""

    def __init__(self, units: int, activation="tanh",
                 recurrent_activation="hard_sigmoid",
                 kernel_initializer="glorot_uniform",
                 recurrent_initializer="orthogonal",
                 kernel_regularizer=None, recurrent_regularizer=None,
                 bias_regularizer=None,
                 return_sequences: bool = False,
                 go_backwards: bool = False, input_shape=None,
                 name=None, **kwargs):
        super().__init__(output_dim=units, activation=activation,
                         inner_activation=recurrent_activation,
                         init=kernel_initializer,
                         inner_init=recurrent_initializer,
                         w_regularizer=kernel_regularizer,
                         u_regularizer=recurrent_regularizer,
                         b_regularizer=bias_regularizer,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards,
                         input_shape=input_shape, name=name, **kwargs)
