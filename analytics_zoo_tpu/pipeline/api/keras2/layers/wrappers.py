"""keras2 wrapper layers (reference
`P/pipeline/api/keras2/layers/wrappers.py`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1

# identical signatures in keras2
TimeDistributed = k1.TimeDistributed
Bidirectional = k1.Bidirectional
