"""keras2 BatchNormalization (reference
`P/pipeline/api/keras2/layers/normalization.py`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1


class BatchNormalization(k1.BatchNormalization):
    """keras2 BatchNormalization: `axis`/`momentum`/`epsilon` keras-2
    conventions (momentum is the moving-average DECAY, same as our
    keras1 layer)."""

    def __init__(self, axis: int = -1, momentum: float = 0.99,
                 epsilon: float = 1e-3, center: bool = True,
                 scale: bool = True, input_shape=None, name=None,
                 **kwargs):
        # axis=-1 → channels_last ("tf"); axis=1 → channels_first ("th")
        if axis in (-1, 3, 4):
            dim_ordering = "tf"
        elif axis == 1:
            dim_ordering = "th"
        else:
            raise ValueError(
                f"unsupported BatchNormalization axis {axis} "
                "(use -1 for channels_last or 1 for channels_first)")
        super().__init__(epsilon=epsilon, momentum=momentum,
                         center=center, scale=scale,
                         dim_ordering=dim_ordering,
                         input_shape=input_shape, name=name, **kwargs)
