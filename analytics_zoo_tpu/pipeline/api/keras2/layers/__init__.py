"""Keras-2-style layer variants (reference `Z/pipeline/api/keras2/layers/`
and `P/pipeline/api/keras2/` — 21 files of Keras-2 arg-name adapters over
the keras1 library).

Exactly like the reference, these are thin subclasses translating Keras-2
argument names (`units`, `filters`, `kernel_size`, `strides`, `padding`,
`rate`, `use_bias`, `kernel_initializer`, ...) onto the keras1 engine —
kernels and semantics are shared.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1
from analytics_zoo_tpu.pipeline.api.keras.layers.conv import _norm_tuple


class Dense(k1.Dense):
    """keras2 Dense (reference `keras2/layers/Dense.scala`)."""

    def __init__(self, units: int, activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        super().__init__(output_dim=units, init=kernel_initializer,
                         activation=activation,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class Activation(k1.Activation):
    """keras2 Activation (reference `keras2/layers/Activation.scala`)."""


class Dropout(k1.Dropout):
    """keras2 Dropout (reference `keras2/layers/Dropout.scala`)."""

    def __init__(self, rate: float, input_shape=None, name=None, **kwargs):
        super().__init__(p=rate, input_shape=input_shape, name=name,
                         **kwargs)


class Flatten(k1.Flatten):
    """keras2 Flatten (reference `keras2/layers/Flatten.scala`)."""


class Softmax(k1.Softmax):
    """keras2 Softmax (reference `keras2/layers/Softmax.scala`)."""


class Conv1D(k1.Convolution1D):
    """keras2 Conv1D (reference `keras2/layers/Conv1D.scala`)."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        (k,) = _norm_tuple(kernel_size, 1, "kernel_size")
        (s,) = _norm_tuple(strides, 1, "strides")
        super().__init__(filters, k, init=kernel_initializer,
                         activation=activation, border_mode=padding,
                         subsample_length=s,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class Conv2D(k1.Convolution2D):
    """keras2 Conv2D (reference `keras2/layers/Conv2D.scala`).
    Channels-last by default (TPU-native), `data_format="channels_first"`
    maps to the keras1 "th" ordering."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid",
                 data_format: str = "channels_last", activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        kh, kw = _norm_tuple(kernel_size, 2, "kernel_size")
        super().__init__(filters, kh, kw, init=kernel_initializer,
                         activation=activation, border_mode=padding,
                         subsample=_norm_tuple(strides, 2, "strides"),
                         dim_ordering=("th" if data_format ==
                                       "channels_first" else "tf"),
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


class MaxPooling1D(k1.MaxPooling1D):
    """keras2 MaxPooling1D (reference `keras2/layers/MaxPooling1D.scala`)."""

    def __init__(self, pool_size: int = 2, strides=None,
                 padding: str = "valid", input_shape=None, name=None,
                 **kwargs):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, input_shape=input_shape,
                         name=name, **kwargs)


class AveragePooling1D(k1.AveragePooling1D):
    """keras2 AveragePooling1D (reference
    `keras2/layers/AveragePooling1D.scala`)."""

    def __init__(self, pool_size: int = 2, strides=None,
                 padding: str = "valid", input_shape=None, name=None,
                 **kwargs):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, input_shape=input_shape,
                         name=name, **kwargs)


class Cropping1D(k1.Cropping1D):
    """keras2 Cropping1D (reference `keras2/layers/Cropping1D.scala`)."""


class LocallyConnected1D(k1.LocallyConnected1D):
    """keras2 LocallyConnected1D (reference
    `keras2/layers/LocallyConnected1D.scala`)."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 activation=None, use_bias: bool = True,
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None, **kwargs):
        (k,) = _norm_tuple(kernel_size, 1, "kernel_size")
        (s,) = _norm_tuple(strides, 1, "strides")
        super().__init__(filters, k, activation=activation,
                         subsample_length=s,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, bias=use_bias,
                         input_shape=input_shape, name=name, **kwargs)


# merge-op layers: identical to keras1 merge aliases
Maximum = k1.Maximum
Minimum = k1.Minimum
Average = k1.Average

# global pooling: names are identical in keras2
GlobalMaxPooling1D = k1.GlobalMaxPooling1D
GlobalMaxPooling2D = k1.GlobalMaxPooling2D
GlobalMaxPooling3D = k1.GlobalMaxPooling3D
GlobalAveragePooling1D = k1.GlobalAveragePooling1D
GlobalAveragePooling2D = k1.GlobalAveragePooling2D
GlobalAveragePooling3D = k1.GlobalAveragePooling3D

__all__ = [
    "Dense", "Activation", "Dropout", "Flatten", "Softmax",
    "Conv1D", "Conv2D", "MaxPooling1D", "AveragePooling1D", "Cropping1D",
    "LocallyConnected1D", "Maximum", "Minimum", "Average",
    "GlobalMaxPooling1D", "GlobalMaxPooling2D", "GlobalMaxPooling3D",
    "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalAveragePooling3D",
]
