"""Keras-2-style layer variants (reference `Z/pipeline/api/keras2/layers/`
— 21 Scala files — and the full Python mirror
`P/pipeline/api/keras2/layers/{core,convolutional,pooling,merge,
recurrent,convolutional_recurrent,embeddings,normalization,
advanced_activations,noise,local,wrappers}.py`).

Exactly like the reference, these are thin adapters translating Keras-2
argument names (`units`, `filters`, `kernel_size`, `strides`,
`padding`, `rate`, `use_bias`, `kernel_initializer`,
`recurrent_activation`, ...) onto the keras1 engine — kernels and
semantics are shared, so keras2 models run the same XLA programs.
"""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras2.layers.core import (
    Activation, Dense, Dropout, Flatten, Masking, Permute,
    RepeatVector, Reshape, Softmax)
from analytics_zoo_tpu.pipeline.api.keras2.layers.convolutional import (
    Conv1D, Conv2D, Conv2DTranspose, Conv3D, Cropping1D, Cropping2D,
    SeparableConv2D, UpSampling1D, UpSampling2D, ZeroPadding1D,
    ZeroPadding2D)
from analytics_zoo_tpu.pipeline.api.keras2.layers.pooling import (
    AveragePooling1D, AveragePooling2D, AveragePooling3D,
    GlobalAveragePooling1D, GlobalAveragePooling2D,
    GlobalAveragePooling3D, GlobalMaxPooling1D, GlobalMaxPooling2D,
    GlobalMaxPooling3D, MaxPooling1D, MaxPooling2D, MaxPooling3D)
from analytics_zoo_tpu.pipeline.api.keras2.layers.merge import (
    Add, Average, Concatenate, Dot, Maximum, Minimum, Multiply,
    Subtract)
from analytics_zoo_tpu.pipeline.api.keras2.layers.recurrent import (
    GRU, LSTM, SimpleRNN)
from analytics_zoo_tpu.pipeline.api.keras2.layers \
    .convolutional_recurrent import ConvLSTM2D
from analytics_zoo_tpu.pipeline.api.keras2.layers.embeddings import (
    Embedding)
from analytics_zoo_tpu.pipeline.api.keras2.layers.normalization import (
    BatchNormalization)
from analytics_zoo_tpu.pipeline.api.keras2.layers \
    .advanced_activations import (ELU, LeakyReLU, PReLU,
                                  ThresholdedReLU)
from analytics_zoo_tpu.pipeline.api.keras2.layers.noise import (
    GaussianDropout, GaussianNoise)
from analytics_zoo_tpu.pipeline.api.keras2.layers.local import (
    LocallyConnected1D, LocallyConnected2D)
from analytics_zoo_tpu.pipeline.api.keras2.layers.wrappers import (
    Bidirectional, TimeDistributed)

__all__ = [
    # core
    "Dense", "Activation", "Dropout", "Flatten", "Softmax", "Reshape",
    "Permute", "RepeatVector", "Masking",
    # convolutional
    "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose", "SeparableConv2D",
    "Cropping1D", "Cropping2D", "UpSampling1D", "UpSampling2D",
    "ZeroPadding1D", "ZeroPadding2D",
    # pooling
    "MaxPooling1D", "MaxPooling2D", "MaxPooling3D", "AveragePooling1D",
    "AveragePooling2D", "AveragePooling3D", "GlobalMaxPooling1D",
    "GlobalMaxPooling2D", "GlobalMaxPooling3D",
    "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalAveragePooling3D",
    # merge
    "Add", "Subtract", "Multiply", "Average", "Maximum", "Minimum",
    "Concatenate", "Dot",
    # recurrent
    "SimpleRNN", "LSTM", "GRU", "ConvLSTM2D",
    # embeddings / normalization / activations / noise
    "Embedding", "BatchNormalization", "LeakyReLU", "ELU", "PReLU",
    "ThresholdedReLU", "GaussianNoise", "GaussianDropout",
    # local / wrappers
    "LocallyConnected1D", "LocallyConnected2D", "TimeDistributed",
    "Bidirectional",
]
