"""keras2 advanced activations (reference
`P/pipeline/api/keras2/layers/advanced_activations.py`)."""

from __future__ import annotations

from analytics_zoo_tpu.pipeline.api.keras import layers as k1


class LeakyReLU(k1.LeakyReLU):
    """keras2 LeakyReLU: `alpha` spelling (same as keras1)."""


class ELU(k1.ELU):
    """keras2 ELU (same arg spelling)."""


class PReLU(k1.PReLU):
    """keras2 PReLU (same arg spelling)."""


class ThresholdedReLU(k1.ThresholdedReLU):
    """keras2 ThresholdedReLU: `theta` spelling."""

    def __init__(self, theta: float = 1.0, input_shape=None, name=None,
                 **kwargs):
        super().__init__(theta=theta, input_shape=input_shape,
                         name=name, **kwargs)

