"""Keras-2-style API surface (reference `Z/pipeline/api/keras2/`,
`P/pipeline/api/keras2/`). Layers carry Keras-2 argument names; the model
containers are shared with the keras1 engine (the reference does the
same — keras2 layers extend keras1's `KerasLayer`)."""

from analytics_zoo_tpu.pipeline.api.keras import Sequential, Model
from analytics_zoo_tpu.pipeline.api.keras2 import layers

__all__ = ["Sequential", "Model", "layers"]
