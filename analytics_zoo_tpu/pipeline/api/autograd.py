"""Autograd surface (L3): `Variable` ops, `Parameter`, `CustomLoss`,
`Lambda`.

The reference implements symbolic autograd by lazily wrapping every op in a
BigDL layer node (`Z/pipeline/api/autograd/math.scala:32-594`,
`KerasParameter.scala`, `CustomLoss.scala`, `Lambda.scala`). On TPU, JAX
*is* the autograd — so this module only keeps the reference's authoring
API: the same op vocabulary building nodes on the functional graph from
`keras.engine`, differentiated for free by `jax.grad` inside the training
step.

Axis convention (matches the reference): `axis` counts the batch dimension
as 0; graph shapes exclude batch, so `axis >= 1` addresses the symbolic
dims. Reducing over the batch axis inside a graph is not supported.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras.engine import (
    KerasLayer, Shape, Variable, as_shape, unique_name)

EPSILON = 1e-7

VarOrScalar = Union[Variable, float, int]


class _OpLayer(KerasLayer):
    """A layer wrapping an arbitrary array function, used to lower autograd
    ops onto the functional graph (the analog of the reference wrapping
    each op in a BigDL module)."""

    def __init__(self, fn: Callable, shape_fn: Callable, name=None):
        super().__init__(name=name or unique_name("op"))
        self.fn = fn
        self.shape_fn = shape_fn

    def call(self, params, inputs, *, training=False, rng=None):
        return self.fn(inputs)

    def compute_output_shape(self, input_shape):
        return self.shape_fn(input_shape)


class Lambda(_OpLayer):
    """User function → layer (reference `autograd/Lambda.scala`).

    Divergence from the reference: the function operates on jnp arrays
    (it runs under jit and is differentiated by JAX), not on Variables —
    strictly more expressive since any traceable JAX code is allowed.
    """

    def __init__(self, function: Callable, output_shape=None,
                 input_shape=None, name=None):
        shape_fn = ((lambda s: as_shape(output_shape))
                    if output_shape is not None else (lambda s: s))
        super().__init__(function, shape_fn,
                         name=name or unique_name("lambda"))
        self._given_input_shape = (None if input_shape is None
                                   else as_shape(input_shape))


class _ParameterLayer(KerasLayer):
    """Standalone trainable weight (reference `KerasParameter.scala:31-104`).
    A zero-input graph node whose output is the weight itself."""

    def __init__(self, shape: Shape, init_weight=None, name=None):
        super().__init__(name=name or unique_name("parameter"))
        self.shape = as_shape(shape)
        self.init_weight = (None if init_weight is None
                            else np.asarray(init_weight, np.float32))

    def build(self, rng, input_shape):
        if self.init_weight is not None:
            if tuple(self.init_weight.shape) != self.shape:
                raise ValueError(
                    f"init_weight shape {self.init_weight.shape} != "
                    f"declared {self.shape}")
            return {"weight": jnp.asarray(self.init_weight)}
        scale = 0.05
        return {"weight": jax.random.uniform(
            rng, self.shape, jnp.float32, -scale, scale)}

    def call(self, params, inputs, *, training=False, rng=None):
        return params["weight"]

    def compute_output_shape(self, input_shape):
        return self.shape


class _ConstantLayer(KerasLayer):
    """Literal value node (reference `KerasConstant`,
    `KerasParameter.scala:181`)."""

    def __init__(self, value, name=None):
        super().__init__(name=name or unique_name("constant"))
        self.value = np.asarray(value, np.float32)
        self.trainable = False

    def build(self, rng, input_shape):
        return {}

    def call(self, params, inputs, *, training=False, rng=None):
        return jnp.asarray(self.value)

    def compute_output_shape(self, input_shape):
        return tuple(self.value.shape)


def Parameter(shape, init_weight=None, name=None) -> Variable:
    """Create a trainable standalone weight variable."""
    layer = _ParameterLayer(as_shape(shape), init_weight, name=name)
    return Variable(shape=layer.shape, layer=layer, parents=[])


def Constant(value, name=None) -> Variable:
    layer = _ConstantLayer(value, name=name)
    return Variable(shape=tuple(layer.value.shape), layer=layer,
                    parents=[])


# ---------------------------------------------------------------------------
# op builders
# ---------------------------------------------------------------------------

def _norm_axis(axis: int, var: Variable) -> int:
    """Reference axis (0 = batch) → runtime array axis; rejects batch."""
    ndim = len(var.shape) + 1
    if axis < 0:
        axis = ndim + axis
    if axis == 0:
        raise ValueError("reducing/indexing over the batch axis inside the "
                         "graph is not supported")
    return axis


def _reduce_shape(shape: Shape, axis: int, keepdims: bool) -> Shape:
    # axis already normalized (>=1); shape excludes batch
    idx = axis - 1
    s = list(shape)
    if keepdims:
        s[idx] = 1
    else:
        del s[idx]
    return tuple(s)


def _unary(var: Variable, fn: Callable, name: str,
           shape_fn: Optional[Callable] = None) -> Variable:
    return _OpLayer(fn, shape_fn or (lambda s: s),
                    name=unique_name(name))(var)


def _binary(a: Variable, b: VarOrScalar, fn: Callable, name: str,
            shape_fn: Optional[Callable] = None) -> Variable:
    if isinstance(b, Variable):
        sf = shape_fn or (lambda shapes: _broadcast_shape(*shapes))
        return _OpLayer(lambda xs: fn(xs[0], xs[1]), sf,
                        name=unique_name(name))([a, b])
    const = b
    return _OpLayer(lambda x: fn(x, const), shape_fn or (lambda s: s),
                    name=unique_name(name))(a)


def _broadcast_shape(sa: Shape, sb: Shape) -> Shape:
    out = list(np.broadcast_shapes(tuple(sa), tuple(sb)))
    return tuple(out)


def add(a, b) -> Variable:
    return _binary(a, b, lambda x, y: x + y, "add")


def sub(a, b) -> Variable:
    return _binary(a, b, lambda x, y: x - y, "sub")


def rsub(a, b) -> Variable:
    return _binary(a, b, lambda x, y: y - x, "rsub")


def mul(a, b) -> Variable:
    return _binary(a, b, lambda x, y: x * y, "mul")


def div(a, b) -> Variable:
    return _binary(a, b, lambda x, y: x / y, "div")


def rdiv(a, b) -> Variable:
    return _binary(a, b, lambda x, y: y / x, "rdiv")


def neg(a) -> Variable:
    return _unary(a, lambda x: -x, "neg")


def abs(a) -> Variable:  # noqa: A001 — matches reference AutoGrad.abs
    return _unary(a, jnp.abs, "abs")


def square(a) -> Variable:
    return _unary(a, jnp.square, "square")


def sqrt(a) -> Variable:
    return _unary(a, jnp.sqrt, "sqrt")


def log(a) -> Variable:
    return _unary(a, jnp.log, "log")


def exp(a) -> Variable:
    return _unary(a, jnp.exp, "exp")


def pow(a, p) -> Variable:  # noqa: A001
    return _unary(a, lambda x: jnp.power(x, p), "pow")


def softsign(a) -> Variable:
    return _unary(a, jax.nn.soft_sign, "softsign")


def softplus(a) -> Variable:
    return _unary(a, jax.nn.softplus, "softplus")


def clip(a, min_value: float, max_value: float) -> Variable:
    return _unary(a, lambda x: jnp.clip(x, min_value, max_value), "clip")


def epsilon() -> float:
    return EPSILON


def maximum(a, b) -> Variable:
    return _binary(a, b, jnp.maximum, "maximum")


def minimum(a, b) -> Variable:
    return _binary(a, b, jnp.minimum, "minimum")


def sum(a: Variable, axis: int = 1, keepdims: bool = False) -> Variable:  # noqa: A001
    ax = _norm_axis(axis, a)
    return _unary(a, lambda x: jnp.sum(x, axis=ax, keepdims=keepdims),
                  "sum", lambda s: _reduce_shape(s, ax, keepdims))


def mean(a: Variable, axis: int = 1, keepdims: bool = False) -> Variable:
    ax = _norm_axis(axis, a)
    return _unary(a, lambda x: jnp.mean(x, axis=ax, keepdims=keepdims),
                  "mean", lambda s: _reduce_shape(s, ax, keepdims))


def max(a: Variable, axis: int = 1, keepdims: bool = False) -> Variable:  # noqa: A001
    ax = _norm_axis(axis, a)
    return _unary(a, lambda x: jnp.max(x, axis=ax, keepdims=keepdims),
                  "max", lambda s: _reduce_shape(s, ax, keepdims))


def stack(inputs: Sequence[Variable], axis: int = 1) -> Variable:
    ax = _norm_axis(axis, inputs[0])

    def shape_fn(shapes):
        s = list(shapes[0])
        s.insert(ax - 1, len(inputs))
        return tuple(s)

    return _OpLayer(lambda xs: jnp.stack(xs, axis=ax), shape_fn,
                    name=unique_name("stack"))(list(inputs))


def expand_dims(a: Variable, axis: int) -> Variable:
    ax = _norm_axis(axis, a)

    def shape_fn(s):
        out = list(s)
        out.insert(ax - 1, 1)
        return tuple(out)

    return _unary(a, lambda x: jnp.expand_dims(x, ax), "expanddims",
                  shape_fn)


def squeeze(a: Variable, dim: Optional[int] = None) -> Variable:
    if dim is None:
        def shape_fn(s):
            return tuple(d for d in s if d != 1)
        return _unary(a, lambda x: jnp.squeeze(
            x, axis=tuple(i for i in range(1, x.ndim)
                          if x.shape[i] == 1)), "squeeze", shape_fn)
    ax = _norm_axis(dim, a)

    def shape_fn(s):
        out = list(s)
        del out[ax - 1]
        return tuple(out)

    return _unary(a, lambda x: jnp.squeeze(x, axis=ax), "squeeze",
                  shape_fn)


def contiguous(a: Variable) -> Variable:
    return _unary(a, lambda x: x, "contiguous")


def slice_var(a: Variable, idx) -> Variable:
    """`v[...]` — numpy basic indexing on non-batch dims (reference
    Variable.slice/indexSelect)."""
    full_idx = (slice(None),) + (idx if isinstance(idx, tuple) else (idx,))

    def shape_fn(s):
        probe = np.zeros((1,) + tuple(s), np.int8)[full_idx]
        return tuple(probe.shape[1:])

    return _unary(a, lambda x: x[full_idx], "slice", shape_fn)


def mm(a: Variable, b: Variable, axes: Optional[Sequence[int]] = None
       ) -> Variable:
    """Matrix multiply (reference `AutoGrad.mm`, math.scala)."""
    def fn(x, y):
        return jnp.matmul(x, y)

    def shape_fn(shapes):
        sa, sb = shapes
        return tuple(sa[:-1]) + (sb[-1],)

    if axes is not None:
        return batch_dot(a, b, axes)
    return _OpLayer(lambda xs: fn(xs[0], xs[1]), shape_fn,
                    name=unique_name("mm"))([a, b])


def batch_dot(a: Variable, b: Variable, axes: Sequence[int] = (2, 1)
              ) -> Variable:
    """Keras-style batch_dot: contract `axes` (batch-inclusive indices)
    per-sample."""
    ax_a, ax_b = axes

    def fn(xs):
        x, y = xs
        return jax.vmap(
            lambda u, v: jnp.tensordot(u, v,
                                       axes=((ax_a - 1,), (ax_b - 1,))))(
            x, y)

    def shape_fn(shapes):
        sa = list(shapes[0])
        sb = list(shapes[1])
        del sa[ax_a - 1]
        del sb[ax_b - 1]
        return tuple(sa + sb)

    return _OpLayer(fn, shape_fn, name=unique_name("batchdot"))([a, b])


def l2_normalize(a: Variable, axis: int = 1) -> Variable:
    ax = _norm_axis(axis, a)
    return _unary(
        a, lambda x: x / jnp.maximum(
            jnp.linalg.norm(x, axis=ax, keepdims=True), EPSILON),
        "l2normalize")


# ---------------------------------------------------------------------------
# CustomLoss
# ---------------------------------------------------------------------------

class CustomLoss:
    """Build a loss function from a Variable lambda (reference
    `autograd/CustomLoss.scala:34`).

    ``loss_func(y_true, y_pred)`` receives Variables and returns a
    Variable (any shape — the result is mean-reduced). The instance is a
    plain ``(y_true, y_pred) -> scalar`` callable usable anywhere an
    objective is accepted (see `keras.objectives`).
    """

    def __init__(self, loss_func: Callable[[Variable, Variable], Variable],
                 y_pred_shape: Shape, y_true_shape: Optional[Shape] = None):
        from analytics_zoo_tpu.pipeline.api.keras.engine import Input
        from analytics_zoo_tpu.pipeline.api.keras.models import Model
        y_pred_shape = as_shape(y_pred_shape)
        y_true_shape = (as_shape(y_true_shape) if y_true_shape is not None
                        else y_pred_shape)
        y_true_v = Input(y_true_shape, name=unique_name("y_true"))
        y_pred_v = Input(y_pred_shape, name=unique_name("y_pred"))
        out = loss_func(y_true_v, y_pred_v)
        if not isinstance(out, Variable):
            raise TypeError("loss_func must return a Variable")
        self._model = Model([y_true_v, y_pred_v], out)
        self._params = self._model.init(jax.random.key(0))

    def __call__(self, y_true, y_pred):
        val = self._model.forward(self._params, [y_true, y_pred])
        return jnp.mean(val)
