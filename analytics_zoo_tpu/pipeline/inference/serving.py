"""HTTP serving facade over InferenceModel.

Plays the role of the reference's plain-Java `AbstractInferenceModel`
POJO + Spring-boot web-service samples (reference
`java/.../inference/AbstractInferenceModel.java:25-103`,
`apps/web-service-sample/`): a language-agnostic boundary for web
services, here a stdlib HTTP/JSON endpoint (no framework deps).

POST /predict  {"inputs": [[...], ...]}  →  {"outputs": [[...], ...]}
GET  /health   →  {"status": "ok", "free_slots": N, "batcher": {...}}
GET  /metrics  →  Prometheus text exposition (docs/observability.md);
     ``?fleet=1`` on a fleet front door serves the MERGED fleet view
     from the federation collector (ticked first unless ``tick=0``)
GET  /metrics/json  →  registry snapshot as JSON (the federation
     collector's scrape format; explicit application/json)
GET  /debug/traces[?n=20]  →  recent traces as JSON (docs/observability.md);
     ``?since=<seq>`` switches to the incremental span scrape the
     federation collector uses (cursor + new spans, zero loss/dup);
     ``?fleet=1`` lists stitched traces from the fleet aggregator
GET  /debug/trace/<id>[?chrome=1]  →  ONE stitched cross-process
     timeline for a trace id (fleet aggregator when mounted, local
     ring otherwise); ``chrome=1`` renders Perfetto JSON with one
     process lane per source
GET  /debug/fleet/telemetry  →  federation collector state (sources,
     scrape health, skew verdicts); 404 when no collector mounted
GET  /debug/slo[?tick=0]  →  live SLO status (docs/slo.md): shipped
     serving objectives (p99 latency, error burn rate, queue depth)
     are installed at server start; the engine re-evaluates on each
     request unless ``tick=0``
GET  /debug/fleet  →  fleet topology + per-replica lifecycle state
     when a FleetRouter fronts this server (docs/serving.md fleet
     section); 404 on single-model servers
GET  /debug/rollout  →  warm-swap rollout state machine + canary
     split + per-replica versions (docs/robustness.md); 404 on
     single-model servers
GET  /debug/metrics/history[?family=&window=&fleet=1]  →  windowed
     metric time series from the in-process history store
     (docs/observability.md §History): no ``family`` lists known
     families + store stats; with one, per-label-set points
     (counters as deltas+rates, histograms as quantile summaries).
     ``fleet=1`` reads the federation collector's merged fleet
     timeline instead of the local store
GET  /debug/dashboard  →  dependency-free single-file HTML live
     dashboard (inline SVG sparklines over the history API: QPS,
     p99, queue depth, goodput/MFU, KV pages free, forecast ETAs,
     anomaly rate + SLO state); ``?fleet=1`` renders the merged
     fleet timeline
POST /debug/profile {"dir": ..., "ms": 500}  →  on-demand jax.profiler
     capture written to ``dir`` (one at a time; 503 while busy)

Tracing: /predict accepts and echoes an ``X-Zoo-Trace-Id`` header
(minted server-side when absent); the request runs under that trace,
so the batcher's queue/pad/execute/scatter child spans and the model
span land in ``GET /debug/traces`` under one id. ``ZOO_TPU_TRACE=0``
disables all of it (the hot path then skips trace bookkeeping
entirely).

Requests route through a :class:`DynamicBatcher`
(`pipeline/inference/batching.py`, docs/serving.md) by default:
cross-request coalescing onto AOT-warmed bucket shapes, with
backpressure. ``ZOO_TPU_SERVING_BATCH=0`` (or ``batcher=None``)
reverts to the per-request path.

Errors are structured JSON — ``{"error": {"code": N, "message": ...}}``
— with real status codes: 404 for unknown paths, 400 for malformed
JSON / missing "inputs" / un-coercible inputs, 500 for model and
runtime failures, 503 (+ ``Retry-After``) when the batcher queue is
full, 504 when a queued request's deadline expires. Each increments
``zoo_tpu_serving_errors_total{kind=...}``.
"""

from __future__ import annotations

import json
import time
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from analytics_zoo_tpu.common import diagnostics
from analytics_zoo_tpu.common import forecast as forecast_lib
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common import slo as slo_lib
from analytics_zoo_tpu.common import timeseries
from analytics_zoo_tpu.common import tracing
from analytics_zoo_tpu.pipeline.inference.batching import (
    DeadlineExpiredError, DynamicBatcher, QueueFullError)
from analytics_zoo_tpu.pipeline.inference.inference_model import (
    InferenceModel)


def _error_body(code: int, message: str, **extra) -> dict:
    err = {"code": code, "message": message}
    err.update(extra)
    return {"error": err}


def _count_error(kind: str):
    obs.counter("zoo_tpu_serving_errors_total",
                help="serving errors by kind",
                labels={"kind": kind}).inc()


def _record_request(path: str, status: int, dt: float):
    """Shared per-request telemetry for both HTTP front-ends. Query
    strings are stripped so label cardinality stays bounded."""
    path = path.split("?", 1)[0]
    obs.counter("zoo_tpu_serving_requests_total",
                help="HTTP requests served",
                labels={"path": path, "status": str(status)}).inc()
    obs.histogram("zoo_tpu_serving_request_seconds",
                  help="request latency (handler wall time)",
                  labels={"path": path}).observe(dt)


def _in_flight() -> "obs.Gauge":
    return obs.gauge("zoo_tpu_serving_in_flight",
                     help="requests currently being handled")


def _coerce_inputs(model: InferenceModel, inputs) -> "list":
    """JSON inputs → list of arrays, honoring the loaded model's
    declared example-input dtypes when available (an embedding/NCF
    model's integer ids must NOT be silently cast to f32); f32 is the
    fallback for undeclared models. Raises ValueError/TypeError on
    un-coercible payloads (ragged rows, non-numeric) — a CLIENT
    error."""
    specs = model.example_input_specs

    def dtype_for(i: int):
        if specs is not None and i < len(specs):
            return specs[i][1]
        return np.float32

    if isinstance(inputs, list) and inputs and \
            isinstance(inputs[0], dict):
        return [np.asarray(d["data"], dtype_for(i))
                for i, d in enumerate(inputs)]
    return [np.asarray(inputs, dtype_for(0))]


def handle_predict(model: InferenceModel, body: bytes,
                   batcher: "Optional[DynamicBatcher]" = None
                   ) -> "Tuple[int, dict]":
    """The /predict contract, shared by the stdlib and native
    front-ends: JSON body → (http_status, payload_dict). With a
    ``batcher``, row-aligned requests ride the coalescing path
    (docs/serving.md); without one (or for inputs the batcher cannot
    coalesce) the model runs per-request.

    Status mapping: client mistakes are 400 (malformed JSON, missing
    "inputs", un-coercible arrays), backpressure is 503 with a
    ``retry_after_s`` hint, expired deadlines are 504, and model or
    runtime failures are 500 ``kind="internal"``."""
    try:
        req = json.loads(body)
    except (ValueError, UnicodeDecodeError) as e:
        _count_error("bad_json")
        return 400, _error_body(400, f"malformed JSON body: {e}")
    try:
        inputs = req["inputs"]
    except (KeyError, TypeError):
        _count_error("bad_request")
        return 400, _error_body(
            400, 'request must be a JSON object with an "inputs" key')
    try:
        xs = _coerce_inputs(model, inputs)
    except (ValueError, TypeError, KeyError) as e:
        _count_error("bad_request")
        return 400, _error_body(
            400, f"inputs are not coercible to arrays: {e}")
    try:
        if batcher is not None and batcher.batchable(xs):
            out = batcher.submit(xs).result()
        else:
            out = model.predict(xs if len(xs) > 1 else xs[0])
        if isinstance(out, list):
            if len(out) == 1:
                return 200, {"outputs": out[0].tolist()}
            return 200, {"outputs": [o.tolist() for o in out]}
        return 200, {"outputs": out.tolist()}
    except QueueFullError as e:
        # admission control: bounded queueing latency, not unbounded
        # (the batcher already counted kind="queue_full")
        return 503, _error_body(
            503, str(e), retry_after_s=round(e.retry_after_s, 3))
    except DeadlineExpiredError as e:
        # the batcher already counted kind="deadline_expired"
        return 504, _error_body(504, str(e))
    except Exception as e:  # serving boundary: report, not die
        _count_error("internal")
        return 500, _error_body(500, str(e), kind="internal")


def handle_generate(model: InferenceModel, body: bytes,
                    gen_batcher=None) -> "Tuple[int, dict]":
    """The /generate contract, shared by both front-ends: JSON body →
    (http_status, payload_dict).

    Request: ``{"prompt": [ids...]}`` (one sequence) or
    ``{"prompts": [[ids...], ...]}``, with optional
    ``max_new_tokens`` (default 32), ``temperature`` (default 0 =
    greedy) and ``eos_id``. Response mirrors the request's shape:
    ``{"tokens": [...]}`` or ``{"tokens": [[...], ...]}`` — the NEWLY
    generated ids only (eos, when hit, included).

    With a :class:`ContinuousBatcher` the sequences join the live
    decode batch (one compiled step, token-boundary admission —
    docs/serving.md); without one they run the sequential compiled
    whole-loop path (`InferenceModel.generate`). The engine-side
    capacity levers — chunked prefill, int8 paged KV, speculative
    decoding (docs/serving.md, docs/perf_flags.md) — are transparent
    to this contract: same request/response either way, with the
    active configuration reported under ``generator`` in
    ``GET /health``. 501 when the model has no generator loaded."""
    try:
        req = json.loads(body)
    except (ValueError, UnicodeDecodeError) as e:
        _count_error("bad_json")
        return 400, _error_body(400, f"malformed JSON body: {e}")
    if not isinstance(req, dict) or \
            ("prompt" not in req) == ("prompts" not in req):
        _count_error("bad_request")
        return 400, _error_body(
            400, 'request must be a JSON object with exactly one of '
            '"prompt" (one token-id list) or "prompts" (a list of '
            'them)')
    if gen_batcher is None and \
            getattr(model, "generator", None) is None:
        _count_error("no_generator")
        return 501, _error_body(
            501, "this server has no generative model loaded "
            "(InferenceModel.load_generator)")
    single = "prompt" in req
    prompts = [req["prompt"]] if single else req["prompts"]
    try:
        prompts = [[int(t) for t in p] for p in prompts]
        max_new = int(req.get("max_new_tokens", 32))
        temperature = float(req.get("temperature", 0.0))
        eos_id = req.get("eos_id")
        eos_id = None if eos_id is None else int(eos_id)
    except (TypeError, ValueError) as e:
        _count_error("bad_request")
        return 400, _error_body(
            400, f"prompts must be lists of token ids: {e}")
    try:
        if gen_batcher is not None:
            futures = [gen_batcher.submit(
                p, max_new_tokens=max_new, temperature=temperature,
                eos_id=eos_id) for p in prompts]
            outs = [f.result() for f in futures]
        else:
            outs = model.generate(prompts, max_new_tokens=max_new,
                                  temperature=temperature,
                                  eos_id=eos_id)
        toks = [[int(t) for t in o] for o in outs]
        return 200, {"tokens": toks[0] if single else toks}
    except QueueFullError as e:
        return 503, _error_body(
            503, str(e), retry_after_s=round(e.retry_after_s, 3))
    except ValueError as e:  # prompt/budget outside the cache bounds
        _count_error("bad_request")
        return 400, _error_body(400, str(e))
    except Exception as e:  # serving boundary: report, not die
        _count_error("internal")
        return 500, _error_body(500, str(e), kind="internal")


def handle_prefill(model: InferenceModel, body: bytes,
                   gen_batcher=None) -> "Tuple[int, dict]":
    """``POST /generate/prefill`` — the disaggregated fleet's
    prefill-pool ingress (docs/serving.md §Disaggregation). Request:
    ``{"prompt": [ids...]}`` with optional ``max_new_tokens`` /
    ``temperature``. The prompt runs to its first sampled token,
    then the sequence's KV pages leave the cache as a handoff blob:
    response ``{"handoff": {...}}`` in the base64 wire form
    (`ops/kv_cache.handoff_to_wire`), ready to POST at a decode
    replica's ``/generate/handoff``. 501 unless this server's
    batcher fronts a prefill-capable engine."""
    sub = getattr(gen_batcher, "submit_prefill", None)
    if sub is None:
        _count_error("no_generator")
        return 501, _error_body(
            501, "this server has no prefill-capable generation "
            "batcher mounted (disaggregated prefill pool only)")
    try:
        req = json.loads(body)
    except (ValueError, UnicodeDecodeError) as e:
        _count_error("bad_json")
        return 400, _error_body(400, f"malformed JSON body: {e}")
    if not isinstance(req, dict) or "prompt" not in req:
        _count_error("bad_request")
        return 400, _error_body(
            400, 'request must be a JSON object with a "prompt" '
            'token-id list')
    try:
        prompt = [int(t) for t in req["prompt"]]
        max_new = int(req.get("max_new_tokens", 32))
        temperature = float(req.get("temperature", 0.0))
    except (TypeError, ValueError) as e:
        _count_error("bad_request")
        return 400, _error_body(
            400, f"prompt must be a list of token ids: {e}")
    from analytics_zoo_tpu.ops.kv_cache import handoff_to_wire
    try:
        blob = sub(prompt, max_new_tokens=max_new,
                   temperature=temperature).result()
        return 200, {"handoff": handoff_to_wire(blob)}
    except QueueFullError as e:
        return 503, _error_body(
            503, str(e), retry_after_s=round(e.retry_after_s, 3))
    except ValueError as e:
        _count_error("bad_request")
        return 400, _error_body(400, str(e))
    except Exception as e:  # serving boundary: report, not die
        _count_error("internal")
        return 500, _error_body(500, str(e), kind="internal")


def handle_handoff(model: InferenceModel, body: bytes,
                   gen_batcher=None) -> "Tuple[int, dict]":
    """``POST /generate/handoff`` — the disaggregated fleet's
    decode-pool ingress. Request: ``{"handoff": {...}}`` (wire form
    from a prefill replica) with optional ``max_new_tokens`` /
    ``eos_id``. The blob's pages splice into this replica's cache
    with no forward pass and the sequence resumes decoding; response
    ``{"tokens": [...]}`` is the FULL new-token stream including the
    prefill-sampled first token — byte-identical to what a
    monolithic ``/generate`` would have returned. 501 unless this
    server's batcher can admit handoffs."""
    sub = getattr(gen_batcher, "submit_handoff", None)
    if sub is None:
        _count_error("no_generator")
        return 501, _error_body(
            501, "this server has no handoff-capable generation "
            "batcher mounted (disaggregated decode pool only)")
    try:
        req = json.loads(body)
    except (ValueError, UnicodeDecodeError) as e:
        _count_error("bad_json")
        return 400, _error_body(400, f"malformed JSON body: {e}")
    if not isinstance(req, dict) or \
            not isinstance(req.get("handoff"), dict):
        _count_error("bad_request")
        return 400, _error_body(
            400, 'request must be a JSON object with a "handoff" '
            'wire blob (POST /generate/prefill produces one)')
    from analytics_zoo_tpu.ops.kv_cache import handoff_from_wire
    try:
        max_new = int(req.get("max_new_tokens", 32))
        eos_id = req.get("eos_id")
        eos_id = None if eos_id is None else int(eos_id)
        blob = handoff_from_wire(req["handoff"])
    except (TypeError, ValueError, KeyError) as e:
        _count_error("bad_request")
        return 400, _error_body(400, f"bad handoff blob: {e}")
    try:
        toks = sub(blob, max_new_tokens=max_new,
                   eos_id=eos_id).result()
        return 200, {"tokens": [int(t) for t in toks]}
    except QueueFullError as e:
        return 503, _error_body(
            503, str(e), retry_after_s=round(e.retry_after_s, 3))
    except ValueError as e:  # blob/engine geometry mismatch
        _count_error("bad_request")
        return 400, _error_body(400, str(e))
    except Exception as e:
        _count_error("internal")
        return 500, _error_body(500, str(e), kind="internal")


def _health_payload(model: InferenceModel,
                    batcher: "Optional[DynamicBatcher]",
                    gen_batcher=None) -> dict:
    """Shared /health body: model pool capacity plus the batcher's
    queue/bucket state (docs/serving.md), and — when a generator is
    mounted — the continuous batcher's slot/page occupancy."""
    payload = {
        "status": "ok",
        "free_slots": model.concurrent_slots_free,
        "batcher": (batcher.stats() if batcher is not None
                    else {"enabled": False}),
    }
    if gen_batcher is not None:
        payload["generator"] = gen_batcher.stats()
    elif getattr(model, "generator", None) is not None:
        payload["generator"] = dict(model.generator.stats(),
                                    enabled=False)
    return payload


def _fed_collector(batcher):
    """The FleetRouter's federation ``TelemetryCollector`` when this
    server fronts a started fleet (None otherwise — the attribute's
    presence is how these routes discover the telemetry plane)."""
    return getattr(batcher, "telemetry", None)


def _metrics_text() -> bytes:
    """Local-registry Prometheus text; refreshes the process vitals
    + build-info gauges first so every scrape carries current
    RSS/uptime/fd readings and provenance (docs/observability.md)."""
    diagnostics.update_process_vitals()
    diagnostics.update_build_info()
    return obs.to_prometheus().encode()


def _metrics_json_payload() -> dict:
    """``GET /metrics/json``: the registry snapshot the federation
    collector scrapes — same data as ``/metrics``, machine-mergeable
    (explicit ``application/json``)."""
    diagnostics.update_process_vitals()
    diagnostics.update_build_info()
    return {"ts": time.time(), "metrics": obs.snapshot()}


def _fleet_metrics_text(path: str, batcher
                        ) -> "Tuple[int, Optional[bytes]]":
    """``GET /metrics?fleet=1``: merged fleet-wide Prometheus text
    from the federation collector (HELP/TYPE deduplicated). Ticks
    the collector first by default so exact-sum assertions see this
    instant, not the last background scrape; ``tick=0`` reads
    passively. ``(404, None)`` when no collector is mounted."""
    from urllib.parse import parse_qs, urlsplit
    q = parse_qs(urlsplit(path).query)
    tele = _fed_collector(batcher)
    if tele is None:
        _count_error("not_found")
        return 404, None
    if q.get("tick", ["1"])[0] != "0":
        tele.tick()
    return 200, tele.fleet_prometheus().encode()


def _traces_payload(path: str, batcher=None) -> dict:
    """``GET /debug/traces[?n=20]``: the most recent traces from the
    in-process ring buffer, newest first. ``?since=<seq>`` switches
    to the federation collector's incremental scrape: the ring's
    cursor plus every span recorded after ``seq`` (cursor and spans
    read under one lock — zero loss, zero duplication). ``?fleet=1``
    on a fleet front door lists stitched traces from the
    aggregator."""
    from urllib.parse import parse_qs, urlsplit
    q = parse_qs(urlsplit(path).query)
    try:
        n = int(q.get("n", ["20"])[0])
    except ValueError:
        n = 20
    n = max(1, min(n, 200))
    if "since" in q:
        try:
            since = int(q["since"][0])
        except ValueError:
            since = 0
        seq, recs = tracing.get_store().records_since(since)
        return {"enabled": tracing.enabled(), "seq": seq,
                "spans": [r.to_dict() for r in recs]}
    tele = _fed_collector(batcher)
    if q.get("fleet", ["0"])[0] == "1" and tele is not None:
        return {"enabled": tracing.enabled(), "fleet": True,
                "traces": tele.aggregator.recent(n)}
    return {"enabled": tracing.enabled(),
            "traces": tracing.get_store().recent(n)}


def _stitched_trace_payload(route: str, path: str, batcher
                            ) -> "Tuple[int, dict]":
    """``GET /debug/trace/<id>[?chrome=1]``: ONE stitched timeline
    for a trace id — from the fleet aggregator when the federation
    plane is mounted (spans from every process, freshened by a
    synchronous collector tick), falling back to the local ring.
    ``chrome=1`` renders Perfetto-loadable JSON with a distinct
    process lane (pid) per source process."""
    from urllib.parse import parse_qs, urlsplit
    q = parse_qs(urlsplit(path).query)
    tid = route[len("/debug/trace/"):]
    chrome = q.get("chrome", ["0"])[0] == "1"
    tele = _fed_collector(batcher)
    if tele is not None:
        tele.tick()  # pull any spans still sitting in the sources
        agg = tele.aggregator
        if agg.spans(tid):
            return 200, (agg.chrome(tid) if chrome
                         else agg.trace(tid))
    recs = sorted((r for r in tracing.get_store().records()
                   if r.trace_id == tid),
                  key=lambda r: r.t_start)
    if not recs:
        _count_error("not_found")
        return 404, _error_body(404, f"unknown trace id {tid!r}")
    if chrome:
        return 200, {"traceEvents": tracing.chrome_events(
            [r.to_dict() for r in recs], source_lanes=True),
            "displayTimeUnit": "ms"}
    t0 = min(r.t_start for r in recs)
    t1 = max(r.t_start + r.dur_s for r in recs)
    return 200, {"trace_id": tid, "t_start": round(t0, 6),
                 "dur_s": round(t1 - t0, 6), "n_spans": len(recs),
                 "sources": ["router"],
                 "spans": [r.to_dict() for r in recs]}


def _fleet_telemetry_payload(batcher) -> "Tuple[int, dict]":
    """``GET /debug/fleet/telemetry``: the federation collector's
    own state — sources and scrape health, merge conflicts, the last
    per-replica window stats and skew verdicts. 404 when this server
    fronts no fleet telemetry plane."""
    tele = _fed_collector(batcher)
    if tele is None:
        _count_error("not_found")
        return 404, _error_body(
            404, "no fleet telemetry collector mounted")
    return 200, tele.status()


def _slo_payload(path: str) -> dict:
    """``GET /debug/slo[?tick=0]``: live objective status from the
    process-global SLO engine (docs/slo.md). Ticks the engine first
    by default so the report reflects this instant, not the last
    background tick; ``tick=0`` reads passively."""
    from urllib.parse import parse_qs, urlsplit
    q = parse_qs(urlsplit(path).query)
    engine = slo_lib.get_engine()
    if q.get("tick", ["1"])[0] != "0":
        return engine.tick()
    return engine.status()


def _history_payload(path: str, batcher=None
                     ) -> "Tuple[int, dict]":
    """``GET /debug/metrics/history[?family=&window=&fleet=1]``:
    windowed series from the in-process
    :class:`~analytics_zoo_tpu.common.timeseries.MetricHistory`.
    Without ``family``, lists known families + store stats. The
    local store takes a fresh sample first by default (so the
    response reflects this instant even with no background ticker;
    ``sample=0`` reads passively); ``fleet=1`` serves the federation
    collector's merged fleet timeline instead (``tick=1`` forces a
    synchronous collector tick first)."""
    from urllib.parse import parse_qs, urlsplit
    q = parse_qs(urlsplit(path).query)
    fleet = q.get("fleet", ["0"])[0] == "1"
    if fleet:
        tele = _fed_collector(batcher)
        if tele is None:
            _count_error("not_found")
            return 404, _error_body(
                404, "no fleet telemetry collector mounted")
        if q.get("tick", ["0"])[0] == "1":
            tele.tick()
        hist = tele.history
    else:
        hist = timeseries.get_history()
        if q.get("sample", ["1"])[0] != "0":
            hist.sample()
    window_s = None
    if q.get("window"):
        try:
            window_s = float(q["window"][0])
        except ValueError:
            _count_error("bad_request")
            return 400, _error_body(
                400, f"bad window {q['window'][0]!r} "
                "(seconds expected)")
        if window_s <= 0:
            _count_error("bad_request")
            return 400, _error_body(
                400, "window must be positive seconds")
    family = q.get("family", [None])[0]
    if not family:
        return 200, {"fleet": fleet,
                     "families": hist.families(),
                     "stats": hist.stats()}
    return 200, dict(hist.series(family, window_s=window_s),
                     fleet=fleet)


# The live dashboard: ONE self-contained HTML file, zero external
# assets (loads even when the fleet is on fire and a CDN is not an
# option). All series come from /debug/metrics/history; sparklines
# are inline SVG built client-side.
_DASHBOARD_PAGE = """<!doctype html>
<html><head><meta charset="utf-8">
<title>analytics-zoo-tpu dashboard</title>
<style>
body{font:13px/1.4 system-ui,sans-serif;margin:16px;
     background:#0b0e14;color:#d6deeb}
h1{font-size:16px;margin:0 0 2px}
#meta{color:#7a88a8;margin-bottom:12px}
#panels{display:grid;gap:10px;
        grid-template-columns:repeat(auto-fill,minmax(290px,1fr))}
.panel{background:#131824;border:1px solid #232b3d;
       border-radius:6px;padding:8px 10px}
.panel h2{font-size:12px;margin:0 0 4px;color:#9fb2d8;
          font-weight:600}
.row{display:flex;align-items:center;gap:8px;margin:2px 0}
.lbl{color:#7a88a8;font-size:11px;white-space:nowrap;
     overflow:hidden;text-overflow:ellipsis;max-width:45%}
.val{margin-left:auto;font-variant-numeric:tabular-nums}
.nodata{color:#53607c;font-style:italic}
svg{flex:1 1 auto;min-width:60px}
polyline{fill:none;stroke:#58a6ff;stroke-width:1.5}
.bad polyline{stroke:#ff7b72}
#slo .breach{color:#ff7b72}
#slo .ok{color:#3fb950}
#slo .no_data{color:#53607c}
</style></head><body>
<h1>analytics-zoo-tpu &mdash; live dashboard</h1>
<div id="meta">loading&hellip;</div>
<div id="panels"></div>
<div class="panel" id="slo" style="margin-top:10px">
<h2>SLO state &amp; recent anomalies</h2>
<div id="slobody" class="nodata">loading&hellip;</div></div>
<script>
"use strict";
var FLEET = new URLSearchParams(location.search)
    .get("fleet") === "1";
var SUFFIX = FLEET ? "&fleet=1" : "";
var PANELS = [
  {t: "QPS (requests/s)", f: "zoo_tpu_serving_requests_total",
   k: "rate"},
  {t: "p99 latency (s)", f: "zoo_tpu_serving_request_seconds",
   k: "q99"},
  {t: "queue depth", f: "zoo_tpu_serving_queue_depth",
   k: "value"},
  {t: "KV pages free", f: "zoo_tpu_serving_gen_free_pages",
   k: "value"},
  {t: "goodput share", f: "zoo_tpu_goodput_share", k: "value"},
  {t: "MFU", f: "zoo_tpu_mfu", k: "value"},
  {t: "forecast ETA (s)", f: "zoo_tpu_forecast_eta_s",
   k: "value", bad: function (v) { return v < 600; }},
  {t: "anomalies/s", f: "zoo_tpu_anomalies_total", k: "rate",
   bad: function (v) { return v > 0; }}
];
function esc(s) {
  return String(s).replace(/[&<>"]/g, function (c) {
    return {"&": "&amp;", "<": "&lt;", ">": "&gt;",
            '"': "&quot;"}[c];
  });
}
function spark(vals) {
  var w = 120, h = 26;
  if (vals.length < 2) {
    return '<svg width="' + w + '" height="' + h + '"></svg>';
  }
  var lo = Math.min.apply(null, vals);
  var hi = Math.max.apply(null, vals);
  var span = (hi - lo) || 1;
  var pts = vals.map(function (v, i) {
    var x = i * w / (vals.length - 1);
    var y = h - 2 - (v - lo) / span * (h - 4);
    return x.toFixed(1) + "," + y.toFixed(1);
  }).join(" ");
  return '<svg width="' + w + '" height="' + h +
    '" viewBox="0 0 ' + w + " " + h +
    '"><polyline points="' + pts + '"/></svg>';
}
function fmtv(v) {
  if (v === null || v === undefined) { return "-"; }
  if (v >= 1e8) { return "&#8734;"; }
  if (Math.abs(v) >= 100) { return v.toFixed(0); }
  return v.toPrecision(3);
}
function labelText(labels) {
  var ks = Object.keys(labels);
  if (!ks.length) { return "total"; }
  return ks.map(function (k) {
    return k + "=" + labels[k];
  }).join(",");
}
function renderPanel(p, doc) {
  var html = "<h2>" + esc(p.t) + "</h2>";
  var series = (doc && doc.series) || [];
  var rows = 0;
  series.forEach(function (s) {
    var vals = s.points.map(function (pt) {
      return pt[p.k];
    }).filter(function (v) {
      return v !== null && v !== undefined;
    });
    if (!vals.length) { return; }
    rows += 1;
    var last = vals[vals.length - 1];
    var bad = p.bad && p.bad(last);
    html += '<div class="row' + (bad ? " bad" : "") +
      '"><span class="lbl" title="' +
      esc(labelText(s.labels)) + '">' +
      esc(labelText(s.labels)) + "</span>" + spark(vals) +
      '<span class="val">' + fmtv(last) + "</span></div>";
  });
  if (!rows) {
    html += '<div class="nodata">no data</div>';
  }
  return html;
}
function refresh() {
  PANELS.forEach(function (p, i) {
    fetch("/debug/metrics/history?family=" + p.f + SUFFIX)
      .then(function (r) { return r.json(); })
      .then(function (doc) {
        document.getElementById("p" + i).innerHTML =
          renderPanel(p, doc);
      }).catch(function () {});
  });
  fetch("/debug/metrics/history?" + (FLEET ? "fleet=1" : ""))
    .then(function (r) { return r.json(); })
    .then(function (doc) {
      var st = doc.stats || {};
      document.getElementById("meta").textContent =
        (FLEET ? "fleet-merged timeline" : "local timeline") +
        " \\u00b7 " + (st.raw_samples || 0) + " samples over " +
        (st.span_s || 0).toFixed(0) + "s \\u00b7 " +
        ((st.resident_bytes || 0) / 1024).toFixed(0) +
        " KiB resident \\u00b7 " + new Date().toLocaleTimeString();
    }).catch(function () {});
  fetch("/debug/slo?tick=0")
    .then(function (r) { return r.json(); })
    .then(function (doc) {
      var html = "";
      (doc.objectives || []).forEach(function (o) {
        html += '<div class="row"><span class="lbl">' +
          esc(o.id) + '</span><span class="' + esc(o.state) +
          '">' + esc(o.state) + "</span>" +
          '<span class="val">' + fmtv(o.value) + "</span></div>";
      });
      document.getElementById("slobody").innerHTML =
        html || '<div class="nodata">no objectives</div>';
    }).catch(function () {});
}
var panels = document.getElementById("panels");
PANELS.forEach(function (p, i) {
  var d = document.createElement("div");
  d.className = "panel";
  d.id = "p" + i;
  d.innerHTML = "<h2>" + esc(p.t) +
    '</h2><div class="nodata">loading&hellip;</div>';
  panels.appendChild(d);
});
refresh();
setInterval(refresh, 5000);
</script></body></html>
"""


def _dashboard_html() -> bytes:
    """``GET /debug/dashboard``: the self-contained live dashboard
    page (same bytes on both front-ends)."""
    return _DASHBOARD_PAGE.encode()


# On-demand jax.profiler capture: one at a time per process (the XLA
# profiler is a process-global singleton).
_profile_lock = threading.Lock()
_profile_thread: "Optional[threading.Thread]" = None


def _fleet_payload(batcher, gen_batcher=None) -> "Tuple[int, dict]":
    """``GET /debug/fleet``: topology + per-replica lifecycle state
    (state machine, outstanding rows, failure counts, per-queue
    batcher stats) when a ``FleetRouter`` fronts this server — or,
    on a disaggregated generation front door, the
    :class:`DisaggRouter`'s role-tagged replicas and per-pool page
    headroom. Single-model servers 404 — the route's presence is how
    clients discover they are talking to a fleet."""
    status_fn = getattr(batcher, "fleet_status", None)
    if status_fn is None:
        status_fn = getattr(gen_batcher, "fleet_status", None)
    if status_fn is None:
        _count_error("not_found")
        return 404, _error_body(
            404, "no fleet router mounted on this server")
    return 200, status_fn()


def _rollout_payload(batcher) -> "Tuple[int, dict]":
    """``GET /debug/rollout``: the rollout state machine (rolling →
    canary → promoted | rolled_back), per-replica versions, swap
    log, and the active canary split — the observable surface of
    ``FleetRouter.rollout`` (docs/robustness.md). 404 on
    single-model servers, ``{"state": "idle"}`` on fleets that never
    rolled."""
    status_fn = getattr(batcher, "rollout_status", None)
    if status_fn is None:
        _count_error("not_found")
        return 404, _error_body(
            404, "no fleet router mounted on this server")
    return 200, status_fn()


def _profiler_capture(out_dir: str, ms: float):
    """Capture ``ms`` milliseconds of jax.profiler trace into
    ``out_dir`` (module-level so tests can stub it)."""
    import jax

    jax.profiler.start_trace(out_dir)
    try:
        time.sleep(ms / 1e3)
    finally:
        jax.profiler.stop_trace()


def handle_profile(body: bytes) -> "Tuple[int, dict]":
    """``POST /debug/profile {"dir": ..., "ms": 500}``: trigger an
    on-demand ``jax.profiler`` capture in a background thread (the
    train loop's ``StepTraceAnnotation`` step markers line up with
    our spans in the result). Returns immediately; 503 while a
    capture is already running."""
    global _profile_thread
    try:
        req = json.loads(body) if body else {}
    except (ValueError, UnicodeDecodeError) as e:
        _count_error("bad_json")
        return 400, _error_body(400, f"malformed JSON body: {e}")
    if not isinstance(req, dict) or not req.get("dir"):
        _count_error("bad_request")
        return 400, _error_body(
            400, 'request must be a JSON object with a "dir" key '
            '(profile output directory); optional "ms" duration')
    out_dir = str(req["dir"])
    try:
        ms = float(req.get("ms", 500))
    except (TypeError, ValueError):
        _count_error("bad_request")
        return 400, _error_body(400, '"ms" must be a number')
    ms = max(1.0, min(ms, 60_000.0))
    if not _profile_lock.acquire(blocking=False):
        _count_error("profile_busy")
        return 503, _error_body(
            503, "a profiler capture is already running")

    def _run():
        try:
            _profiler_capture(out_dir, ms)
            obs.event("serving/profile_capture", dir=out_dir, ms=ms)
        except Exception as e:
            obs.event("serving/profile_error", dir=out_dir,
                      error=f"{type(e).__name__}: {e}")
        finally:
            _profile_lock.release()

    t = threading.Thread(target=_run, name="zoo-tpu-profiler",
                         daemon=True)
    _profile_thread = t
    t.start()
    return 200, {"status": "capturing", "dir": out_dir, "ms": ms}


def _resolve_gen_batcher(model: InferenceModel, gen_batcher):
    """``"auto"`` → a :class:`ContinuousBatcher` over the model's
    loaded generator (None when no generator is loaded or
    ``ZOO_TPU_GEN_BATCH=0`` — /generate then runs the sequential
    per-request path); explicit ``None`` / instance pass through. A
    FleetRouter standing in for the model has no generator, so fleet
    front doors resolve to None and /generate degrades cleanly.

    ``ZOO_TPU_DISAGG=1`` swaps the ContinuousBatcher for a
    :class:`fleet.DisaggRouter` carved out of the loaded generator
    (pool sizes from ``ZOO_TPU_DISAGG_PREFILL_REPLICAS`` /
    ``ZOO_TPU_DISAGG_DECODE_REPLICAS``): /generate then runs the
    prefill→handoff→decode path transparently, same contract. Only a
    ``role="both"`` engine is split — pool workers (role-specific
    engines behind /generate/prefill + /generate/handoff) keep their
    plain batcher."""
    if gen_batcher == "auto":
        import os
        engine = getattr(model, "generator", None)
        if engine is None or \
                os.environ.get("ZOO_TPU_GEN_BATCH", "1") == "0":
            return None
        if os.environ.get("ZOO_TPU_DISAGG", "0") not in ("", "0") \
                and getattr(engine, "role", "both") == "both":
            from analytics_zoo_tpu.pipeline.inference.fleet import \
                DisaggRouter
            return DisaggRouter.for_engine(engine)
        from analytics_zoo_tpu.pipeline.inference.batching import \
            ContinuousBatcher
        return ContinuousBatcher(engine)
    return gen_batcher


def _resolve_batcher(model: InferenceModel, batcher):
    """``"auto"`` → env-configured batcher (None when
    ``ZOO_TPU_SERVING_BATCH=0``); explicit ``None`` → per-request
    serving; a DynamicBatcher instance passes through. A
    ``FleetRouter`` passed as the *model* is its own batcher (it
    duck-types both surfaces — `pipeline/inference/fleet.py`), so
    ``make_inference_server(router)`` just works."""
    if batcher == "auto":
        if hasattr(model, "fleet_status"):
            return model
        return DynamicBatcher.from_env(model)
    return batcher


class InferenceServer:
    def __init__(self, model: InferenceModel, host: str = "127.0.0.1",
                 port: int = 0, batcher="auto", gen_batcher="auto"):
        self.model = model
        self.batcher = _resolve_batcher(model, batcher)
        self.gen_batcher = _resolve_gen_batcher(model, gen_batcher)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, payload: dict,
                       headers: Optional[dict] = None):
                body = json.dumps(payload).encode()
                self._reply_raw(code, body, "application/json",
                                headers)

            def _reply_raw(self, code: int, body: bytes,
                           ctype: str,
                           headers: Optional[dict] = None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                if code == 503:
                    err = {}
                    try:
                        err = json.loads(body).get("error", {})
                    except ValueError:
                        pass
                    retry = err.get("retry_after_s")
                    if retry is not None:
                        import math
                        self.send_header(
                            "Retry-After",
                            str(max(1, math.ceil(retry))))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                t0 = time.perf_counter()
                _in_flight().inc()
                status = 0
                payload = None
                raw = None  # (body, ctype) short-circuits _reply
                route = self.path.split("?", 1)[0]
                try:
                    if route == "/health":
                        status = 200
                        payload = _health_payload(
                            server.model, server.batcher,
                            server.gen_batcher)
                    elif route == "/metrics" and \
                            "fleet=1" in self.path:
                        status, body = _fleet_metrics_text(
                            self.path, server.batcher)
                        if body is None:
                            payload = _error_body(
                                404, "no fleet telemetry "
                                "collector mounted")
                        else:
                            raw = (body,
                                   "text/plain; version=0.0.4")
                    elif route == "/metrics":
                        status = 200  # rendered after accounting
                    elif route == "/metrics/json":
                        status = 200
                        payload = _metrics_json_payload()
                    elif route == "/debug/traces":
                        status = 200
                        payload = _traces_payload(
                            self.path, server.batcher)
                    elif route.startswith("/debug/trace/"):
                        status, payload = _stitched_trace_payload(
                            route, self.path, server.batcher)
                    elif route == "/debug/slo":
                        status = 200
                        payload = _slo_payload(self.path)
                    elif route == "/debug/fleet/telemetry":
                        status, payload = _fleet_telemetry_payload(
                            server.batcher)
                    elif route == "/debug/fleet":
                        status, payload = _fleet_payload(
                            server.batcher, server.gen_batcher)
                    elif route == "/debug/rollout":
                        status, payload = _rollout_payload(
                            server.batcher)
                    elif route == "/debug/metrics/history":
                        status, payload = _history_payload(
                            self.path, server.batcher)
                    elif route == "/debug/dashboard":
                        status = 200
                        raw = (_dashboard_html(),
                               "text/html; charset=utf-8")
                    else:
                        status = 404
                        _count_error("not_found")
                        payload = _error_body(
                            404, "not found", path=route)
                finally:
                    # account BEFORE replying: a client that scrapes
                    # /metrics right after a response must see its own
                    # request already counted (and in-flight back at 0)
                    _in_flight().dec()
                    _record_request(self.path, status,
                                    time.perf_counter() - t0)
                if raw is None and payload is None:
                    # local /metrics renders AFTER accounting so the
                    # scrape sees itself counted
                    raw = (_metrics_text(),
                           "text/plain; version=0.0.4")
                if raw is not None:
                    self._reply_raw(status, raw[0], raw[1])
                else:
                    self._reply(status, payload)

            def do_POST(self):
                t0 = time.perf_counter()
                _in_flight().inc()
                status = 0
                trace_id = None
                route = self.path.split("?", 1)[0]
                try:
                    if route not in ("/predict", "/generate",
                                     "/generate/prefill",
                                     "/generate/handoff",
                                     "/debug/profile"):
                        status = 404
                        _count_error("not_found")
                        payload = _error_body(
                            404, "not found", path=route)
                    else:
                        try:
                            n = int(self.headers.get(
                                "Content-Length", 0))
                            body = self.rfile.read(n)
                        except Exception as e:  # client gone
                            status = 400
                            _count_error("bad_request")
                            payload = _error_body(400, str(e))
                        else:
                            if route == "/debug/profile":
                                status, payload = handle_profile(
                                    body)
                            else:
                                with tracing.trace(
                                        "serving/request",
                                        trace_id=self.headers.get(
                                            tracing.TRACE_HEADER),
                                        path=route) as tr:
                                    if route == \
                                            "/generate/prefill":
                                        status, payload = \
                                            handle_prefill(
                                                server.model, body,
                                                server.gen_batcher)
                                    elif route == \
                                            "/generate/handoff":
                                        status, payload = \
                                            handle_handoff(
                                                server.model, body,
                                                server.gen_batcher)
                                    elif route == "/generate":
                                        status, payload = \
                                            handle_generate(
                                                server.model, body,
                                                server.gen_batcher)
                                    else:
                                        status, payload = \
                                            handle_predict(
                                                server.model, body,
                                                batcher=server
                                                .batcher)
                                    tr.annotate(status=status)
                                trace_id = tr.trace_id
                finally:
                    _in_flight().dec()
                    _record_request(route, status,
                                    time.perf_counter() - t0)
                self._reply(
                    status, payload,
                    {tracing.TRACE_HEADER: trace_id}
                    if trace_id else None)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self, background: bool = True):
        # bucket warm-up happens HERE (AOT, before traffic): steady
        # state then serves any request-size mix with zero compiles
        if self.batcher is not None:
            self.batcher.start()
        if self.gen_batcher is not None:
            self.gen_batcher.start()
        # shipped serving objectives + background evaluation ticker
        # (docs/slo.md; ZOO_TPU_SLO=0 disables); a fleet front door
        # adds the fleet-level objectives on top. The SLO ticker
        # also feeds the shared MetricHistory, which the capacity
        # forecaster rides (docs/observability.md §Forecasting).
        slo_lib.ensure_default_slos("serving")
        slo_lib.ensure_default_slos("forecast")
        forecast_lib.ensure_forecaster()
        if hasattr(self.batcher, "fleet_status"):
            slo_lib.ensure_default_slos("fleet")
            if _fed_collector(self.batcher) is not None:
                slo_lib.ensure_default_slos("fed")
        if background:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self

    def stop(self):
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        if self.batcher is not None:
            self.batcher.stop()
        if self.gen_batcher is not None:
            self.gen_batcher.stop()


class NativeInferenceServer:
    """Same /predict contract as :class:`InferenceServer`, fronted by
    the C++ HTTP server (`native/src/serving_http.cpp`): socket accept,
    HTTP parsing, request queueing, and /health all run native (no GIL
    contention with the XLA dispatch thread) — the role the reference's
    JVM/Spring + JNI serving stack played (SURVEY §2.8/§2.11.2).

    Worker threads (= model concurrency) pull raw request bytes over
    the C ABI, run `InferenceModel.predict`, and post response bytes
    back. ``GET /metrics`` routes through the worker (Python owns the
    registry); /health stays native.
    """

    def __init__(self, model: InferenceModel, port: int = 0,
                 workers: Optional[int] = None, batcher="auto",
                 gen_batcher="auto"):
        from analytics_zoo_tpu.native import NativeHttpServer
        self.model = model
        self.batcher = _resolve_batcher(model, batcher)
        self.gen_batcher = _resolve_gen_batcher(model, gen_batcher)
        self._srv = NativeHttpServer(port=port)
        self._workers = workers or model.supported_concurrent_num
        self._threads: "list[threading.Thread]" = []
        self._stopping = False

    @property
    def port(self) -> int:
        return self._srv.port

    def _serve_one(self, rid: int, path: str, body: bytes,
                   trace_hdr: "Optional[str]" = None):
        t0 = time.perf_counter()
        _in_flight().inc()
        status = 0
        out = b""
        trace_id = None
        route = path.split("?", 1)[0]
        try:
            if route == "/metrics" and "fleet=1" in path:
                status, body = _fleet_metrics_text(
                    path, self.batcher)
                out = body if body is not None else json.dumps(
                    _error_body(404, "no fleet telemetry "
                                "collector mounted")).encode()
            elif route == "/metrics":
                status = 200
                out = None  # rendered after accounting, below
            elif route == "/metrics/json":
                status = 200
                out = json.dumps(_metrics_json_payload()).encode()
            elif route == "/debug/traces":
                status = 200
                out = json.dumps(_traces_payload(
                    path, self.batcher)).encode()
            elif route.startswith("/debug/trace/"):
                status, payload = _stitched_trace_payload(
                    route, path, self.batcher)
                out = json.dumps(payload).encode()
            elif route == "/debug/slo":
                status = 200
                out = json.dumps(_slo_payload(path)).encode()
            elif route == "/debug/fleet/telemetry":
                status, payload = _fleet_telemetry_payload(
                    self.batcher)
                out = json.dumps(payload).encode()
            elif route == "/debug/fleet":
                status, payload = _fleet_payload(self.batcher,
                                                 self.gen_batcher)
                out = json.dumps(payload).encode()
            elif route == "/debug/rollout":
                status, payload = _rollout_payload(self.batcher)
                out = json.dumps(payload).encode()
            elif route == "/debug/metrics/history":
                status, payload = _history_payload(
                    path, self.batcher)
                out = json.dumps(payload).encode()
            elif route == "/debug/dashboard":
                status = 200
                out = _dashboard_html()
            elif route == "/debug/profile":
                status, payload = handle_profile(body)
                out = json.dumps(payload).encode()
            elif route not in ("/predict", "/generate",
                               "/generate/prefill",
                               "/generate/handoff"):
                status = 404
                _count_error("not_found")
                out = json.dumps(
                    _error_body(404, "not found",
                                path=route)).encode()
            else:
                with tracing.trace("serving/request",
                                   trace_id=trace_hdr,
                                   path=route) as tr:
                    if route == "/generate/prefill":
                        status, payload = handle_prefill(
                            self.model, body, self.gen_batcher)
                    elif route == "/generate/handoff":
                        status, payload = handle_handoff(
                            self.model, body, self.gen_batcher)
                    elif route == "/generate":
                        status, payload = handle_generate(
                            self.model, body, self.gen_batcher)
                    else:
                        status, payload = handle_predict(
                            self.model, body, batcher=self.batcher)
                    tr.annotate(status=status)
                trace_id = tr.trace_id
                out = json.dumps(payload).encode()
        except Exception as e:
            status = 500
            out = json.dumps(_error_body(
                500, str(e), kind="internal")).encode()
        finally:
            # account BEFORE responding: a client that scrapes
            # /metrics right after its response must see this request
            # already counted (and in-flight back at 0)
            _in_flight().dec()
            _record_request(route, status, time.perf_counter() - t0)
        if out is None:
            out = _metrics_text()
        try:
            self._srv.respond(rid, status, out, trace_id=trace_id)
        except Exception:
            pass  # client gone — nothing to tell it
        # refresh the C++-cached health AFTER the slot freed, so
        # /health reflects post-request capacity (and current
        # batcher queue state; the native front-end cannot set a
        # Retry-After header, so 503 bodies carry retry_after_s)
        self._srv.set_health(json.dumps(
            _health_payload(self.model, self.batcher,
                            self.gen_batcher)))

    def _loop(self):
        from analytics_zoo_tpu.common.nncontext import logger
        while not self._stopping:
            try:
                got = self._srv.next_request(timeout_ms=200)
            except StopIteration:
                return
            except Exception as e:  # transient — keep the worker alive
                if self._stopping:
                    return
                logger.warning("native serving worker error: %s", e)
                continue
            if got is None:
                continue
            self._serve_one(*got)

    def start(self, background: bool = True):
        if self.batcher is not None:
            self.batcher.start()
        if self.gen_batcher is not None:
            self.gen_batcher.start()
        slo_lib.ensure_default_slos("serving")
        slo_lib.ensure_default_slos("forecast")
        forecast_lib.ensure_forecaster()
        if hasattr(self.batcher, "fleet_status"):
            slo_lib.ensure_default_slos("fleet")
            if _fed_collector(self.batcher) is not None:
                slo_lib.ensure_default_slos("fed")
        self._srv.set_health(json.dumps(
            _health_payload(self.model, self.batcher,
                            self.gen_batcher)))
        for _ in range(self._workers):
            t = threading.Thread(target=self._loop, daemon=True)
            t.start()
            self._threads.append(t)
        if not background:
            for t in self._threads:
                t.join()
        return self

    def stop(self):
        # workers drain first (they poll with a 200ms timeout; an
        # in-flight predict finishes), THEN the native handle is
        # destroyed — never while a thread may be inside zoo_http_*.
        # If a worker is wedged (hung predict), leak the native handle
        # instead of freeing under it or hanging the caller forever.
        self._stopping = True
        deadline = time.monotonic() + 60.0
        for t in self._threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))
        if self.batcher is not None:
            self.batcher.stop()
        if self.gen_batcher is not None:
            self.gen_batcher.stop()
        if any(t.is_alive() for t in self._threads):
            from analytics_zoo_tpu.common.nncontext import logger
            logger.warning(
                "native serving: a worker is still busy after 60s; "
                "leaking the native server handle instead of freeing "
                "it underneath the worker")
            return
        self._srv.close()


def make_inference_server(model: InferenceModel, port: int = 0,
                          prefer_native: bool = True,
                          batcher="auto", gen_batcher="auto"):
    """Native C++ front-end when the toolchain built it, else the
    stdlib ThreadingHTTPServer — same endpoints either way.
    ``batcher``: ``"auto"`` (env-configured dynamic batching),
    ``None`` (per-request), or a :class:`DynamicBatcher`.
    ``gen_batcher``: same trio for /generate — ``"auto"`` mounts a
    :class:`ContinuousBatcher` iff the model has a generator loaded
    (and ``ZOO_TPU_GEN_BATCH`` != 0)."""
    if prefer_native:
        try:
            return NativeInferenceServer(model, port=port,
                                         batcher=batcher,
                                         gen_batcher=gen_batcher)
        except (RuntimeError, OSError):
            pass
    return InferenceServer(model, port=port, batcher=batcher,
                           gen_batcher=gen_batcher)
