"""HTTP serving facade over InferenceModel.

Plays the role of the reference's plain-Java `AbstractInferenceModel`
POJO + Spring-boot web-service samples (reference
`java/.../inference/AbstractInferenceModel.java:25-103`,
`apps/web-service-sample/`): a language-agnostic boundary for web
services, here a stdlib HTTP/JSON endpoint (no framework deps).

POST /predict  {"inputs": [[...], ...]}  →  {"outputs": [[...], ...]}
GET  /health   →  {"status": "ok", "free_slots": N}
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from analytics_zoo_tpu.pipeline.inference.inference_model import (
    InferenceModel)


class InferenceServer:
    def __init__(self, model: InferenceModel, host: str = "127.0.0.1",
                 port: int = 0):
        self.model = model
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._reply(200, {
                        "status": "ok",
                        "free_slots":
                            server.model.concurrent_slots_free})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    inputs = req["inputs"]
                    if isinstance(inputs, list) and inputs and \
                            isinstance(inputs[0], dict):
                        xs = [np.asarray(i["data"], np.float32)
                              for i in inputs]
                    else:
                        xs = np.asarray(inputs, np.float32)
                    out = server.model.predict(xs)
                    if isinstance(out, list):
                        payload = {"outputs": [o.tolist() for o in out]}
                    else:
                        payload = {"outputs": out.tolist()}
                    self._reply(200, payload)
                except Exception as e:  # serving boundary: report, not die
                    self._reply(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self, background: bool = True):
        if background:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self

    def stop(self):
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
