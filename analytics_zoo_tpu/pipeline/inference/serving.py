"""HTTP serving facade over InferenceModel.

Plays the role of the reference's plain-Java `AbstractInferenceModel`
POJO + Spring-boot web-service samples (reference
`java/.../inference/AbstractInferenceModel.java:25-103`,
`apps/web-service-sample/`): a language-agnostic boundary for web
services, here a stdlib HTTP/JSON endpoint (no framework deps).

POST /predict  {"inputs": [[...], ...]}  →  {"outputs": [[...], ...]}
GET  /health   →  {"status": "ok", "free_slots": N}
"""

from __future__ import annotations

import json
import time
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from analytics_zoo_tpu.pipeline.inference.inference_model import (
    InferenceModel)


def handle_predict(model: InferenceModel, body: bytes):
    """The /predict contract, shared by the stdlib and native
    front-ends: JSON body → (http_status, payload_dict)."""
    try:
        req = json.loads(body)
        inputs = req["inputs"]
        if isinstance(inputs, list) and inputs and \
                isinstance(inputs[0], dict):
            xs = [np.asarray(i["data"], np.float32) for i in inputs]
        else:
            xs = np.asarray(inputs, np.float32)
        out = model.predict(xs)
        if isinstance(out, list):
            return 200, {"outputs": [o.tolist() for o in out]}
        return 200, {"outputs": out.tolist()}
    except Exception as e:  # serving boundary: report, not die
        return 400, {"error": str(e)}


class InferenceServer:
    def __init__(self, model: InferenceModel, host: str = "127.0.0.1",
                 port: int = 0):
        self.model = model
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._reply(200, {
                        "status": "ok",
                        "free_slots":
                            server.model.concurrent_slots_free})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                except Exception as e:  # bad header / client dropped
                    self._reply(400, {"error": str(e)})
                    return
                status, payload = handle_predict(server.model, body)
                self._reply(status, payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self, background: bool = True):
        if background:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self

    def stop(self):
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


class NativeInferenceServer:
    """Same /predict contract as :class:`InferenceServer`, fronted by
    the C++ HTTP server (`native/src/serving_http.cpp`): socket accept,
    HTTP parsing, request queueing, and /health all run native (no GIL
    contention with the XLA dispatch thread) — the role the reference's
    JVM/Spring + JNI serving stack played (SURVEY §2.8/§2.11.2).

    Worker threads (= model concurrency) pull raw request bytes over
    the C ABI, run `InferenceModel.predict`, and post response bytes
    back.
    """

    def __init__(self, model: InferenceModel, port: int = 0,
                 workers: Optional[int] = None):
        from analytics_zoo_tpu.native import NativeHttpServer
        self.model = model
        self._srv = NativeHttpServer(port=port)
        self._workers = workers or model.supported_concurrent_num
        self._threads: "list[threading.Thread]" = []
        self._stopping = False

    @property
    def port(self) -> int:
        return self._srv.port

    def _serve_one(self, rid: int, path: str, body: bytes):
        try:
            if path != "/predict":
                self._srv.respond(rid, 404,
                                  b'{"error": "not found"}')
                return
            status, payload = handle_predict(self.model, body)
            self._srv.respond(rid, status,
                              json.dumps(payload).encode())
        except Exception as e:  # respond() itself failed
            try:
                self._srv.respond(
                    rid, 400, json.dumps({"error": str(e)}).encode())
            except Exception:
                pass
        finally:
            # refresh the C++-cached health AFTER the slot freed, so
            # /health reflects post-request capacity
            self._srv.set_health(json.dumps({
                "status": "ok",
                "free_slots": self.model.concurrent_slots_free}))

    def _loop(self):
        from analytics_zoo_tpu.common.nncontext import logger
        while not self._stopping:
            try:
                got = self._srv.next_request(timeout_ms=200)
            except StopIteration:
                return
            except Exception as e:  # transient — keep the worker alive
                if self._stopping:
                    return
                logger.warning("native serving worker error: %s", e)
                continue
            if got is None:
                continue
            self._serve_one(*got)

    def start(self, background: bool = True):
        self._srv.set_health(json.dumps({
            "status": "ok",
            "free_slots": self.model.concurrent_slots_free}))
        for _ in range(self._workers):
            t = threading.Thread(target=self._loop, daemon=True)
            t.start()
            self._threads.append(t)
        if not background:
            for t in self._threads:
                t.join()
        return self

    def stop(self):
        # workers drain first (they poll with a 200ms timeout; an
        # in-flight predict finishes), THEN the native handle is
        # destroyed — never while a thread may be inside zoo_http_*.
        # If a worker is wedged (hung predict), leak the native handle
        # instead of freeing under it or hanging the caller forever.
        self._stopping = True
        deadline = time.monotonic() + 60.0
        for t in self._threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))
        if any(t.is_alive() for t in self._threads):
            from analytics_zoo_tpu.common.nncontext import logger
            logger.warning(
                "native serving: a worker is still busy after 60s; "
                "leaking the native server handle instead of freeing "
                "it underneath the worker")
            return
        self._srv.close()


def make_inference_server(model: InferenceModel, port: int = 0,
                          prefer_native: bool = True):
    """Native C++ front-end when the toolchain built it, else the
    stdlib ThreadingHTTPServer — same endpoints either way."""
    if prefer_native:
        try:
            return NativeInferenceServer(model, port=port)
        except (RuntimeError, OSError):
            pass
    return InferenceServer(model, port=port)
