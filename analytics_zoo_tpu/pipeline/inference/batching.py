"""Dynamic request batching for the serving layer (L9).

The reference platform's serving story is per-request: one POST, one
forward (`AbstractInferenceModel.java:25-103`, the web-service
samples). On TPU that shape is pathological twice over — the MXU is
utilization-starved at batch 1, and every distinct request batch size
is a distinct XLA program, so a production mix of request sizes
recompiles forever. This module supplies the two levers Clipper
(NSDI'17) and ORCA (OSDI'22) establish for the problem:

- **shape-bucketed coalescing** — requests land in a bounded queue; a
  dispatcher thread drains up to ``max_batch_size`` rows or until
  ``max_wait_ms`` expires, pads the coalesced batch up to the next
  size in a bucket ladder (powers of two by default), runs ONE
  compiled call per bucket shape, and scatters the un-padded result
  rows back to per-request futures;
- **admission discipline** — a full queue rejects immediately
  (:class:`QueueFullError` → HTTP 503 + ``Retry-After``), bounding
  queue latency instead of letting it grow without limit, and
  per-request deadlines evict expired entries before dispatch
  (:class:`DeadlineExpiredError` → HTTP 504).

Every bucket is AOT-lowered-and-compiled up front (server start when
the model declared ``example_inputs``; first sight of a signature
otherwise), so steady-state serving performs **zero** compilations
regardless of the request-size mix.

Configuration: constructor kwargs override the environment —
``ZOO_TPU_SERVING_BATCH`` (``0`` disables, reverting to the
per-request path), ``ZOO_TPU_SERVING_MAX_BATCH``,
``ZOO_TPU_SERVING_MAX_WAIT_MS``, ``ZOO_TPU_SERVING_QUEUE_DEPTH``,
``ZOO_TPU_SERVING_DEADLINE_MS``, ``ZOO_TPU_SERVING_BUCKETS``
(comma-separated ladder override). See docs/serving.md for the
request lifecycle and the tuning guide, docs/perf_flags.md for the
flag catalog.

Correctness contract: the served forward must be row-wise in eval
mode (row *i* of the output depends only on row *i* of the inputs) —
true of every model the zoo serves (inference runs with
``training=False``, so BatchNorm uses moving statistics). Padding
rows are zeros and are sliced off before scatter.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.common import diagnostics
from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common import tracing
from analytics_zoo_tpu.common.nncontext import logger

__all__ = [
    "DynamicBatcher",
    "ContinuousBatcher",
    "QueueFullError",
    "DeadlineExpiredError",
    "bucket_ladder",
]

# fill-ratio histogram buckets: rows / bucket capacity in (0, 1]
_FILL_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

# chaos hook: armed via ZOO_TPU_FAULTS or tests (docs/robustness.md);
# fires at the head of every batch dispatch, inside the dispatcher
# thread — the spot a pad/scatter bug would surface
_DISPATCH_FAULT = faults.point("batcher/dispatch")


def _fail_entry(entry, exc):
    """Fail one entry's future without ever raising back into the
    dispatcher: a future a client already cancelled (or that a prior
    pass resolved) refuses ``set_exception``, and that must not take
    the serving thread down with it."""
    try:
        if not entry.future.done():
            entry.future.set_exception(exc)
    except Exception:  # cancelled/resolved between check and set
        pass


class QueueFullError(Exception):
    """Admission rejected: the batcher queue is at capacity. Carries
    ``retry_after_s``, an estimate of when capacity frees up (served
    to clients as HTTP 503 + ``Retry-After``)."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"serving queue full ({depth} requests waiting); "
            f"retry in ~{retry_after_s:.2f}s")
        self.retry_after_s = retry_after_s


class DeadlineExpiredError(Exception):
    """The request's deadline elapsed while it waited in the queue
    (served to clients as HTTP 504)."""


def bucket_ladder(max_batch: int,
                  override: Optional[Sequence[int]] = None
                  ) -> "Tuple[int, ...]":
    """The batch sizes the batcher compiles and pads to: powers of
    two up to ``max_batch`` (with ``max_batch`` itself appended when
    it is not a power of two), or a validated copy of ``override``."""
    if override is not None:
        ladder = sorted({int(b) for b in override})
        if not ladder or ladder[0] < 1:
            raise ValueError(f"invalid bucket ladder: {override!r}")
        return tuple(ladder)
    ladder = []
    b = 1
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return tuple(ladder)


class _Entry:
    """One queued request: input arrays, row count, completion
    future, the two clocks (enqueue time, absolute deadline), and —
    when the submitting thread had an open trace — its captured
    trace context, so the dispatcher can credit queue-wait / execute
    / scatter back to the request's trace."""

    __slots__ = ("xs", "n", "sig", "future", "t_enq", "deadline",
                 "trace", "t_enq_wall")

    def __init__(self, xs, n, sig, deadline):
        self.xs = xs
        self.n = n
        self.sig = sig
        self.future: "Future" = Future()
        self.t_enq = time.monotonic()
        self.deadline = deadline  # absolute monotonic, or None
        self.trace = tracing.current()  # None when untraced
        self.t_enq_wall = time.time() if self.trace else 0.0


def _signature(xs) -> tuple:
    """Coalescing key: per-input (row shape, dtype). Requests only
    merge when every input position agrees on both."""
    return tuple((tuple(x.shape[1:]), str(x.dtype)) for x in xs)


class DynamicBatcher:
    """Cross-request micro-batching between the HTTP front-ends and
    :class:`InferenceModel` (module docstring has the design).

    Thread model: any number of handler threads call :meth:`submit`;
    ONE dispatcher thread drains, pads, executes, and scatters — so
    device execution is serialized by construction and the model's
    slot pool is not consumed by the batched path.
    """

    def __init__(self, model, *,
                 max_batch_size: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 labels: Optional[dict] = None):
        env = os.environ
        if max_batch_size is None:
            max_batch_size = int(env.get(
                "ZOO_TPU_SERVING_MAX_BATCH", 32))
        if max_wait_ms is None:
            max_wait_ms = float(env.get(
                "ZOO_TPU_SERVING_MAX_WAIT_MS", 5))
        if queue_depth is None:
            queue_depth = int(env.get(
                "ZOO_TPU_SERVING_QUEUE_DEPTH", 256))
        if deadline_ms is None:
            deadline_ms = float(env.get(
                "ZOO_TPU_SERVING_DEADLINE_MS", 0))
        if buckets is None and env.get("ZOO_TPU_SERVING_BUCKETS"):
            buckets = [int(b) for b in
                       env["ZOO_TPU_SERVING_BUCKETS"].split(",")]
        self.model = model
        self.buckets = bucket_ladder(int(max_batch_size), buckets)
        self.max_batch = self.buckets[-1]
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.queue_depth = int(queue_depth)
        self.deadline_s = (float(deadline_ms) / 1e3
                           if deadline_ms else None)

        self._q: "deque[_Entry]" = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # (signature, bucket) -> compiled executable; invalidated
        # when the model swaps generations (reload)
        self._compiled: dict = {}
        self._unlowerable: set = set()  # sigs that failed to warm
        self._compile_lock = threading.Lock()
        self._model_gen = getattr(model, "generation", 0)
        # optional metric labels (the serving fleet tags each
        # replica's batcher with {"replica": name} so the shared
        # gauge families stay per-queue; label-free children keep
        # the exact pre-fleet exposition)
        self._labels = dict(labels) if labels else None
        self._ema_batch_s = 0.01  # retry-after estimator seed
        # touch the gauges so /metrics carries them from the start
        self._depth_gauge().set(0)
        self._warmed_gauge().set(0)

    # -- factory ------------------------------------------------------------
    @classmethod
    def from_env(cls, model) -> "Optional[DynamicBatcher]":
        """The servers' default construction path: a batcher with
        env-derived settings, or ``None`` when
        ``ZOO_TPU_SERVING_BATCH=0`` reverts to per-request serving."""
        if os.environ.get("ZOO_TPU_SERVING_BATCH", "1") == "0":
            return None
        return cls(model)

    # -- metrics handles ----------------------------------------------------
    def _depth_gauge(self):
        return obs.gauge("zoo_tpu_serving_queue_depth",
                         help="requests waiting in the batcher queue",
                         labels=self._labels)

    def _warmed_gauge(self):
        return obs.gauge("zoo_tpu_serving_warmed_buckets",
                         help="bucket executables compiled and ready",
                         labels=self._labels)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "DynamicBatcher":
        """Warm every bucket (when the model declared example inputs)
        and start the dispatcher thread. Idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return self
        diagnostics.install_recompile_monitor()
        # re-touch the gauges at start: the serving_queue_depth SLO
        # (docs/slo.md) must see the family before the first request,
        # even if the registry was reset since construction
        with self._cond:
            self._depth_gauge().set(len(self._q))
        self._warmed_gauge().set(self.warmed_buckets)
        self.warm()
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="zoo-tpu-batcher", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0):
        """Drain the queue (pending entries execute or expire), then
        stop the dispatcher."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def warm(self) -> int:
        """AOT-lower-and-compile the whole bucket ladder for the
        model's declared example-input signature (the `_install`
        example-inputs path). Returns the number of warmed buckets;
        0 when the signature is unknown (warming then happens on
        first sight of each request signature) or the model cannot
        re-lower (a `load_compiled` serialized executable)."""
        specs = getattr(self.model, "example_input_specs", None)
        if not specs or not getattr(self.model, "can_relower", False):
            return 0
        sig = tuple((tuple(shape[1:]), str(np.dtype(dt)))
                    for shape, dt in specs)
        try:
            return self._warm_signature(sig)
        except Exception as e:
            with self._compile_lock:
                self._unlowerable.add(sig)
            logger.warning(
                "bucket warm failed at start for declared signature "
                "%s (%s: %s); serving it unpadded", sig,
                type(e).__name__, e)
            return 0

    # -- admission ----------------------------------------------------------
    def batchable(self, xs: Sequence[np.ndarray]) -> bool:
        """Whether these inputs can ride the coalescing path: every
        input has a leading (row) dimension and all agree on it."""
        if not xs:
            return False
        if any(x.ndim < 1 for x in xs):
            return False
        n = xs[0].shape[0]
        return n >= 1 and all(x.shape[0] == n for x in xs)

    def submit(self, xs: Sequence[np.ndarray]) -> "Future":
        """Enqueue one request (a list of row-aligned input arrays).
        Returns a future resolving to exactly what
        ``model.predict`` would return for these inputs (one array,
        or a list for multi-output models). Raises
        :class:`QueueFullError` when the queue is at capacity."""
        xs = [np.asarray(x) for x in xs]
        if not self.batchable(xs):
            raise ValueError(
                "inputs are not row-aligned (every input needs the "
                "same leading dimension >= 1)")
        n = xs[0].shape[0]
        deadline = (time.monotonic() + self.deadline_s
                    if self.deadline_s else None)
        entry = _Entry(xs, n, _signature(xs), deadline)
        with self._cond:
            if len(self._q) >= self.queue_depth:
                # ~time for the backlog to drain at current exec rate
                retry = max(
                    0.05, len(self._q) * self._ema_batch_s
                    * max(1.0, n / self.max_batch))
                obs.counter("zoo_tpu_serving_errors_total",
                            help="serving errors by kind",
                            labels={"kind": "queue_full"}).inc()
                raise QueueFullError(len(self._q), retry)
            self._q.append(entry)
            self._depth_gauge().set(len(self._q))
            self._cond.notify_all()
        return entry.future

    # -- dispatcher ---------------------------------------------------------
    def _evict_expired_locked(self):
        if self.deadline_s is None or not self._q:
            return
        now = time.monotonic()
        kept = deque()
        for e in self._q:
            if e.deadline is not None and e.deadline < now:
                obs.counter("zoo_tpu_serving_errors_total",
                            help="serving errors by kind",
                            labels={"kind": "deadline_expired"}).inc()
                _fail_entry(e, DeadlineExpiredError(
                    f"request waited past its "
                    f"{self.deadline_s * 1e3:.0f}ms deadline"))
            else:
                kept.append(e)
        if len(kept) != len(self._q):
            self._q = kept
            self._depth_gauge().set(len(self._q))

    def _ready_rows_locked(self) -> int:
        """Row count of the maximal coalescible prefix (same
        signature as the head, cumulative rows <= max_batch)."""
        rows = 0
        sig = self._q[0].sig
        for e in self._q:
            if e.sig != sig or (rows and rows + e.n > self.max_batch):
                break
            rows += e.n
        return rows

    def _take_batch_locked(self) -> "list[_Entry]":
        batch: "list[_Entry]" = []
        rows = 0
        while self._q:
            e = self._q[0]
            if batch and (e.sig != batch[0].sig
                          or rows + e.n > self.max_batch):
                break
            batch.append(self._q.popleft())
            rows += e.n
            if rows >= self.max_batch:
                break
        self._depth_gauge().set(len(self._q))
        return batch

    def _run(self):
        # Hardening contract (docs/robustness.md): NOTHING that goes
        # wrong while handling one batch — pad, scatter, an injected
        # fault, even a bug in the queue bookkeeping itself — may
        # escape this loop. An escape would kill the one dispatcher
        # thread and wedge the queue forever: every later submit
        # would enqueue, never dispatch, and time out. Each iteration
        # therefore fails at most its own batch and keeps serving.
        while True:
            batch: "list[_Entry]" = []
            try:
                with self._cond:
                    while not self._q and not self._stop:
                        self._cond.wait(timeout=0.1)
                    if not self._q:
                        if self._stop:
                            return
                        continue
                    self._evict_expired_locked()
                    if not self._q:
                        continue
                    # coalescing window anchored at the head's
                    # arrival: the oldest request never waits past
                    # max_wait_ms
                    wait_until = self._q[0].t_enq + self.max_wait_s
                    while (not self._stop
                           and self._ready_rows_locked()
                           < self.max_batch):
                        remaining = wait_until - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=min(remaining, 0.05))
                        self._evict_expired_locked()
                        if not self._q:
                            break
                    if not self._q:
                        continue
                    batch = self._take_batch_locked()
                if batch:
                    self._execute(batch)
            except Exception as e:
                for entry in batch:
                    _fail_entry(entry, e)
                obs.counter("zoo_tpu_serving_errors_total",
                            help="serving errors by kind",
                            labels={"kind": "dispatch_error"}).inc()
                logger.warning("batcher dispatch error (%s: %s); "
                               "dispatcher continues",
                               type(e).__name__, e)

    # -- execution ----------------------------------------------------------
    def _execute(self, batch: "list[_Entry]"):
        _DISPATCH_FAULT.fire(rows=sum(e.n for e in batch))
        now = time.monotonic()
        wait_h = obs.histogram(
            "zoo_tpu_serving_queue_wait_seconds",
            help="time requests spent queued before dispatch")
        rows = sum(e.n for e in batch)
        for e in batch:
            wait_h.observe(now - e.t_enq)
            # credit the queue wait back to each request's trace
            tracing.record_span(
                e.trace, "serving/queue_wait", e.t_enq_wall,
                now - e.t_enq, rows=e.n, batch_rows=rows,
                n_requests=len(batch))
        sig = batch[0].sig
        n_inputs = len(batch[0].xs)
        if len(batch) == 1:
            xs = batch[0].xs
        else:
            xs = [np.concatenate([e.xs[i] for e in batch])
                  for i in range(n_inputs)]
        t0 = time.monotonic()
        t0_wall = time.time()
        try:
            # the first entry's trace becomes ambient, so the pad /
            # predict spans inside _pad_and_run join it as children
            with tracing.activate(batch[0].trace):
                outs, multi = self._run_rows(sig, xs, rows)
        except Exception as e:
            for entry in batch:
                _fail_entry(entry, e)
            return
        exec_s = time.monotonic() - t0
        # coalesced requests beyond the first get an explicit execute
        # span (their trace was not the ambient one during the call)
        for e in batch[1:]:
            tracing.record_span(
                e.trace, "serving/execute", t0_wall, exec_s,
                rows=e.n, batch_rows=rows, n_requests=len(batch))
        self._ema_batch_s = (0.8 * self._ema_batch_s + 0.2 * exec_s)
        off = 0
        t_sc = time.monotonic()
        t_sc_wall = time.time()
        for entry in batch:
            rows_out = [o[off:off + entry.n] for o in outs]
            try:
                if not entry.future.done():
                    entry.future.set_result(
                        rows_out if multi else rows_out[0])
            except Exception:  # cancelled under us: drop the rows,
                pass           # the batchmates still get theirs
            off += entry.n
        scatter_s = time.monotonic() - t_sc
        for e in batch:
            tracing.record_span(
                e.trace, "serving/scatter", t_sc_wall, scatter_s,
                rows=e.n, n_requests=len(batch))

    def _run_rows(self, sig, xs, rows):
        """Execute ``rows`` coalesced rows, chunking when a single
        oversized request exceeds ``max_batch``. Returns ``(outs,
        multi)``: row-aligned output arrays (one per model output)
        and whether the model returned a list (so scatter can
        preserve the per-request output structure)."""
        if rows <= self.max_batch:
            return self._pad_and_run(sig, xs, rows)
        chunks = []
        multi = False
        for lo in range(0, rows, self.max_batch):
            hi = min(lo + self.max_batch, rows)
            part, multi = self._pad_and_run(
                sig, [x[lo:hi] for x in xs], hi - lo)
            chunks.append(part)
        return [np.concatenate([c[i] for c in chunks])
                for i in range(len(chunks[0]))], multi

    def _pad_and_run(self, sig, xs, n):
        bucket = next(b for b in self.buckets if b >= n)
        fn = self._get_compiled(sig, bucket)
        obs.histogram("zoo_tpu_serving_batch_size",
                      help="predict batch size (leading dim)",
                      buckets=obs.SIZE_BUCKETS).observe(n)
        obs.histogram("zoo_tpu_serving_batch_fill_ratio",
                      help="coalesced rows / bucket capacity",
                      buckets=_FILL_BUCKETS).observe(n / bucket)
        if fn is None:
            # model cannot re-lower (serialized executable without a
            # batch-polymorphic blob): coalesce without padding via
            # the per-request path — still one call per drained batch
            with obs.span("serving/predict", rows=n, bucket=0):
                out = self.model.predict(
                    list(xs) if len(xs) > 1 else xs[0])
            multi = isinstance(out, list)
            outs = out if multi else [out]
            return [np.asarray(o) for o in outs], multi
        pad = bucket - n
        if pad:
            with obs.span("serving/pad", rows=n, bucket=bucket,
                          pad=pad):
                xs = [np.concatenate(
                    [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
                    for x in xs]
            obs.counter("zoo_tpu_serving_padding_rows_total",
                        help="padding rows executed (bucket waste)"
                        ).inc(pad)
        obs.counter("zoo_tpu_serving_batch_executions_total",
                    help="bucket executions",
                    labels={"bucket": str(bucket)}).inc()
        with obs.span("serving/predict", rows=n, bucket=bucket,
                      fill=round(n / bucket, 4)):
            out = fn(*xs)
        multi = isinstance(out, (list, tuple))
        outs = list(out) if multi else [out]
        outs = [np.asarray(o) for o in outs]
        for o in outs:
            if o.ndim < 1 or o.shape[0] != bucket:
                raise ValueError(
                    "model output is not row-aligned with its input "
                    f"(expected leading dim {bucket}, got "
                    f"{o.shape}); dynamic batching requires a "
                    "row-wise forward")
        return [o[:n] for o in outs], multi

    # -- bucket executables -------------------------------------------------
    def _get_compiled(self, sig, bucket: int):
        gen = getattr(self.model, "generation", 0)
        with self._compile_lock:
            if gen != self._model_gen:  # model reloaded underneath us
                self._compiled.clear()
                self._unlowerable.clear()
                self._model_gen = gen
                self._warmed_gauge().set(0)
            fn = self._compiled.get((sig, bucket))
            blocked = sig in self._unlowerable
        if fn is not None:
            return fn
        if blocked or not getattr(self.model, "can_relower", False):
            return None
        # first sight of this signature: warm the WHOLE ladder so the
        # request mix that follows never compiles again
        try:
            self._warm_signature(sig)
        except Exception as e:
            # e.g. a program that only lowers at its declared shapes
            # — serve this signature through the un-padded fallback
            with self._compile_lock:
                self._unlowerable.add(sig)
            logger.warning(
                "bucket warm failed for signature %s (%s: %s); "
                "serving it unpadded through model.predict",
                sig, type(e).__name__, e)
        with self._compile_lock:
            return self._compiled.get((sig, bucket))

    def _warm_signature(self, sig) -> int:
        import jax
        warmed = 0
        for b in self.buckets:
            with self._compile_lock:
                if (sig, b) in self._compiled:
                    continue
            args = [jax.ShapeDtypeStruct((b,) + tuple(shape),
                                         np.dtype(dt))
                    for shape, dt in sig]
            with obs.span("serving/bucket_warm", bucket=b):
                fn = self.model.lower_for(args)
            obs.counter("zoo_tpu_serving_bucket_compiles_total",
                        help="bucket executables compiled "
                        "(warm-up only in steady state)").inc()
            with self._compile_lock:
                self._compiled[(sig, b)] = fn
                self._warmed_gauge().set(len(self._compiled))
            warmed += 1
        return warmed

    # -- introspection ------------------------------------------------------
    @property
    def warmed_buckets(self) -> int:
        with self._compile_lock:
            return len(self._compiled)

    def retry_hint_s(self) -> float:
        """The Retry-After estimate a ``QueueFullError`` raised right
        now would carry (EMA batch execution time x queued entries).
        The fleet router aggregates this across replicas to hint
        clients when the whole fleet is saturated."""
        with self._cond:
            depth = len(self._q)
        return max(0.05, depth * self._ema_batch_s)

    def stats(self) -> dict:
        """JSON-able summary for ``GET /health``."""
        with self._cond:
            depth = len(self._q)
        return {
            "enabled": True,
            "queue_depth": depth,
            "queue_capacity": self.queue_depth,
            "buckets": list(self.buckets),
            "warmed_buckets": self.warmed_buckets,
            "max_wait_ms": self.max_wait_s * 1e3,
            "deadline_ms": (self.deadline_s * 1e3
                            if self.deadline_s else None),
        }

    def __repr__(self):
        return (f"DynamicBatcher(buckets={list(self.buckets)}, "
                f"max_wait_ms={self.max_wait_s * 1e3:g}, "
                f"queue_depth={self.queue_depth}, "
                f"warmed={self.warmed_buckets})")


class _GenEntry:
    """One queued generation request: prompt tokens, decode budget,
    sampling knobs, completion future, clocks, and — once admitted —
    its slot and the tokens emitted so far."""

    __slots__ = ("ids", "max_new", "temperature", "eos_id", "future",
                 "t_enq", "t_enq_wall", "trace", "slot", "tokens",
                 "t_first", "prefilling", "handoff", "blob",
                 "prompt_len")

    def __init__(self, ids, max_new, temperature, eos_id):
        self.ids = ids
        self.max_new = max_new
        self.temperature = temperature
        self.eos_id = eos_id
        self.future: "Future" = Future()
        self.t_enq = time.monotonic()
        self.t_enq_wall = time.time()
        self.trace = tracing.current()
        self.slot = -1
        self.tokens: "list[int]" = []
        self.t_first = 0.0  # monotonic time of the first token
        self.prefilling = False  # admitted, prompt not fully cached
        # disaggregation: None = ordinary request; "out" = prefill
        # side (future resolves to a handoff blob at first token);
        # "in" = decode side (admitted from ``blob``, no prefill)
        self.handoff = None
        self.blob = None
        # page-accounting length: the prompt length, or — for a
        # handoff-in entry that never sees the prompt — the blob's
        # cached position
        self.prompt_len = len(ids)


class ContinuousBatcher:
    """Iteration-level scheduling for autoregressive decode — the
    generation-side sibling of :class:`DynamicBatcher` (ORCA,
    OSDI'22). Where DynamicBatcher coalesces whole fixed-shape
    forwards, generation requests run for a variable number of steps,
    so batching whole *requests* would hold every sequence hostage to
    the longest one. Instead ONE compiled decode step runs
    continuously over a fixed slot array
    (`pipeline/inference/generation.py::GenerationEngine`), and this
    batcher reschedules **between steps**: finished sequences retire
    (pages reclaimed, future resolved) and queued ones are admitted
    into the freed slots via a bucket-padded prefill — the running
    neighbours never stop, and (inactive-slot scatters being dropped)
    never observe the churn.

    Thread model: handler threads call :meth:`submit`; ONE loop
    thread drives admit → step → retire. Admission is gated on a free
    slot AND a full worst-case page reservation
    (`GenerationEngine.can_admit`), so an admitted sequence always
    runs to completion.

    Telemetry: `decode/admit` / `decode/step` / `decode/retire` spans
    (the PR 5 trace vocabulary), slot-occupancy + free-page gauges,
    a tokens counter and a time-to-first-token histogram
    (docs/observability.md). ``ZOO_TPU_GEN_QUEUE_DEPTH`` bounds the
    wait queue (default 64; full → :class:`QueueFullError` → 503),
    ``ZOO_TPU_GEN_MAX_NEW`` caps any request's decode budget
    (default 256).
    """

    def __init__(self, engine, *,
                 queue_depth: Optional[int] = None,
                 max_new_cap: Optional[int] = None):
        env = os.environ
        if queue_depth is None:
            queue_depth = int(env.get("ZOO_TPU_GEN_QUEUE_DEPTH", 64))
        if max_new_cap is None:
            max_new_cap = int(env.get("ZOO_TPU_GEN_MAX_NEW", 256))
        self.engine = engine
        self.queue_depth = int(queue_depth)
        self.max_new_cap = int(max_new_cap)
        self._q: "deque[_GenEntry]" = deque()
        self._active: "list[_GenEntry]" = []
        self._cond = threading.Condition()
        self._stop = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._ema_req_s = 0.05  # retry-after estimator seed
        self._slots_gauge().set(0)
        self._pages_gauge().set(engine.free_pages)

    # -- metrics handles ----------------------------------------------------
    def _slots_gauge(self):
        return obs.gauge("zoo_tpu_serving_gen_slots_active",
                         help="decode slots currently generating")

    def _pages_gauge(self):
        return obs.gauge("zoo_tpu_serving_gen_free_pages",
                         help="free KV-cache pages in the pool")

    def _depth_gauge(self):
        return obs.gauge("zoo_tpu_serving_gen_queue_depth",
                         help="generation requests waiting for a slot")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        """AOT-warm the decode/prefill programs and start the loop
        thread. Idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return self
        diagnostics.install_recompile_monitor()
        with obs.span("decode/warm"):
            self.engine.warm()
        self._stop = False
        self._draining = False
        self._thread = threading.Thread(
            target=self._run, name="zoo-tpu-gen-batcher", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0):
        """Drain first (resident sequences run to completion within
        ``timeout``), then stop the loop thread. Whatever is STILL
        resident or queued when the budget runs out fails with
        RuntimeError and has its slot pages reclaimed — generation
        cannot be handed off mid-sequence the way a queued predict
        can, but an orderly stop should never have to cut anyone off
        (`drain` waited for them)."""
        self.drain(timeout=timeout)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._cond:
            pending = list(self._q) + list(self._active)
            self._q.clear()
            self._active = []
        for e in pending:
            if e.slot >= 0:
                self.engine.release(e.slot)
            _fail_entry(e, RuntimeError("generation batcher stopped"))
        self._slots_gauge().set(self.engine.slots_active)
        self._pages_gauge().set(self.engine.free_pages)

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting new sequences but run the RESIDENT ones to
        completion: their futures resolve with real tokens and their
        pages return to the pool (iteration-level scheduling makes
        this cheap — the loop simply steps the shrinking active set
        until it empties). Queued-but-unadmitted entries fail
        immediately with a retryable RuntimeError — the fleet router
        redispatches them to a sibling, exactly like a queued predict
        during a predict-replica drain. New submits are rejected
        while draining. Returns True when every resident sequence
        retired within ``timeout`` (False = some still running; a
        following `stop` cuts them off). Idempotent."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            queued = list(self._q)
            self._q.clear()
            self._depth_gauge().set(0)
            self._cond.notify_all()
        for e in queued:
            _fail_entry(e, RuntimeError(
                "replica draining; resubmit to another replica"))
        alive = (self._thread is not None
                 and self._thread.is_alive())
        while time.monotonic() < deadline:
            with self._cond:
                if not self._active or not alive:
                    break
            time.sleep(0.005)
        with self._cond:
            drained = not self._active
            owned = {e.slot for e in self._active}
        # page-leak audit (disaggregated serving): a sequence whose
        # handoff was in flight when we started draining may hold a
        # claimed slot no entry owns — e.g. the decode-side splice
        # failed after its entry was failed back to the router.
        # Reclaim such orphans and count the pages; in a correct
        # handoff flow this counter stays at exactly 0 (the smoke
        # asserts it), because export reclaims prefill-side pages
        # the moment the blob exists and a rejected blob is refunded
        # before any allocation.
        before = self.engine.free_pages
        orphans = [s for s in range(self.engine.max_slots)
                   if s not in self.engine.free_slots
                   and s not in owned]
        for s in orphans:
            self.engine.release(s)
        obs.counter(
            "zoo_tpu_serving_gen_handoff_pages_leaked",
            help="pages the drain audit reclaimed from slots no "
                 "request owned (0 = exact pool refill)"
        ).inc(self.engine.free_pages - before)
        self._pages_gauge().set(self.engine.free_pages)
        return drained

    # -- admission ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, eos_id=None) -> "Future":
        """Enqueue one generation request. The future resolves to a
        1-D int array of the NEWLY generated token ids (eos, when
        hit, included). Raises ValueError for prompts the cache can
        never hold and :class:`QueueFullError` at capacity."""
        ids = [int(t) for t in prompt_ids]
        max_new = min(int(max_new_tokens), self.max_new_cap)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 1 <= len(ids) <= self.engine.max_context - 1:
            raise ValueError(
                f"prompt length {len(ids)} outside [1, "
                f"{self.engine.max_context - 1}] for this cache")
        entry = _GenEntry(ids, max_new, float(temperature), eos_id)
        self._enqueue(entry)
        return entry.future

    def _enqueue(self, entry: "_GenEntry"):
        with self._cond:
            if self._draining or self._stop:
                raise RuntimeError(
                    "generation batcher is draining/stopped")
            if len(self._q) >= self.queue_depth:
                retry = max(0.05, len(self._q) * self._ema_req_s)
                obs.counter("zoo_tpu_serving_errors_total",
                            help="serving errors by kind",
                            labels={"kind": "gen_queue_full"}).inc()
                raise QueueFullError(len(self._q), retry)
            self._q.append(entry)
            self._depth_gauge().set(len(self._q))
            self._cond.notify_all()

    def submit_prefill(self, prompt_ids, max_new_tokens: int = 32,
                       temperature: float = 0.0) -> "Future":
        """Prefill-pool admission (disaggregated serving): the prompt
        runs through the normal whole-prompt or chunked prefill path,
        but at the first sampled token the slot's cache state is
        exported and its pages reclaimed — the future resolves to a
        handoff blob (`ops/kv_cache.export`), not tokens. ``max_new``
        rides along in the reservation so admission applies the same
        worst-case page gate a monolithic engine would."""
        ids = [int(t) for t in prompt_ids]
        max_new = min(int(max_new_tokens), self.max_new_cap)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 1 <= len(ids) <= self.engine.max_context - 1:
            raise ValueError(
                f"prompt length {len(ids)} outside [1, "
                f"{self.engine.max_context - 1}] for this cache")
        entry = _GenEntry(ids, max_new, float(temperature), None)
        entry.handoff = "out"
        self._enqueue(entry)
        return entry.future

    def submit_handoff(self, blob: dict, max_new_tokens: int = 32,
                       eos_id=None) -> "Future":
        """Decode-pool admission (disaggregated serving): claim a
        slot + pages for a prefilled sequence and splice its shipped
        KV pages in — no forward pass. The future resolves to the
        FULL new-token stream (the blob's first token included), so
        the router's caller sees exactly the monolithic result.
        Raises ValueError for a blob this engine can never hold
        (geometry/dtype mismatch — a client error, not a retry)."""
        max_new = min(int(max_new_tokens), self.max_new_cap)
        if max_new < 2:
            raise ValueError(
                "handoff admission needs max_new_tokens >= 2 "
                "(the first token was already sampled at prefill)")
        self.engine._check_handoff_blob(blob)
        entry = _GenEntry([], max_new,
                          float(blob.get("temperature", 0.0)),
                          eos_id)
        entry.handoff = "in"
        entry.blob = blob
        entry.prompt_len = int(blob["seq_len"])
        # the prefill side already emitted token 1 — seed it so the
        # done/budget arithmetic and the resolved stream match the
        # monolithic engine byte-for-byte
        entry.tokens = [int(blob["last_token"])]
        self._enqueue(entry)
        return entry.future

    # -- the decode loop ----------------------------------------------------
    def _finish(self, e: "_GenEntry", now: float):
        with obs.span("decode/retire", slot=e.slot,
                      tokens=len(e.tokens)):
            self.engine.release(e.slot)
        dur = now - e.t_enq
        self._ema_req_s = 0.8 * self._ema_req_s + 0.2 * dur
        tracing.record_span(e.trace, "decode/retire", e.t_enq_wall,
                            dur, slot=e.slot, tokens=len(e.tokens))
        e.future.set_result(np.asarray(e.tokens, np.int32))

    def _finish_handoff_out(self, e: "_GenEntry", now: float):
        """Prefill-side retirement: export the slot's cache state
        (which reclaims its pages immediately) and resolve the future
        with the blob. The entry never joins the decode set."""
        with obs.span("decode/handoff_export", slot=e.slot):
            blob = self.engine.export_handoff(e.slot)
        obs.counter(
            "zoo_tpu_serving_gen_handoffs_total",
            help="KV-page handoffs between prefill and decode pools",
            labels={"direction": "out"}).inc()
        dur = now - e.t_enq
        self._ema_req_s = 0.8 * self._ema_req_s + 0.2 * dur
        tracing.record_span(e.trace, "decode/handoff_export",
                            e.t_enq_wall, dur, slot=e.slot,
                            seq_len=blob["seq_len"])
        e.future.set_result(blob)

    def _admit_handoffs(self, entries, done):
        """Decode-side admission: splice each blob into the engine —
        no forward pass — and join the active set. A failed splice
        fails only its own entry (the router refunds the blob to a
        sibling); the engine validates before allocating, so a
        rejected blob leaves the pool intact."""
        engine = self.engine
        for e in entries:
            try:
                with obs.span("decode/handoff_admit"):
                    slot = engine.admit_from_handoff(e.blob,
                                                     e.max_new)
            except Exception as exc:
                _fail_entry(e, exc)
                continue
            now = time.monotonic()
            e.slot = slot
            e.blob = None  # drop the host copy once spliced
            obs.histogram(
                "zoo_tpu_serving_gen_handoff_seconds",
                help="decode-pool handoff admission latency "
                     "(blob enqueue to pages spliced)"
            ).observe(now - e.t_enq)
            obs.counter(
                "zoo_tpu_serving_gen_handoffs_total",
                help="KV-page handoffs between prefill and decode "
                     "pools", labels={"direction": "in"}).inc()
            tracing.record_span(e.trace, "decode/handoff_admit",
                                e.t_enq_wall, now - e.t_enq,
                                slot=slot, seq_len=e.prompt_len)
            # the seeded first token may already satisfy the budget
            # (or be eos — the router normally short-circuits that
            # case before the hop, but stay defensive)
            if (e.eos_id is not None
                    and e.tokens[-1] == e.eos_id) \
                    or len(e.tokens) >= e.max_new:
                done.append(e)
            else:
                self._active.append(e)

    def _token_out(self, e: "_GenEntry", tok: int, now: float
                   ) -> bool:
        """Record one emitted token; True when the request is done."""
        if not e.tokens:
            e.t_first = now
            obs.histogram(
                "zoo_tpu_serving_gen_ttft_seconds",
                help="time from submit to first generated token"
            ).observe(now - e.t_enq)
        e.tokens.append(tok)
        if e.eos_id is not None and tok == e.eos_id:
            return True
        return len(e.tokens) >= e.max_new

    def _admit_locked_pop(self) -> "list[_GenEntry]":
        """Pop the longest queue prefix that fits (FIFO — no request
        starves behind a smaller one that jumped it). Slots and pages
        consumed by entries popped earlier in the SAME batch are
        debited provisionally — `engine.can_admit` alone only knows
        the committed state."""
        take = []
        slots = len(self.engine.free_slots)
        pages = self.engine.free_pages
        while self._q and slots > 0:
            e = self._q[0]
            need = self.engine.pages_for(e.prompt_len, e.max_new)
            if need > pages:
                break
            take.append(self._q.popleft())
            slots -= 1
            pages -= need
        if take:
            self._depth_gauge().set(len(self._q))
        return take

    def _spec_eligible(self, e: "_GenEntry") -> bool:
        """Whether a resident slot may take a speculative round. A
        round consumes a full k-token verify window even when the
        request only needs one more token, so the window must fit
        inside the slot's page reservation AND the cache context:
        consumed rows after the round are ``plen + emitted - 1 + k``
        and the reservation covers ``min(plen + max_new,
        max_context)`` rows. Ineligible slots fall back to regular
        one-token steps in the same iteration."""
        k = self.engine.spec_k
        consumed_after = e.prompt_len + len(e.tokens) - 1 + k
        budget = min(e.prompt_len + e.max_new,
                     self.engine.max_context)
        return consumed_after <= budget

    def _run(self):
        engine = self.engine
        chunked = getattr(engine, "prefill_chunk", 0) > 0
        spec_k = int(getattr(engine, "spec_k", 0))
        while True:
            with self._cond:
                while not self._q and not self._active \
                        and not self._stop:
                    self._cond.wait(timeout=0.1)
                if self._stop:
                    return
                fresh = ([] if self._draining
                         else self._admit_locked_pop())
            try:
                now = time.monotonic()
                done: "list[_GenEntry]" = []

                def chunk_step():
                    # advance every mid-prefill slot by one chunk
                    # and emit first tokens for prompts whose final
                    # chunk just landed
                    with obs.span(
                            "decode/prefill_chunk",
                            n=len(engine.prefilling_slots)):
                        firsts = engine.prefill_step()
                    t = time.monotonic()
                    obs.counter(
                        "zoo_tpu_serving_gen_prefill_chunks_total",
                        help="prompt chunks written by chunked "
                             "prefill").inc()
                    if firsts:
                        by_slot = {e.slot: e
                                   for e in self._active}
                        for slot, tok in firsts:
                            e = by_slot[slot]
                            e.prefilling = False
                            if e.handoff == "out":
                                self._token_out(e, tok, t)
                                self._active.remove(e)
                                self._finish_handoff_out(e, t)
                            elif self._token_out(e, tok, t):
                                done.append(e)
                                self._active.remove(e)
                if fresh:
                    hand_in = [e for e in fresh
                               if e.handoff == "in"]
                    if hand_in:
                        fresh = [e for e in fresh
                                 if e.handoff != "in"]
                        self._admit_handoffs(hand_in, done)
                if fresh:
                    # chunked admission only pays off past one
                    # chunk: a prompt that fits in a single chunk
                    # would run the full-width chunk program padded,
                    # where the classic bucket-padded prefill runs
                    # one right-sized call — so short prompts keep
                    # the direct path even when chunking is on
                    long_p = [e for e in fresh if chunked
                              and len(e.ids) > engine.prefill_chunk]
                    short_p = [e for e in fresh if e not in long_p]
                    if long_p:
                        # claim slots + pages only; the prompt is
                        # written chunk-by-chunk below, interleaved
                        # with decode steps of resident slots
                        reqs = [(e.ids, e.max_new, e.temperature)
                                for e in long_p]
                        with obs.span("decode/admit",
                                      n=len(long_p)):
                            slots = engine.admit_partial(reqs)
                        now = time.monotonic()
                        for e, slot in zip(long_p, slots):
                            e.slot = slot
                            e.prefilling = True
                            tracing.record_span(
                                e.trace, "decode/admit",
                                e.t_enq_wall, now - e.t_enq,
                                slot=slot, prompt_len=len(e.ids))
                            self._active.append(e)
                        # kickoff: land the fresh prompts' first
                        # chunk in the iteration that admitted them
                        # rather than waiting a full loop pass —
                        # one bounded extra chunk call, mirroring
                        # how short prompts prefill inline at admit
                        chunk_step()
                    if short_p:
                        reqs = [(e.ids, e.max_new, e.temperature)
                                for e in short_p]
                        with obs.span("decode/admit",
                                      n=len(short_p)):
                            first = engine.admit(reqs)
                        now = time.monotonic()
                        for e, (slot, tok) in zip(short_p, first):
                            e.slot = slot
                            tracing.record_span(
                                e.trace, "decode/admit",
                                e.t_enq_wall, now - e.t_enq,
                                slot=slot, prompt_len=len(e.ids))
                            if e.handoff == "out":
                                self._token_out(e, tok, now)
                                self._finish_handoff_out(e, now)
                            elif self._token_out(e, tok, now):
                                done.append(e)
                            else:
                                self._active.append(e)
                if chunked and engine.prefilling_slots:
                    chunk_step()
                    now = time.monotonic()
                spec: "list[_GenEntry]" = []
                regular: "list[_GenEntry]" = []
                for e in self._active:
                    if e.prefilling:
                        continue
                    if spec_k > 0 and self._spec_eligible(e):
                        spec.append(e)
                    else:
                        regular.append(e)
                emitted = 0
                if spec:
                    active = np.zeros((engine.max_slots,),
                                      np.bool_)
                    for e in spec:
                        active[e.slot] = True
                    prev_acc = engine.spec_accepted
                    with obs.span("decode/spec_step",
                                  n=len(spec)):
                        out, n_emit = engine.spec_step(active)
                    now = time.monotonic()
                    obs.counter(
                        "zoo_tpu_serving_gen_spec_proposed_total",
                        help="draft tokens proposed for "
                             "verification").inc(
                        spec_k * len(spec))
                    obs.counter(
                        "zoo_tpu_serving_gen_spec_accepted_total",
                        help="draft tokens accepted by the "
                             "target model").inc(
                        engine.spec_accepted - prev_acc)
                    for e in spec:
                        fin = False
                        for j in range(int(n_emit[e.slot])):
                            emitted += 1
                            if self._token_out(
                                    e, int(out[e.slot, j]), now):
                                fin = True
                                break
                        if fin:
                            done.append(e)
                            self._active.remove(e)
                if regular:
                    active = np.zeros((engine.max_slots,),
                                      np.bool_)
                    for e in regular:
                        active[e.slot] = True
                    with obs.span("decode/step",
                                  n=len(regular)):
                        toks = engine.step(active)
                    now = time.monotonic()
                    for e in regular:
                        emitted += 1
                        if self._token_out(e, int(toks[e.slot]),
                                           now):
                            done.append(e)
                            self._active.remove(e)
                if spec or regular:
                    obs.counter(
                        "zoo_tpu_serving_gen_tokens_total",
                        help="tokens generated").inc(emitted)
                    obs.counter(
                        "zoo_tpu_serving_gen_steps_total",
                        help="decode iterations executed").inc()
                for e in done:
                    self._finish(e, now)
            except Exception as exc:
                # a device/step failure must fail its requests, not
                # the loop thread; slots are reclaimed so the batch
                # keeps serving whoever comes next
                failing = {id(e): e
                           for e in fresh + self._active}
                for e in failing.values():
                    if e.slot >= 0:
                        engine.release(e.slot)
                    _fail_entry(e, exc)
                self._active = []
                logger.warning("generation batcher error: %s", exc)
            self._slots_gauge().set(engine.slots_active)
            self._pages_gauge().set(engine.free_pages)

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        """JSON-able summary for ``GET /health``."""
        with self._cond:
            depth = len(self._q)
            active = len(self._active)
        s = {"enabled": True, "queue_depth": depth,
             "queue_capacity": self.queue_depth,
             "requests_active": active,
             "max_new_cap": self.max_new_cap}
        s.update(self.engine.stats())
        return s

    def __repr__(self):
        return (f"ContinuousBatcher(slots={self.engine.max_slots}, "
                f"context={self.engine.max_context}, "
                f"queue_depth={self.queue_depth})")
