"""Generation serving engine: device state + compiled programs for
autoregressive decode.

The model layer owns the math (`TransformerLayer.prefill` /
`decode_step` / `generate` — `pipeline/api/keras/layers/transformer.py`);
this module owns everything a *server* needs around it:

- ONE resident :class:`~analytics_zoo_tpu.ops.kv_cache.PagedKVCache`
  sized ``(max_slots, max_context)``, with the host-side
  `PageAllocator` assigning physical pages to slots at admission and
  reclaiming them at retirement — the vLLM bookkeeping half;
- ONE compiled decode-step program (shape-static over the full slot
  array, inactive slots frozen by the ``active`` mask) plus one
  compiled prefill program per prompt-length bucket (the PR 4 bucket
  ladder, reused) — after :meth:`GenerationEngine.warm`, steady-state
  serving performs **zero** compilations regardless of the
  prompt/output-length mix;
- per-slot sampling state: a traced ``(max_slots,)`` temperature
  vector (per-request temperature without recompiles) and a static
  ``top_k`` (``ZOO_TPU_GEN_TOP_K``);
- a sequential whole-loop :meth:`generate` (the model's compiled
  `lax.while_loop` path, jit-cached per shape) — the per-request
  baseline `InferenceModel.generate` serves and `bench_generate.py`
  A/Bs continuous batching against.

The engine is NOT thread-safe by design: exactly one driver — the
:class:`~analytics_zoo_tpu.pipeline.inference.batching.ContinuousBatcher`
loop thread, or a caller of :meth:`generate` — may touch it at a time
(the batcher serializes admission, stepping, and retirement by
construction, the same single-dispatcher discipline DynamicBatcher
uses).

Configuration (constructor kwargs override the environment):
``ZOO_TPU_GEN_SLOTS`` (default 8), ``ZOO_TPU_GEN_MAX_CONTEXT``
(default: the net's ``seq_len``), ``ZOO_TPU_GEN_PAGE_SIZE`` (16),
``ZOO_TPU_GEN_TOP_K`` (0 = full softmax). docs/serving.md has the
slot/page sizing guide, docs/perf_flags.md the flag catalog.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.pipeline.inference.batching import bucket_ladder

__all__ = ["GenerationEngine"]

# chaos hook: armed via ZOO_TPU_FAULTS or tests (docs/robustness.md);
# a "kill" here simulates the device/replica dying mid-decode with
# resident sequences holding KV pages
_STEP_FAULT = faults.point("generation/decode_step")


class GenerationEngine:
    """Resident decode state + compiled programs for one generative
    net (module docstring has the design).

    ``net`` must expose the decode surface the transformer layer
    defines: ``init_kv_cache / prefill / decode_step / generate`` and
    a ``seq_len`` attribute (duck-typed — any net with those methods
    serves).
    """

    def __init__(self, net, params, *,
                 max_slots: Optional[int] = None,
                 max_context: Optional[int] = None,
                 page_size: Optional[int] = None,
                 top_k: Optional[int] = None,
                 cache_dtype=None,
                 rng_seed: int = 0):
        import jax

        env = os.environ
        if max_slots is None:
            max_slots = int(env.get("ZOO_TPU_GEN_SLOTS", 8))
        if max_context is None:
            max_context = int(env.get("ZOO_TPU_GEN_MAX_CONTEXT",
                                      net.seq_len))
        if page_size is None:
            page_size = int(env.get("ZOO_TPU_GEN_PAGE_SIZE", 16))
        if top_k is None:
            top_k = int(env.get("ZOO_TPU_GEN_TOP_K", 0))
        if max_context > net.seq_len:
            raise ValueError(
                f"max_context {max_context} exceeds the net's "
                f"position table ({net.seq_len})")
        self.net = net
        self.params = params
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.top_k = int(top_k)
        self.cache_dtype = cache_dtype

        from analytics_zoo_tpu.ops import kv_cache as kvc
        cache = net.init_kv_cache(self.max_slots, int(max_context),
                                  page_size=self.page_size,
                                  dtype=cache_dtype)
        self.max_context = cache.max_context  # whole-page rounded
        self.pages_per_slot = cache.page_table.shape[1]
        # the engine owns page placement: blank the identity table and
        # hand every physical page to the allocator
        self._table = np.zeros(
            (self.max_slots, self.pages_per_slot), np.int32)
        self.cache = cache._replace(
            page_table=jax.numpy.asarray(self._table))
        self.allocator = kvc.PageAllocator(cache.k_pages.shape[1])
        self._slot_pages: "dict[int, list]" = {}
        self.free_slots = set(range(self.max_slots))

        # per-slot sampling state (traced per call — no recompiles)
        self._temps = np.zeros((self.max_slots,), np.float32)
        self._last_tok = np.zeros((self.max_slots,), np.int32)
        self._rng = jax.random.key(int(rng_seed))
        self._step_id = 0

        # prompt-length buckets: the PR 4 ladder, capped at what the
        # position table and the cache can hold
        self.prompt_buckets = bucket_ladder(
            min(self.max_context, int(net.seq_len)))

        self._compiled_step = None
        self._compiled_prefill: dict = {}
        self._gen_jits: dict = {}

    # -- compiled programs --------------------------------------------------
    def _step_fn(self, cache, params, tok, active, temps, rng, step):
        import jax
        from analytics_zoo_tpu.ops.sampling import sample_tokens
        cache, logits = self.net.decode_step(params, cache, tok,
                                             active=active)
        nxt = sample_tokens(jax.random.fold_in(rng, step),
                            logits.astype(jax.numpy.float32), temps,
                            self.top_k)
        return cache, nxt

    def _prefill_fn(self, cache, params, ids, plens, temps, rng,
                    step):
        import jax
        from analytics_zoo_tpu.ops.sampling import sample_tokens
        cache, logits = self.net.prefill(params, cache, ids, plens)
        nxt = sample_tokens(jax.random.fold_in(rng, step),
                            logits.astype(jax.numpy.float32), temps,
                            self.top_k)
        return cache, nxt

    def _abstract(self, tree):
        import jax
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a),
                                           np.asarray(a).dtype)
            if not hasattr(a, "aval") else
            jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

    def _get_step(self):
        if self._compiled_step is None:
            import jax
            s = self.max_slots
            structs = (
                self._abstract(self.cache),
                self._abstract(self.params),
                jax.ShapeDtypeStruct((s,), np.int32),
                jax.ShapeDtypeStruct((s,), np.bool_),
                jax.ShapeDtypeStruct((s,), np.float32),
                self._abstract(self._rng),
                jax.ShapeDtypeStruct((), np.int32),
            )
            with obs.span("decode/compile", program="step"):
                self._compiled_step = jax.jit(
                    self._step_fn,
                    donate_argnums=(0,)).lower(*structs).compile()
            obs.counter(
                "zoo_tpu_serving_gen_compiles_total",
                help="generation programs compiled (warm-up only in "
                "steady state)", labels={"program": "step"}).inc()
        return self._compiled_step

    def _get_prefill(self, tp: int):
        fn = self._compiled_prefill.get(tp)
        if fn is None:
            import jax
            s = self.max_slots
            structs = (
                self._abstract(self.cache),
                self._abstract(self.params),
                jax.ShapeDtypeStruct((s, tp), np.int32),
                jax.ShapeDtypeStruct((s,), np.int32),
                jax.ShapeDtypeStruct((s,), np.float32),
                self._abstract(self._rng),
                jax.ShapeDtypeStruct((), np.int32),
            )
            with obs.span("decode/compile", program="prefill",
                          bucket=tp):
                fn = jax.jit(
                    self._prefill_fn,
                    donate_argnums=(0,)).lower(*structs).compile()
            obs.counter(
                "zoo_tpu_serving_gen_compiles_total",
                help="generation programs compiled (warm-up only in "
                "steady state)", labels={"program": "prefill"}).inc()
            self._compiled_prefill[tp] = fn
        return fn

    def warm(self) -> int:
        """AOT-compile the decode step and every prompt bucket's
        prefill up front, so the serving loop never compiles under
        traffic (the DynamicBatcher bucket-warm discipline). Returns
        the number of programs compiled this call. Idempotent."""
        n0 = len(self._compiled_prefill) + bool(self._compiled_step)
        self._get_step()
        for tp in self.prompt_buckets:
            self._get_prefill(tp)
        return (len(self._compiled_prefill) + 1) - n0

    # -- admission / stepping / retirement ----------------------------------
    def pages_for(self, prompt_len: int, max_new: int) -> int:
        """Worst-case page reservation for one request (prompt +
        max_new tokens, capped at the context window)."""
        from analytics_zoo_tpu.ops.kv_cache import PageAllocator
        return PageAllocator.pages_needed(
            min(prompt_len + max_new, self.max_context),
            self.page_size)

    def can_admit(self, prompt_len: int, max_new: int) -> bool:
        """Whether a request of this size fits RIGHT NOW: a free slot
        and enough free pages for its worst case. Pages are reserved
        in full at admission (prompt + max_new tokens), so an admitted
        sequence can always run to completion — no mid-decode
        eviction, no allocation deadlock."""
        return bool(self.free_slots) and self.allocator.can_alloc(
            self.pages_for(prompt_len, max_new))

    def admit(self, requests: "Sequence[tuple]") -> "list[tuple]":
        """Admit ``[(prompt_ids, max_new, temperature), ...]`` into
        free slots of the LIVE batch: assign pages, write the table
        rows, run ONE bucket-padded prefill (slots not being admitted
        pass ``prompt_lens == 0`` and are untouched — the property
        `prefill` guarantees), and sample each new slot's first
        token. Returns ``[(slot, first_token), ...]``. Raises
        MemoryError when slots/pages run out mid-list (callers gate
        with :meth:`can_admit` per request first)."""
        import jax
        from analytics_zoo_tpu.ops.kv_cache import PageAllocator
        if not requests:
            return []
        for prompt_ids, _, _ in requests:
            if not 1 <= len(prompt_ids) <= self.max_context - 1:
                raise ValueError(
                    f"prompt length {len(prompt_ids)} outside [1, "
                    f"{self.max_context - 1}]")
        tp = max(len(r[0]) for r in requests)
        tp = next(b for b in self.prompt_buckets if b >= tp)
        ids_arr = np.zeros((self.max_slots, tp), np.int32)
        plens = np.zeros((self.max_slots,), np.int32)
        admitted = []
        for prompt_ids, max_new, temperature in requests:
            n = len(prompt_ids)
            need = PageAllocator.pages_needed(
                min(n + int(max_new), self.max_context),
                self.page_size)
            if not self.free_slots:
                raise MemoryError("no free decode slot")
            pages = self.allocator.alloc(need)  # MemoryError if short
            slot = min(self.free_slots)
            self.free_slots.discard(slot)
            self._slot_pages[slot] = pages
            row = np.full((self.pages_per_slot,), pages[-1], np.int32)
            row[:need] = pages
            self._table[slot] = row
            ids_arr[slot, :n] = np.asarray(prompt_ids, np.int32)
            plens[slot] = n
            self._temps[slot] = float(temperature)
            admitted.append(slot)
        self.cache = self.cache._replace(
            page_table=jax.numpy.asarray(self._table))
        fn = self._get_prefill(tp)
        self.cache, toks = fn(self.cache, self.params, ids_arr,
                              plens, self._temps, self._rng,
                              np.int32(self._step_id))
        self._step_id += 1
        toks = np.asarray(toks)
        out = []
        for slot in admitted:
            self._last_tok[slot] = toks[slot]
            out.append((slot, int(toks[slot])))
        return out

    def step(self, active: np.ndarray) -> np.ndarray:
        """One decode iteration over the WHOLE slot array: append each
        active slot's last token to the cache, attend, sample. Slots
        with ``active == False`` are frozen (nothing written, lengths
        unchanged). Returns the ``(max_slots,)`` sampled tokens —
        meaningful only at active slots."""
        _STEP_FAULT.fire()
        fn = self._get_step()
        active = np.asarray(active, np.bool_)
        self.cache, toks = fn(self.cache, self.params,
                              self._last_tok, active, self._temps,
                              self._rng, np.int32(self._step_id))
        self._step_id += 1
        toks = np.asarray(toks)
        self._last_tok = np.where(active, toks, self._last_tok
                                  ).astype(np.int32)
        return toks

    def release(self, slot: int):
        """Retire a slot: reclaim its pages and return it to the free
        pool. The cache rows need no reset — a future `prefill` with
        ``prompt_lens > 0`` overwrites ``seq_lens``, and until then
        the ``active`` mask keeps the slot frozen."""
        pages = self._slot_pages.pop(slot, None)
        if pages:
            self.allocator.free(pages)
        self.free_slots.add(slot)

    @property
    def slots_active(self) -> int:
        return self.max_slots - len(self.free_slots)

    @property
    def free_pages(self) -> int:
        return self.allocator.free_pages

    # -- sequential whole-loop path -----------------------------------------
    def generate(self, prompts, max_new_tokens: int = 32, *,
                 temperature: float = 0.0, eos_id=None, rng=None
                 ) -> "list[np.ndarray]":
        """Per-request compiled generation: the model's whole-loop
        `generate` (prefill + `lax.while_loop`), jit-cached per
        (batch, prompt-bucket, max_new) shape. This is the SEQUENTIAL
        baseline — each call owns a fresh cache and runs to
        completion; concurrent traffic should go through the
        continuous batcher instead. Returns one array of NEWLY
        generated token ids per prompt (eos, when hit, included)."""
        import jax
        if prompts and np.isscalar(prompts[0]):
            prompts = [prompts]
        s = len(prompts)
        tp = max(len(p) for p in prompts)
        tp = next((b for b in self.prompt_buckets if b >= tp), tp)
        max_new = int(max_new_tokens)
        ids = np.zeros((s, tp), np.int32)
        plens = np.zeros((s,), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = np.asarray(p, np.int32)
            plens[i] = len(p)
        key = (s, tp, max_new, eos_id)
        fn = self._gen_jits.get(key)
        if fn is None:
            net, tk = self.net, self.top_k
            ps, cd = self.page_size, self.cache_dtype

            def run(params, ids, plens, temps, rng):
                return net.generate(
                    params, ids, prompt_lens=plens,
                    max_new_tokens=max_new, temperature=temps,
                    top_k=tk, eos_id=eos_id, rng=rng,
                    page_size=ps, cache_dtype=cd)

            fn = jax.jit(run)
            self._gen_jits[key] = fn
        temps = np.full((s,), float(temperature), np.float32)
        buf, lens = fn(self.params, ids, plens, temps,
                       self._rng if rng is None else rng)
        buf, lens = np.asarray(buf), np.asarray(lens)
        return [buf[i, plens[i]:lens[i]] for i in range(s)]

    def stats(self) -> dict:
        """JSON-able summary for ``GET /health``."""
        return {
            "max_slots": self.max_slots,
            "slots_active": self.slots_active,
            "max_context": self.max_context,
            "page_size": self.page_size,
            "free_pages": self.free_pages,
            "total_pages": self.allocator.max_pages,
            "prompt_buckets": list(self.prompt_buckets),
            "warmed_programs": (len(self._compiled_prefill)
                                + bool(self._compiled_step)),
        }

    def __repr__(self):
        return (f"GenerationEngine(slots={self.max_slots}, "
                f"context={self.max_context}, "
                f"page_size={self.page_size}, "
                f"free_pages={self.free_pages})")
