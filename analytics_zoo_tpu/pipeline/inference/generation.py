"""Generation serving engine: device state + compiled programs for
autoregressive decode.

The model layer owns the math (`TransformerLayer.prefill` /
`decode_step` / `generate` — `pipeline/api/keras/layers/transformer.py`);
this module owns everything a *server* needs around it:

- ONE resident :class:`~analytics_zoo_tpu.ops.kv_cache.PagedKVCache`
  sized ``(max_slots, max_context)``, with the host-side
  `PageAllocator` assigning physical pages to slots at admission and
  reclaiming them at retirement — the vLLM bookkeeping half;
- ONE compiled decode-step program (shape-static over the full slot
  array, inactive slots frozen by the ``active`` mask) plus one
  compiled prefill program per prompt-length bucket (the PR 4 bucket
  ladder, reused) — after :meth:`GenerationEngine.warm`, steady-state
  serving performs **zero** compilations regardless of the
  prompt/output-length mix;
- per-slot sampling state: a traced ``(max_slots,)`` temperature
  vector (per-request temperature without recompiles) and a static
  ``top_k`` (``ZOO_TPU_GEN_TOP_K``);
- a sequential whole-loop :meth:`generate` (the model's compiled
  `lax.while_loop` path, jit-cached per shape) — the per-request
  baseline `InferenceModel.generate` serves and `bench_generate.py`
  A/Bs continuous batching against.

Three capacity levers layer on top (each off by default, all
compounding — docs/serving.md has the tuning guide):

- **Chunked prefill** (``ZOO_TPU_PREFILL_CHUNK`` = chunk width C,
  0 = off): :meth:`admit_partial` assigns slots/pages WITHOUT running
  the prompt; :meth:`prefill_step` then advances every prefilling
  slot by at most C prompt tokens through ONE compiled chunk program
  (`TransformerLayer.forward_chunk`), so the batcher can interleave
  a bounded chunk with every decode iteration — a long prompt never
  stalls resident sequences for more than one chunk's latency, and
  TTFT p99 stops depending on the longest co-resident prompt.
- **Int8 paged KV** (``ZOO_TPU_KV_DTYPE=int8|bf16|f32``): the cache
  pools quantize per row with per-page scale arrays
  (`ops/kv_cache.quantize_rows`) — ~2x resident sequences per chip
  for a bounded accuracy cost (the kv-dtype conformance matrix in
  tests/test_generate.py states the tolerance).
- **Speculative decoding** (``ZOO_TPU_SPEC_K`` = draft length k,
  0 = off; needs a ``drafter`` net registered through
  `InferenceModel.load_generator`): a small drafter proposes k
  tokens (one compiled scan, :meth:`_get_draft`), the target scores
  all k in ONE verify chunk (`forward_chunk(all_logits=True)`), and
  rejection sampling (`ops/sampling.speculative_accept`) accepts a
  prefix — distribution-exact for temperature sampling, byte-exact
  for greedy. Both caches simply rewind ``seq_lens`` on rejection
  (stale rows past the length are invisible by construction), and
  the drafter's pages mirror the target's table, so page accounting
  is unchanged.

The engine is NOT thread-safe by design: exactly one driver — the
:class:`~analytics_zoo_tpu.pipeline.inference.batching.ContinuousBatcher`
loop thread, or a caller of :meth:`generate` — may touch it at a time
(the batcher serializes admission, stepping, and retirement by
construction, the same single-dispatcher discipline DynamicBatcher
uses).

Configuration (constructor kwargs override the environment):
``ZOO_TPU_GEN_SLOTS`` (default 8), ``ZOO_TPU_GEN_MAX_CONTEXT``
(default: the net's ``seq_len``), ``ZOO_TPU_GEN_PAGE_SIZE`` (16),
``ZOO_TPU_GEN_TOP_K`` (0 = full softmax), ``ZOO_TPU_KV_DTYPE``
(f32), ``ZOO_TPU_PREFILL_CHUNK`` (0 = whole-prompt prefill),
``ZOO_TPU_SPEC_K`` (0 = no speculation). docs/serving.md has the
slot/page sizing guide, docs/perf_flags.md the flag catalog.

Every AOT compile here is *deliberate* (warm-up or first-use of a
known program), so they are bracketed with
`diagnostics.expected_compiles()` — the RecompileMonitor keeps its
total count but excludes them from the storm window (a warm() of
step + buckets used to fire a spurious ``recompile_storm``).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.pipeline.inference.batching import bucket_ladder

__all__ = ["GenerationEngine", "resolve_kv_dtype"]

# chaos hook: armed via ZOO_TPU_FAULTS or tests (docs/robustness.md);
# a "kill" here simulates the device/replica dying mid-decode with
# resident sequences holding KV pages
_STEP_FAULT = faults.point("generation/decode_step")

_KV_DTYPES = ("f32", "bf16", "int8")


def resolve_kv_dtype(cache_dtype=None):
    """Resolve the paged-cache storage dtype: an explicit dtype (or
    its string name) wins, else ``ZOO_TPU_KV_DTYPE`` (default f32 —
    bit-identical to PR 8; bf16 halves cache HBM, int8 halves it
    again with per-page scales). Returns a jnp dtype."""
    import jax.numpy as jnp
    named = {"f32": jnp.float32, "float32": jnp.float32,
             "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
             "int8": jnp.int8}
    if cache_dtype is None:
        cache_dtype = os.environ.get("ZOO_TPU_KV_DTYPE", "f32")
    if isinstance(cache_dtype, str):
        if cache_dtype not in named:
            raise ValueError(
                f"ZOO_TPU_KV_DTYPE {cache_dtype!r} not one of "
                f"{_KV_DTYPES}")
        return named[cache_dtype]
    return cache_dtype


class GenerationEngine:
    """Resident decode state + compiled programs for one generative
    net (module docstring has the design).

    ``net`` must expose the decode surface the transformer layer
    defines: ``init_kv_cache / prefill / decode_step / forward_chunk
    / generate`` and ``seq_len`` / ``vocab`` attributes (duck-typed —
    any net with those methods serves). A ``drafter`` (same surface,
    same vocab, typically far fewer blocks) plus ``spec_k > 0`` turns
    on speculative decoding.
    """

    def __init__(self, net, params, *,
                 max_slots: Optional[int] = None,
                 max_context: Optional[int] = None,
                 page_size: Optional[int] = None,
                 top_k: Optional[int] = None,
                 cache_dtype=None,
                 prefill_chunk: Optional[int] = None,
                 spec_k: Optional[int] = None,
                 drafter=None, drafter_params=None,
                 rng_seed: int = 0,
                 role: str = "both"):
        import jax

        env = os.environ
        if max_slots is None:
            max_slots = int(env.get("ZOO_TPU_GEN_SLOTS", 8))
        if max_context is None:
            max_context = int(env.get("ZOO_TPU_GEN_MAX_CONTEXT",
                                      net.seq_len))
        if page_size is None:
            page_size = int(env.get("ZOO_TPU_GEN_PAGE_SIZE", 16))
        if top_k is None:
            top_k = int(env.get("ZOO_TPU_GEN_TOP_K", 0))
        if prefill_chunk is None:
            prefill_chunk = int(env.get("ZOO_TPU_PREFILL_CHUNK", 0))
        if spec_k is None:
            spec_k = int(env.get("ZOO_TPU_SPEC_K", 0))
        if max_context > net.seq_len:
            raise ValueError(
                f"max_context {max_context} exceeds the net's "
                f"position table ({net.seq_len})")
        self.net = net
        self.params = params
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.top_k = int(top_k)
        self.cache_dtype = resolve_kv_dtype(cache_dtype)
        self.prefill_chunk = max(0, int(prefill_chunk))
        self.spec_k = max(0, int(spec_k))
        self.drafter = drafter
        self.drafter_params = drafter_params
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role {role!r} not one of 'prefill'/'decode'/'both'")
        self.role = role
        if self.spec_k > 0 and drafter is None:
            raise ValueError(
                "spec_k > 0 needs a drafter net (load_generator"
                "(..., drafter=..., drafter_params=...))")
        if self.spec_k > 0 and role != "both":
            # the drafter's cache state cannot be reconstructed from
            # a handoff blob without re-running its forward pass, so
            # speculation stays a monolithic-engine lever
            raise ValueError(
                "speculative decoding (spec_k > 0) is incompatible "
                "with disaggregated roles; use role='both'")
        if self.spec_k > 1_000:
            raise ValueError(f"spec_k {self.spec_k} is absurd")

        from analytics_zoo_tpu.ops import kv_cache as kvc
        cache = net.init_kv_cache(self.max_slots, int(max_context),
                                  page_size=self.page_size,
                                  dtype=self.cache_dtype)
        self.max_context = cache.max_context  # whole-page rounded
        self.pages_per_slot = cache.page_table.shape[1]
        # the engine owns page placement: blank the identity table and
        # hand every physical page to the allocator
        self._table = np.zeros(
            (self.max_slots, self.pages_per_slot), np.int32)
        self.cache = cache._replace(
            page_table=jax.numpy.asarray(self._table))
        self.allocator = kvc.PageAllocator(cache.k_pages.shape[1])
        self._slot_pages: "dict[int, list]" = {}
        self.free_slots = set(range(self.max_slots))

        # drafter state: its own (smaller) page pool, but the SAME
        # slot/page geometry and the SAME table — the target's page
        # accounting covers both, and seq_lens stay in lockstep
        # because draft/verify rewind them together
        self._draft_cache = None
        if drafter is not None and self.spec_k > 0:
            if int(drafter.vocab) != int(net.vocab):
                raise ValueError(
                    f"drafter vocab {drafter.vocab} != target vocab "
                    f"{net.vocab}")
            if self.max_context > drafter.seq_len:
                raise ValueError(
                    f"max_context {self.max_context} exceeds the "
                    f"drafter's position table ({drafter.seq_len})")
            dcache = drafter.init_kv_cache(
                self.max_slots, int(max_context),
                page_size=self.page_size, dtype=self.cache_dtype)
            # own device copy of the table — the compiled programs
            # donate whole cache pytrees, and a buffer shared with
            # the target cache would be deleted out from under it
            self._draft_cache = dcache._replace(
                page_table=jax.numpy.array(self._table))

        # per-slot sampling state (traced per call — no recompiles)
        self._temps = np.zeros((self.max_slots,), np.float32)
        self._last_tok = np.zeros((self.max_slots,), np.int32)
        self._rng = jax.random.key(int(rng_seed))
        self._step_id = 0

        # chunked-prefill scheduler state: slot -> [ids, next_offset]
        # (prompts admitted but not yet fully written to the cache)
        self._pending_prompts: "dict[int, list]" = {}

        # speculative acceptance accounting (bench + /health)
        self.spec_proposed = 0
        self.spec_accepted = 0

        # prompt-length buckets: the PR 4 ladder, capped at what the
        # position table and the cache can hold
        self.prompt_buckets = bucket_ladder(
            min(self.max_context, int(net.seq_len)))

        self._compiled_step = None
        self._compiled_prefill: dict = {}
        self._compiled_chunk = None
        self._compiled_draft_prefill: dict = {}
        self._compiled_draft_chunk = None
        self._compiled_draft = None
        self._compiled_verify = None
        self._compiled_handoff_export = None
        self._compiled_handoff_import = None
        self._gen_jits: dict = {}

    # -- compiled programs --------------------------------------------------
    def _step_fn(self, cache, params, tok, active, temps, rng, step):
        import jax
        from analytics_zoo_tpu.ops.sampling import sample_tokens
        cache, logits = self.net.decode_step(params, cache, tok,
                                             active=active)
        nxt = sample_tokens(jax.random.fold_in(rng, step),
                            logits.astype(jax.numpy.float32), temps,
                            self.top_k)
        return cache, nxt

    def _prefill_fn(self, cache, params, ids, plens, temps, rng,
                    step):
        import jax
        from analytics_zoo_tpu.ops.sampling import sample_tokens
        cache, logits = self.net.prefill(params, cache, ids, plens)
        nxt = sample_tokens(jax.random.fold_in(rng, step),
                            logits.astype(jax.numpy.float32), temps,
                            self.top_k)
        return cache, nxt

    def _abstract(self, tree):
        import jax
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a),
                                           np.asarray(a).dtype)
            if not hasattr(a, "aval") else
            jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

    def _chunk_fn(self, cache, params, ids, starts, n_new, temps,
                  rng, step):
        import jax
        from analytics_zoo_tpu.ops.sampling import sample_tokens
        cache, logits = self.net.forward_chunk(params, cache, ids,
                                               starts, n_new)
        nxt = sample_tokens(jax.random.fold_in(rng, step),
                            logits.astype(jax.numpy.float32), temps,
                            self.top_k)
        return cache, nxt

    def _draft_prefill_fn(self, dcache, dparams, ids, plens):
        dcache, _ = self.drafter.prefill(dparams, dcache, ids, plens)
        return dcache

    def _draft_chunk_fn(self, dcache, dparams, ids, starts, n_new):
        dcache, _ = self.drafter.forward_chunk(dparams, dcache, ids,
                                               starts, n_new)
        return dcache

    def _draft_fn(self, dcache, dparams, t0, active, temps, rng,
                  step):
        """Propose ``spec_k`` draft tokens per active slot: a scan of
        drafter decode steps, each sampling with the slot's OWN
        temperature/top_k so the proposal distribution q (returned
        per step, (S, K, V)) is exactly what `speculative_accept`
        needs. Consumes [t0, d1, …, d_{k-1}]; proposes [d1, …, dk]."""
        import jax
        from analytics_zoo_tpu.ops.sampling import (sample_tokens,
                                                    sampling_probs)
        base = jax.random.fold_in(rng, step)

        def body(carry, i):
            dcache, tok = carry
            dcache, logits = self.drafter.decode_step(
                dparams, dcache, tok, active=active)
            logits = logits.astype(jax.numpy.float32)
            nxt = sample_tokens(jax.random.fold_in(base, i), logits,
                                temps, self.top_k)
            q = sampling_probs(logits, temps, self.top_k)
            return (dcache, nxt), (nxt, q)

        (dcache, _), (drafts, qs) = jax.lax.scan(
            body, (dcache, t0),
            jax.numpy.arange(self.spec_k, dtype=jax.numpy.int32))
        return (dcache, jax.numpy.transpose(drafts, (1, 0)),
                jax.numpy.transpose(qs, (1, 0, 2)))

    def _verify_fn(self, cache, dcache, params, t0, drafts, qprobs,
                   active, temps, rng, step):
        """One compiled speculative verify: score the k drafts with
        the target in a single `forward_chunk(all_logits=True)` pass,
        run rejection sampling, and rewind BOTH caches' seq_lens to
        the accepted length. The chunk consumes [t0, d1, …, d_{k-1}]
        — exactly the tokens the drafter consumed — so target and
        drafter caches stay row-for-row in lockstep with no resync
        pass, and a full acceptance leaves ``dk`` as the pending
        token. Returns (cache, dcache, out_tokens (S, K), n_accept,
        n_emit, next_tok)."""
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.ops.sampling import (sampling_probs,
                                                    speculative_accept)
        k = self.spec_k
        toks = jnp.concatenate([t0[:, None], drafts[:, :k - 1]],
                               axis=1)
        starts = cache.seq_lens
        n_new = jnp.where(active, k, 0).astype(jnp.int32)
        cache, all_logits = self.net.forward_chunk(
            params, cache, toks, starts, n_new, all_logits=True)
        p = sampling_probs(all_logits.astype(jnp.float32),
                           jnp.broadcast_to(temps[:, None],
                                            drafts.shape),
                           self.top_k)
        n_acc, corrected = speculative_accept(
            jax.random.fold_in(rng, step), p, qprobs, drafts)
        # emitted: the accepted prefix, then (on any rejection) the
        # corrected token; a full acceptance emits all k drafts and
        # keeps dk pending — in both cases the caches hold exactly
        # the consumed tokens, so the rewind is one where()
        n_emit = jnp.minimum(n_acc + 1, k)
        idx = jnp.arange(k, dtype=jnp.int32)[None, :]
        out = jnp.where(idx < n_acc[:, None], drafts,
                        corrected[:, None])
        nxt = jnp.where(n_acc == k, drafts[:, -1], corrected)
        new_len = starts + jnp.where(active, n_emit, 0)
        cache = cache._replace(
            seq_lens=jnp.where(active, new_len, cache.seq_lens))
        dcache = dcache._replace(
            seq_lens=jnp.where(active, new_len, dcache.seq_lens))
        return cache, dcache, out, n_acc, n_emit, nxt

    def _compile(self, fn, structs, program, bucket=None,
                 donate=(0,)):
        """AOT-compile one engine program inside an
        `expected_compiles` bracket (deliberate warm/first-use
        compiles must not count toward the RecompileMonitor's storm
        window) + the usual span/counter."""
        import jax
        from analytics_zoo_tpu.common.diagnostics import \
            expected_compiles
        kw = {} if bucket is None else {"bucket": bucket}
        with expected_compiles(), \
                obs.span("decode/compile", program=program, **kw):
            compiled = jax.jit(
                fn, donate_argnums=donate).lower(*structs).compile()
        obs.counter(
            "zoo_tpu_serving_gen_compiles_total",
            help="generation programs compiled (warm-up only in "
            "steady state)", labels={"program": program}).inc()
        return compiled

    def _shape(self, *dims, dtype=np.int32):
        import jax
        return jax.ShapeDtypeStruct(tuple(dims), dtype)

    def _get_step(self):
        if self._compiled_step is None:
            s = self.max_slots
            structs = (
                self._abstract(self.cache),
                self._abstract(self.params),
                self._shape(s),
                self._shape(s, dtype=np.bool_),
                self._shape(s, dtype=np.float32),
                self._abstract(self._rng),
                self._shape(),
            )
            self._compiled_step = self._compile(
                self._step_fn, structs, "step")
        return self._compiled_step

    def _get_prefill(self, tp: int):
        fn = self._compiled_prefill.get(tp)
        if fn is None:
            s = self.max_slots
            structs = (
                self._abstract(self.cache),
                self._abstract(self.params),
                self._shape(s, tp),
                self._shape(s),
                self._shape(s, dtype=np.float32),
                self._abstract(self._rng),
                self._shape(),
            )
            fn = self._compile(self._prefill_fn, structs, "prefill",
                               bucket=tp)
            self._compiled_prefill[tp] = fn
        return fn

    def _get_chunk(self):
        if self._compiled_chunk is None:
            s, c = self.max_slots, self.prefill_chunk
            structs = (
                self._abstract(self.cache),
                self._abstract(self.params),
                self._shape(s, c),
                self._shape(s),
                self._shape(s),
                self._shape(s, dtype=np.float32),
                self._abstract(self._rng),
                self._shape(),
            )
            self._compiled_chunk = self._compile(
                self._chunk_fn, structs, "chunk")
        return self._compiled_chunk

    def _get_draft_prefill(self, tp: int):
        fn = self._compiled_draft_prefill.get(tp)
        if fn is None:
            s = self.max_slots
            structs = (
                self._abstract(self._draft_cache),
                self._abstract(self.drafter_params),
                self._shape(s, tp),
                self._shape(s),
            )
            fn = self._compile(self._draft_prefill_fn, structs,
                               "draft_prefill", bucket=tp)
            self._compiled_draft_prefill[tp] = fn
        return fn

    def _get_draft_chunk(self):
        if self._compiled_draft_chunk is None:
            s, c = self.max_slots, self.prefill_chunk
            structs = (
                self._abstract(self._draft_cache),
                self._abstract(self.drafter_params),
                self._shape(s, c),
                self._shape(s),
                self._shape(s),
            )
            self._compiled_draft_chunk = self._compile(
                self._draft_chunk_fn, structs, "draft_chunk")
        return self._compiled_draft_chunk

    def _get_draft(self):
        if self._compiled_draft is None:
            s = self.max_slots
            structs = (
                self._abstract(self._draft_cache),
                self._abstract(self.drafter_params),
                self._shape(s),
                self._shape(s, dtype=np.bool_),
                self._shape(s, dtype=np.float32),
                self._abstract(self._rng),
                self._shape(),
            )
            self._compiled_draft = self._compile(
                self._draft_fn, structs, "draft")
        return self._compiled_draft

    def _get_verify(self):
        if self._compiled_verify is None:
            s, k = self.max_slots, self.spec_k
            v = int(self.net.vocab)
            structs = (
                self._abstract(self.cache),
                self._abstract(self._draft_cache),
                self._abstract(self.params),
                self._shape(s),
                self._shape(s, k),
                self._shape(s, k, v, dtype=np.float32),
                self._shape(s, dtype=np.bool_),
                self._shape(s, dtype=np.float32),
                self._abstract(self._rng),
                self._shape(),
            )
            self._compiled_verify = self._compile(
                self._verify_fn, structs, "verify", donate=(0, 1))
        return self._compiled_verify

    def _handoff_export_fn(self, cache, page_ids):
        from analytics_zoo_tpu.ops import kv_cache as kvc
        return kvc.gather_slot_pages(cache, page_ids)

    def _handoff_import_fn(self, cache, page_ids, active, slot,
                           seq_len, k_rows, v_rows, k_srows,
                           v_srows):
        from analytics_zoo_tpu.ops import kv_cache as kvc
        return kvc.scatter_slot_pages(cache, page_ids, active, slot,
                                      seq_len, k_rows, v_rows,
                                      k_srows, v_srows)

    def _handoff_row_structs(self):
        """(k/v rows, scale rows) ShapeDtypeStructs at the FIXED
        handoff width ``pages_per_slot`` — both handoff programs are
        shape-static over the full width (unused entries masked/
        dropped), so each compiles exactly once per engine."""
        lyr, _, page, h, d = self.cache.k_pages.shape
        p = self.pages_per_slot
        rows = self._shape(lyr, p, page, h, d,
                           dtype=self.cache.k_pages.dtype)
        if self.cache.k_scales is None:
            return rows, None
        return rows, self._shape(lyr, p, page, h, dtype=np.float32)

    def _get_handoff_export(self):
        if self._compiled_handoff_export is None:
            structs = (
                self._abstract(self.cache),
                self._shape(self.pages_per_slot),
            )
            # read-only: the cache must survive the export (the
            # prefill engine keeps serving other slots), so nothing
            # is donated
            self._compiled_handoff_export = self._compile(
                self._handoff_export_fn, structs, "handoff_export",
                donate=())
        return self._compiled_handoff_export

    def _get_handoff_import(self):
        if self._compiled_handoff_import is None:
            p = self.pages_per_slot
            rows, srows = self._handoff_row_structs()
            structs = (
                self._abstract(self.cache),
                self._shape(p),
                self._shape(p, dtype=np.bool_),
                self._shape(),
                self._shape(),
                rows, rows, srows, srows,
            )
            self._compiled_handoff_import = self._compile(
                self._handoff_import_fn, structs, "handoff_import")
        return self._compiled_handoff_import

    def _warmed(self) -> int:
        return (bool(self._compiled_step)
                + len(self._compiled_prefill)
                + bool(self._compiled_chunk)
                + len(self._compiled_draft_prefill)
                + bool(self._compiled_draft_chunk)
                + bool(self._compiled_draft)
                + bool(self._compiled_verify)
                + bool(self._compiled_handoff_export)
                + bool(self._compiled_handoff_import))

    def warm(self) -> int:
        """AOT-compile every program steady-state serving can need —
        the decode step, every prompt bucket's prefill (plus the
        drafter's, under speculation), the chunk programs (under
        chunked prefill), and the draft/verify pair — so the serving
        loop never compiles under traffic (the DynamicBatcher
        bucket-warm discipline). Returns the number of programs
        compiled this call. Idempotent."""
        n0 = self._warmed()
        # role-gated: a prefill-pool engine never decodes (its only
        # steady-state programs are prefill/chunk + handoff export);
        # a decode-pool engine never sees a raw prompt (step + handoff
        # import). Monolithic "both" engines skip the handoff pair —
        # they never hand off, so they never pay those compiles.
        if self.role != "prefill":
            self._get_step()
        if self.role != "decode":
            for tp in self.prompt_buckets:
                self._get_prefill(tp)
            if self.prefill_chunk > 0:
                self._get_chunk()
        if self.role == "prefill":
            self._get_handoff_export()
        if self.role == "decode":
            self._get_handoff_import()
        if self.spec_k > 0 and self.drafter is not None:
            self._get_draft()
            self._get_verify()
            if self.prefill_chunk > 0:
                self._get_draft_chunk()
            # prompts that fit in one chunk admit through the
            # bucket-padded path even when chunking is on (the
            # batcher routes them directly), so the drafter's
            # prefill buckets are steady-state programs regardless
            for tp in self.prompt_buckets:
                self._get_draft_prefill(tp)
        return self._warmed() - n0

    # -- admission / stepping / retirement ----------------------------------
    def pages_for(self, prompt_len: int, max_new: int) -> int:
        """Worst-case page reservation for one request (prompt +
        max_new tokens, capped at the context window)."""
        from analytics_zoo_tpu.ops.kv_cache import PageAllocator
        return PageAllocator.pages_needed(
            min(prompt_len + max_new, self.max_context),
            self.page_size)

    def can_admit(self, prompt_len: int, max_new: int) -> bool:
        """Whether a request of this size fits RIGHT NOW: a free slot
        and enough free pages for its worst case. Pages are reserved
        in full at admission (prompt + max_new tokens), so an admitted
        sequence can always run to completion — no mid-decode
        eviction, no allocation deadlock."""
        return bool(self.free_slots) and self.allocator.can_alloc(
            self.pages_for(prompt_len, max_new))

    def admit(self, requests: "Sequence[tuple]") -> "list[tuple]":
        """Admit ``[(prompt_ids, max_new, temperature), ...]`` into
        free slots of the LIVE batch: assign pages, write the table
        rows, run ONE bucket-padded prefill (slots not being admitted
        pass ``prompt_lens == 0`` and are untouched — the property
        `prefill` guarantees), and sample each new slot's first
        token. Returns ``[(slot, first_token), ...]``. Raises
        MemoryError when slots/pages run out mid-list (callers gate
        with :meth:`can_admit` per request first)."""
        import jax
        from analytics_zoo_tpu.ops.kv_cache import PageAllocator
        if not requests:
            return []
        for prompt_ids, _, _ in requests:
            if not 1 <= len(prompt_ids) <= self.max_context - 1:
                raise ValueError(
                    f"prompt length {len(prompt_ids)} outside [1, "
                    f"{self.max_context - 1}]")
        tp = max(len(r[0]) for r in requests)
        tp = next(b for b in self.prompt_buckets if b >= tp)
        ids_arr = np.zeros((self.max_slots, tp), np.int32)
        plens = np.zeros((self.max_slots,), np.int32)
        admitted = []
        for prompt_ids, max_new, temperature in requests:
            slot = self._claim_slot(prompt_ids, max_new, temperature)
            n = len(prompt_ids)
            ids_arr[slot, :n] = np.asarray(prompt_ids, np.int32)
            plens[slot] = n
            admitted.append(slot)
        self._push_table()
        fn = self._get_prefill(tp)
        self.cache, toks = fn(self.cache, self.params, ids_arr,
                              plens, self._temps, self._rng,
                              np.int32(self._step_id))
        self._step_id += 1
        if self._draft_cache is not None:
            dfn = self._get_draft_prefill(tp)
            self._draft_cache = dfn(self._draft_cache,
                                    self.drafter_params, ids_arr,
                                    plens)
        toks = np.asarray(toks)
        out = []
        for slot in admitted:
            self._last_tok[slot] = toks[slot]
            out.append((slot, int(toks[slot])))
        return out

    def _claim_slot(self, prompt_ids, max_new, temperature) -> int:
        """Allocate pages + a slot + its table row for one request
        (shared by whole-prompt and chunked admission)."""
        from analytics_zoo_tpu.ops.kv_cache import PageAllocator
        n = len(prompt_ids)
        need = PageAllocator.pages_needed(
            min(n + int(max_new), self.max_context), self.page_size)
        if not self.free_slots:
            raise MemoryError("no free decode slot")
        pages = self.allocator.alloc(need)  # MemoryError if short
        slot = min(self.free_slots)
        self.free_slots.discard(slot)
        self._slot_pages[slot] = pages
        row = np.full((self.pages_per_slot,), pages[-1], np.int32)
        row[:need] = pages
        self._table[slot] = row
        self._temps[slot] = float(temperature)
        return slot

    def _push_table(self):
        """Publish the host table to BOTH device caches (the drafter
        mirrors the target's page placement by construction). Each
        cache gets its OWN device copy: the compiled programs donate
        whole cache pytrees, and a buffer shared across the two would
        be deleted under the survivor's feet."""
        import jax
        self.cache = self.cache._replace(
            page_table=jax.numpy.array(self._table))
        if self._draft_cache is not None:
            self._draft_cache = self._draft_cache._replace(
                page_table=jax.numpy.array(self._table))

    # -- chunked prefill ----------------------------------------------------
    def admit_partial(self, requests: "Sequence[tuple]"
                      ) -> "list[int]":
        """Chunked admission: assign each request a slot, pages and a
        table row — but run NO forward pass. The prompt is parked in
        the chunk scheduler and :meth:`prefill_step` feeds it to the
        cache ``prefill_chunk`` tokens at a time, interleaved with
        decode iterations by the batcher. Returns the slots (first
        tokens arrive from the prefill_step that lands each prompt's
        final chunk). Same gating contract as :meth:`admit`."""
        if self.prefill_chunk <= 0:
            raise ValueError("admit_partial needs prefill_chunk > 0")
        for prompt_ids, _, _ in requests:
            if not 1 <= len(prompt_ids) <= self.max_context - 1:
                raise ValueError(
                    f"prompt length {len(prompt_ids)} outside [1, "
                    f"{self.max_context - 1}]")
        slots = []
        for prompt_ids, max_new, temperature in requests:
            slot = self._claim_slot(prompt_ids, max_new, temperature)
            self._pending_prompts[slot] = [
                np.asarray(prompt_ids, np.int32), 0]
            slots.append(slot)
        if slots:
            self._push_table()
        return slots

    @property
    def prefilling_slots(self) -> "set[int]":
        """Slots admitted via :meth:`admit_partial` whose prompts are
        not yet fully cached (must NOT take decode steps)."""
        return set(self._pending_prompts)

    def cancel_prefill(self, slot: int):
        """Drop a mid-prefill slot (drain/cancel): forget its pending
        prompt; the caller releases pages via :meth:`release` as
        usual. Rows its finished chunks wrote are dead — seq_lens
        stops advancing and a future occupant overwrites them."""
        self._pending_prompts.pop(slot, None)

    def prefill_step(self) -> "list[tuple]":
        """Advance every prefilling slot by ONE chunk (at most
        ``prefill_chunk`` prompt tokens) through the compiled chunk
        program. Slots whose final chunk just landed sample their
        first token: returns ``[(slot, first_token), ...]`` for
        exactly those. No-op ([]) when nothing is prefilling."""
        if not self._pending_prompts:
            return []
        c = self.prefill_chunk
        ids_arr = np.zeros((self.max_slots, c), np.int32)
        starts = np.zeros((self.max_slots,), np.int32)
        n_new = np.zeros((self.max_slots,), np.int32)
        finishing = []
        for slot, st in self._pending_prompts.items():
            ids, off = st
            n = min(c, len(ids) - off)
            ids_arr[slot, :n] = ids[off:off + n]
            starts[slot] = off
            n_new[slot] = n
            if off + n >= len(ids):
                finishing.append(slot)
        fn = self._get_chunk()
        self.cache, toks = fn(self.cache, self.params, ids_arr,
                              starts, n_new, self._temps, self._rng,
                              np.int32(self._step_id))
        self._step_id += 1
        if self._draft_cache is not None:
            dfn = self._get_draft_chunk()
            self._draft_cache = dfn(self._draft_cache,
                                    self.drafter_params, ids_arr,
                                    starts, n_new)
        toks = np.asarray(toks)
        out = []
        for slot in list(self._pending_prompts):
            if slot in finishing:
                del self._pending_prompts[slot]
            else:
                self._pending_prompts[slot][1] += int(n_new[slot])
        for slot in finishing:
            self._last_tok[slot] = toks[slot]
            out.append((slot, int(toks[slot])))
        return out

    def step(self, active: np.ndarray) -> np.ndarray:
        """One decode iteration over the WHOLE slot array: append each
        active slot's last token to the cache, attend, sample. Slots
        with ``active == False`` are frozen (nothing written, lengths
        unchanged). Returns the ``(max_slots,)`` sampled tokens —
        meaningful only at active slots."""
        _STEP_FAULT.fire()
        fn = self._get_step()
        active = np.asarray(active, np.bool_)
        self.cache, toks = fn(self.cache, self.params,
                              self._last_tok, active, self._temps,
                              self._rng, np.int32(self._step_id))
        self._step_id += 1
        toks = np.asarray(toks)
        self._last_tok = np.where(active, toks, self._last_tok
                                  ).astype(np.int32)
        return toks

    def spec_step(self, active: np.ndarray):
        """One speculative round over the active slots: draft
        ``spec_k`` tokens with the drafter (one compiled scan), then
        verify them against the target in one compiled chunk pass
        with rejection sampling. Returns ``(out_tokens (S, K),
        n_emit (S,))`` — slot s emitted ``out_tokens[s, :n_emit[s]]``
        this round (1..K tokens; inactive slots emit 0). Callers must
        only include slots whose remaining token budget AND context
        window can absorb K tokens (the batcher gates this)."""
        _STEP_FAULT.fire()
        active = np.asarray(active, np.bool_)
        dfn, vfn = self._get_draft(), self._get_verify()
        self._draft_cache, drafts, qprobs = dfn(
            self._draft_cache, self.drafter_params, self._last_tok,
            active, self._temps, self._rng, np.int32(self._step_id))
        self._step_id += 1
        (self.cache, self._draft_cache, out, n_acc, n_emit,
         nxt) = vfn(self.cache, self._draft_cache, self.params,
                    self._last_tok, drafts, qprobs, active,
                    self._temps, self._rng, np.int32(self._step_id))
        self._step_id += 1
        out, nxt = np.asarray(out), np.asarray(nxt)
        n_emit = np.where(active, np.asarray(n_emit), 0)
        self._last_tok = np.where(active, nxt, self._last_tok
                                  ).astype(np.int32)
        n_active = int(active.sum())
        self.spec_proposed += self.spec_k * n_active
        self.spec_accepted += int(
            np.asarray(n_acc)[active].sum()) if n_active else 0
        return out, n_emit

    def release(self, slot: int):
        """Retire a slot: reclaim its pages and return it to the free
        pool. The cache rows need no reset — a future `prefill` with
        ``prompt_lens > 0`` overwrites ``seq_lens``, and until then
        the ``active`` mask keeps the slot frozen. A slot still
        mid-chunked-prefill is cancelled (its pending prompt
        dropped), so cancel/drain leaks neither pages nor scheduler
        state."""
        self._pending_prompts.pop(slot, None)
        pages = self._slot_pages.pop(slot, None)
        if pages:
            self.allocator.free(pages)
        self.free_slots.add(slot)

    # -- prefill/decode handoff ---------------------------------------------
    def export_handoff(self, slot: int) -> dict:
        """Extract an active slot's cache state into a handoff blob
        and retire the slot (pages reclaimed immediately — the
        prefill pool's capacity frees the moment the blob exists;
        exactly-once on a lost blob is the router's job, via
        re-prefill from the original prompt). The blob carries the
        used pages of every layer (int8 scales included), the
        position, the last sampled token, and the slot's sampling
        temperature — everything :meth:`admit_from_handoff` needs to
        resume decode token-exactly with NO forward pass."""
        import jax
        from analytics_zoo_tpu.ops import kv_cache as kvc
        if slot in self._pending_prompts:
            raise ValueError(
                f"slot {slot} is still mid-chunked-prefill")
        if slot in self.free_slots:
            raise ValueError(f"slot {slot} is not active")
        seq_len = int(np.asarray(self.cache.seq_lens)[slot])
        if seq_len <= 0:
            raise ValueError(f"slot {slot} has no cached tokens")
        n_used = kvc.PageAllocator.pages_needed(seq_len,
                                                self.page_size)
        fn = self._get_handoff_export()
        k, v, k_s, v_s = fn(self.cache,
                            jax.numpy.asarray(self._table[slot]))
        blob = {
            "version": kvc.HANDOFF_VERSION,
            "seq_len": seq_len,
            "page_size": self.page_size,
            "kv_dtype": np.dtype(self.cache.k_pages.dtype).name,
            "num_layers": int(self.cache.k_pages.shape[0]),
            "heads": int(self.cache.k_pages.shape[3]),
            "head_dim": int(self.cache.k_pages.shape[4]),
            "last_token": int(self._last_tok[slot]),
            "temperature": float(self._temps[slot]),
            "k": np.asarray(k)[:, :n_used].copy(),
            "v": np.asarray(v)[:, :n_used].copy(),
            "k_scales": (None if k_s is None
                         else np.asarray(k_s)[:, :n_used].copy()),
            "v_scales": (None if v_s is None
                         else np.asarray(v_s)[:, :n_used].copy()),
        }
        self.release(slot)
        return blob

    def _check_handoff_blob(self, blob: dict):
        from analytics_zoo_tpu.ops import kv_cache as kvc
        if int(blob.get("version", -1)) != kvc.HANDOFF_VERSION:
            raise ValueError(
                f"handoff version {blob.get('version')!r} != "
                f"{kvc.HANDOFF_VERSION}")
        mine = {
            "page_size": self.page_size,
            "kv_dtype": np.dtype(self.cache.k_pages.dtype).name,
            "num_layers": int(self.cache.k_pages.shape[0]),
            "heads": int(self.cache.k_pages.shape[3]),
            "head_dim": int(self.cache.k_pages.shape[4]),
        }
        for key, want in mine.items():
            if blob.get(key) != want:
                raise ValueError(
                    f"handoff {key} mismatch: blob has "
                    f"{blob.get(key)!r}, engine has {want!r}")
        seq_len = int(blob["seq_len"])
        if not 1 <= seq_len <= self.max_context - 1:
            raise ValueError(
                f"handoff seq_len {seq_len} outside [1, "
                f"{self.max_context - 1}]")

    def admit_from_handoff(self, blob: dict, max_new: int) -> int:
        """Splice a handoff blob into this engine: claim a slot +
        pages (the same worst-case reservation :meth:`admit` makes,
        with the blob's position standing in for the prompt length),
        scatter the shipped pages into the freshly allocated physical
        pages, and restore the resume state — NO forward pass runs.
        The very next :meth:`step` with this slot active appends the
        blob's ``last_token`` and continues the stream token-exactly.
        Validation happens before any allocation, so a rejected blob
        leaves the engine untouched (the router refunds it to a
        sibling). Returns the claimed slot."""
        import jax
        from analytics_zoo_tpu.ops import kv_cache as kvc
        self._check_handoff_blob(blob)
        seq_len = int(blob["seq_len"])
        n_used = kvc.PageAllocator.pages_needed(seq_len,
                                                self.page_size)
        need = kvc.PageAllocator.pages_needed(
            min(seq_len + int(max_new), self.max_context),
            self.page_size)
        if not self.free_slots:
            raise MemoryError("no free decode slot")
        pages = self.allocator.alloc(need)  # MemoryError if short
        slot = min(self.free_slots)
        self.free_slots.discard(slot)
        self._slot_pages[slot] = pages
        row = np.full((self.pages_per_slot,), pages[-1], np.int32)
        row[:need] = pages
        self._table[slot] = row
        self._temps[slot] = float(blob["temperature"])
        self._push_table()
        p = self.pages_per_slot
        active = np.zeros((p,), np.bool_)
        active[:n_used] = True

        def pad(a):
            if a is None:
                return None
            out = np.zeros((a.shape[0], p) + a.shape[2:], a.dtype)
            out[:, :n_used] = a
            return out

        fn = self._get_handoff_import()
        self.cache = fn(self.cache, jax.numpy.asarray(row), active,
                        np.int32(slot), np.int32(seq_len),
                        pad(blob["k"]), pad(blob["v"]),
                        pad(blob["k_scales"]),
                        pad(blob["v_scales"]))
        self._last_tok[slot] = int(blob["last_token"])
        return slot

    @property
    def slots_active(self) -> int:
        return self.max_slots - len(self.free_slots)

    @property
    def free_pages(self) -> int:
        return self.allocator.free_pages

    # -- sequential whole-loop path -----------------------------------------
    def generate(self, prompts, max_new_tokens: int = 32, *,
                 temperature: float = 0.0, eos_id=None, rng=None
                 ) -> "list[np.ndarray]":
        """Per-request compiled generation: the model's whole-loop
        `generate` (prefill + `lax.while_loop`), jit-cached per
        (batch, prompt-bucket, max_new) shape. This is the SEQUENTIAL
        baseline — each call owns a fresh cache and runs to
        completion; concurrent traffic should go through the
        continuous batcher instead. Returns one array of NEWLY
        generated token ids per prompt (eos, when hit, included)."""
        import jax
        if prompts and np.isscalar(prompts[0]):
            prompts = [prompts]
        s = len(prompts)
        tp = max(len(p) for p in prompts)
        tp = next((b for b in self.prompt_buckets if b >= tp), tp)
        max_new = int(max_new_tokens)
        ids = np.zeros((s, tp), np.int32)
        plens = np.zeros((s,), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = np.asarray(p, np.int32)
            plens[i] = len(p)
        key = (s, tp, max_new, eos_id)
        fn = self._gen_jits.get(key)
        if fn is None:
            net, tk = self.net, self.top_k
            ps, cd = self.page_size, self.cache_dtype

            def run(params, ids, plens, temps, rng):
                return net.generate(
                    params, ids, prompt_lens=plens,
                    max_new_tokens=max_new, temperature=temps,
                    top_k=tk, eos_id=eos_id, rng=rng,
                    page_size=ps, cache_dtype=cd)

            fn = jax.jit(run)
            self._gen_jits[key] = fn
        temps = np.full((s,), float(temperature), np.float32)
        buf, lens = fn(self.params, ids, plens, temps,
                       self._rng if rng is None else rng)
        buf, lens = np.asarray(buf), np.asarray(lens)
        return [buf[i, plens[i]:lens[i]] for i in range(s)]

    def stats(self) -> dict:
        """JSON-able summary for ``GET /health``."""
        out = {
            "role": self.role,
            "max_slots": self.max_slots,
            "slots_active": self.slots_active,
            "max_context": self.max_context,
            "page_size": self.page_size,
            "free_pages": self.free_pages,
            "total_pages": self.allocator.max_pages,
            "prompt_buckets": list(self.prompt_buckets),
            "warmed_programs": self._warmed(),
            "kv_dtype": np.dtype(self.cache.k_pages.dtype).name,
            "prefill_chunk": self.prefill_chunk,
            "spec_k": self.spec_k,
        }
        if self.spec_k > 0:
            out["spec_proposed"] = self.spec_proposed
            out["spec_accepted"] = self.spec_accepted
            out["spec_accept_rate"] = (
                self.spec_accepted / self.spec_proposed
                if self.spec_proposed else None)
        return out

    def __repr__(self):
        return (f"GenerationEngine(slots={self.max_slots}, "
                f"context={self.max_context}, "
                f"page_size={self.page_size}, "
                f"free_pages={self.free_pages})")
