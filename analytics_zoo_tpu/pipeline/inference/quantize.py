"""Post-training INT8 quantization for serving.

Reference: the BigDL white paper's headline serving claim — INT8
quantized inference with ~2x speedup, 4x model-size reduction, <0.1%
accuracy drop (`/root/reference/docs/docs/wp-bigdl.md:192-196`,
SSD/VGG16/VGG19 on CPU via MKL int8 GEMM).

TPU-native redesign: symmetric int8 quantization mapped onto the MXU —
`lax.dot_general` / `lax.conv_general_dilated` accept int8 operands
with `preferred_element_type=int32`, which XLA lowers to the MXU's
native 8-bit multiply / 32-bit accumulate path (2× the bf16 MAC rate
on v5e). Scheme:

- weights: per-output-channel symmetric int8 (`w ≈ w_q · s_w`);
- activations: per-tensor symmetric int8, scale calibrated as the
  max-|x| each quantized layer sees over a calibration batch (the
  reference's calibration-data flow);
- matmul/conv accumulate in int32, one fused rescale
  (`s_x · s_w`) back to float, then bias + activation as usual.

By default only Dense layers are quantized: measured on TPU v5e
(2026-07-30), XLA lowers int8 `dot_general` to the MXU's 8-bit path
(1.2x over bf16 at 4096³) but int8 `conv_general_dilated` does NOT
take the fast path (0.65x vs bf16 at VGG-shape 3x3 convs, making a
full int8 VGG16 0.48x) — so conv quantization is opt-in via
``quantize_types`` (still valuable for the 4x weight-size reduction;
top-1 agreement measured at 1.000 on VGG16). The reference's 2x
serving speedup is a CPU/VNNI result (`wp-bigdl.md:192-196`); the
TPU-honest equivalents are bf16 serving + int8 Dense layers.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.common.nncontext import logger


def _quantize_per_channel(w: np.ndarray, channel_axis: int):
    """Symmetric per-channel int8: returns (w_q int8, scale f32 with
    singleton dims except channel_axis)."""
    reduce_axes = tuple(a for a in range(w.ndim) if a != channel_axis)
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = (amax / 127.0).astype(np.float32)
    scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
    w_q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return w_q, scale


def _quantize_activation(x, scale):
    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)


class QuantizedModel:
    """A Sequential with its Dense/Conv2D layers swapped for int8
    kernels (reference `InferenceModel` quantized load path)."""

    def __init__(self, model, params, calibration_inputs,
                 quantize_types=("Dense",)):
        from analytics_zoo_tpu.pipeline.api.keras.models import \
            Sequential
        if not isinstance(model, Sequential):
            raise TypeError(
                "quantization requires a Sequential model (got "
                f"{type(model).__name__})")
        self.model = model
        self.params = jax.device_get(params)
        self._plan: List[Dict[str, Any]] = []
        self._calibrate(calibration_inputs, quantize_types)

    # -- calibration --------------------------------------------------------
    def _calibrate(self, calibration_inputs, quantize_types) -> None:
        x = jnp.asarray(np.asarray(calibration_inputs, np.float32))
        n_q = 0
        for layer in self.model.layers:
            p = self.params.get(layer.name, {})
            tname = type(layer).__name__
            entry: Dict[str, Any] = {"layer": layer, "mode": "float"}
            if tname in quantize_types and "kernel" in p:
                kernel = np.asarray(p["kernel"])
                # kernel layouts: Dense (in, out) / conv HWIO — the
                # output channel is always the LAST axis
                w_q, w_scale = _quantize_per_channel(
                    kernel, kernel.ndim - 1)
                a_scale = float(np.max(np.abs(np.asarray(x)))) / 127.0
                entry.update(mode="int8", w_q=w_q,
                             w_scale=w_scale.reshape(-1),
                             a_scale=np.float32(a_scale or 1.0))
                n_q += 1
            self._plan.append(entry)
            x = layer.call(p, x, training=False)
        logger.info("quantize: %d/%d layers int8",
                    n_q, len(self.model.layers))

    # -- forward ------------------------------------------------------------
    def forward(self, x):
        for entry in self._plan:
            layer = entry["layer"]
            p = self.params.get(layer.name, {})
            if entry["mode"] == "float":
                x = layer.call(p, x, training=False)
                continue
            x = self._int8_layer(entry, layer, p, x)
        return x

    def __call__(self, x):
        return self.forward(x)

    def _int8_layer(self, entry, layer, p, x):
        a_scale = entry["a_scale"]
        w_q = entry["w_q"]
        w_scale = entry["w_scale"]
        x_q = _quantize_activation(x, a_scale)
        tname = type(layer).__name__
        if tname == "Dense":
            acc = jax.lax.dot_general(
                x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * (a_scale * w_scale)
            if layer.bias:
                y = y + p["bias"]
        else:  # Convolution2D
            acc = jax.lax.conv_general_dilated(
                x_q, w_q,
                window_strides=layer.subsample,
                padding=layer.border_mode.upper(),
                rhs_dilation=layer.dilation,
                dimension_numbers=layer._dn(),
                preferred_element_type=jnp.int32)
            scale = a_scale * w_scale
            if layer.dim_ordering == "tf":
                y = acc.astype(jnp.float32) * scale
                if layer.bias:
                    y = y + p["bias"]
            else:
                shape = (1, -1) + (1,) * layer.ndim
                y = acc.astype(jnp.float32) * scale.reshape(shape)
                if layer.bias:
                    y = y + p["bias"].reshape(shape)
        if getattr(layer, "activation", None) is not None:
            y = layer.activation(y)
        return y

    # -- introspection ------------------------------------------------------
    @property
    def n_quantized(self) -> int:
        return sum(1 for e in self._plan if e["mode"] == "int8")

    def size_bytes(self) -> "tuple[int, int]":
        """(float_bytes, int8_bytes) of the quantized kernels — the
        reference's 4x model-size-reduction metric."""
        f = q = 0
        for e in self._plan:
            if e["mode"] == "int8":
                f += e["w_q"].size * 4
                q += e["w_q"].size + e["w_scale"].size * 4
        return f, q
