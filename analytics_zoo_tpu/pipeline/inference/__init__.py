from analytics_zoo_tpu.pipeline.inference.inference_model import (
    InferenceModel)
from analytics_zoo_tpu.pipeline.inference.serving import InferenceServer

__all__ = ["InferenceModel", "InferenceServer"]
