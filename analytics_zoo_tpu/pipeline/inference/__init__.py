from analytics_zoo_tpu.pipeline.inference.batching import (
    ContinuousBatcher, DynamicBatcher)
from analytics_zoo_tpu.pipeline.inference.fleet import (
    FleetRouter, HttpReplica, Replica, ReplicaPool,
    make_fleet_server)
from analytics_zoo_tpu.pipeline.inference.generation import (
    GenerationEngine)
from analytics_zoo_tpu.pipeline.inference.inference_model import (
    InferenceModel)
from analytics_zoo_tpu.pipeline.inference.registry import (
    ModelRegistry, ModelVersion, RolloutController)
from analytics_zoo_tpu.pipeline.inference.serving import (
    InferenceServer, make_inference_server)

__all__ = ["InferenceModel", "InferenceServer", "DynamicBatcher",
           "ContinuousBatcher", "GenerationEngine",
           "make_inference_server",
           "ReplicaPool", "Replica", "HttpReplica", "FleetRouter",
           "make_fleet_server",
           "ModelRegistry", "ModelVersion", "RolloutController"]
