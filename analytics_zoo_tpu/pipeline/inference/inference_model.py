"""InferenceModel (L9): thread-safe serving wrapper.

Reference: `Z/pipeline/inference/InferenceModel.scala:29-120` — a
`LinkedBlockingQueue` of `supportedConcurrentNum` weight-sharing model
copies with loaders for BigDL/Caffe/TF/OpenVINO backends.

TPU-native redesign:
- the blocking pool is the native C++ queue (`native/serving_queue.cpp`),
  holding slot ids; each slot is a *compiled executable* reference —
  XLA-compiled programs are reentrant, so slots share one executable
  (the exact analog of the reference's weight-sharing clones,
  `FloatModel.scala:73-87`);
- OpenVINO's accelerated-inference role is played by XLA ahead-of-time
  compilation: `load_*` lowers + compiles the forward at load time for
  the declared input shapes;
- TF models load via a frozen `tf.function` bridged into XLA
  (`jax2tf.call_tf`) — the TFNet serving path without a JNI session.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.common.nncontext import get_nncontext, logger
from analytics_zoo_tpu.native import make_serving_queue


class InferenceModel:
    def __init__(self, supported_concurrent_num: int = 1):
        self.supported_concurrent_num = int(supported_concurrent_num)
        self._queue = make_serving_queue()
        self._predict_fn: Optional[Callable] = None
        self._compiled = False
        self._lock = threading.Lock()
        self.quantized = None  # QuantizedModel when loaded with int8

    # -- loaders ------------------------------------------------------------
    def _install(self, predict_fn: Callable,
                 example_inputs: Optional[Sequence[np.ndarray]] = None):
        import jax
        fn = jax.jit(predict_fn)
        if example_inputs is not None:
            # AOT-compile for the declared shapes (the OpenVINO-IR role)
            fn = fn.lower(*example_inputs).compile()
        self._predict_fn = fn
        for slot in range(self.supported_concurrent_num):
            self._queue.put(slot)
        self._compiled = example_inputs is not None

    def load(self, model_path: str,
             example_inputs: Optional[Sequence] = None,
             quantize: bool = False):
        """Load a saved ZooModel (`ZooModel.save_model` output) —
        the `doLoad` BigDL path. ``quantize=True`` serves int8 (the
        reference's quantized-inference claim, wp-bigdl.md:192-196;
        requires example_inputs for calibration)."""
        from analytics_zoo_tpu.models.common import ZooModel
        zm = ZooModel.load_model(model_path)
        return self.load_keras_net(zm.model,
                                   example_inputs=example_inputs,
                                   quantize=quantize)

    def load_keras_net(self, net, params=None,
                       example_inputs: Optional[Sequence] = None,
                       quantize: bool = False,
                       quantize_types: Optional[Sequence[str]] = None):
        """Serve an in-memory KerasNet; ``quantize=True`` swaps Dense
        kernels for int8 (MXU 8-bit path) calibrated on
        ``example_inputs``. ``quantize_types`` widens the layer set
        (e.g. ``("Dense", "Convolution2D")`` — conv int8 is measured
        slower than bf16 on v5e but 4x smaller; see
        `inference/quantize.py`)."""
        if params is None:
            est = net.estimator
            if est.params is None:
                est._ensure_initialized()
            params = est.params

        if quantize:
            if example_inputs is None:
                raise ValueError(
                    "quantize=True needs example_inputs for "
                    "activation-scale calibration")
            from analytics_zoo_tpu.pipeline.inference.quantize import \
                QuantizedModel
            kw = {} if quantize_types is None else \
                {"quantize_types": tuple(quantize_types)}
            qm = QuantizedModel(net, params,
                                np.asarray(example_inputs[0]), **kw)
            self.quantized = qm

            def predict_fn(*xs):
                return qm.forward(xs[0] if len(xs) == 1 else list(xs))
        else:
            self.quantized = None

            def predict_fn(*xs):
                x = list(xs) if len(xs) > 1 else xs[0]
                return net.forward(params, x, training=False)

        self._install(predict_fn,
                      None if example_inputs is None
                      else [np.asarray(e) for e in example_inputs])
        return self

    def load_tf(self, saved_model_path: str,
                example_inputs: Optional[Sequence] = None,
                signature: str = "serving_default"):
        """TF SavedModel → XLA (the `doLoadTF` path,
        InferenceModel.scala:69, without the TFNet JNI session)."""
        from analytics_zoo_tpu.pipeline.api.net import TFNet
        net = TFNet.from_saved_model(saved_model_path,
                                     signature=signature)

        def predict_fn(*xs):
            return net(*xs)

        self._install(predict_fn,
                      None if example_inputs is None
                      else [np.asarray(e) for e in example_inputs])
        return self

    def load_openvino(self, *args, **kwargs):
        raise NotImplementedError(
            "OpenVINO's role (ahead-of-time compiled serving) is played "
            "by XLA AOT here: use load/load_tf with example_inputs to "
            "pre-compile")

    # -- predict ------------------------------------------------------------
    def predict(self, inputs, timeout_ms: int = -1):
        """Take a slot from the pool, run, return the slot (reference
        `doPredict` contract)."""
        if self._predict_fn is None:
            raise RuntimeError("no model loaded")
        slot = self._queue.take(timeout_ms)
        if slot < 0:
            raise TimeoutError(
                f"no free model slot within {timeout_ms}ms "
                f"(concurrency={self.supported_concurrent_num})")
        try:
            xs = (inputs if isinstance(inputs, (list, tuple))
                  else [inputs])
            # device-resident inputs pass straight to a jit fn —
            # np.asarray would round-trip them through the host. The
            # AOT path (example_inputs) keeps the conversion: its
            # executable pins the example arrays' layout, which a
            # committed/sharded caller array need not match.
            xs = [x if isinstance(x, jax.Array)
                  and not self._compiled else np.asarray(x)
                  for x in xs]
            out = self._predict_fn(*xs)
            if isinstance(out, (list, tuple)):
                return [np.asarray(o) for o in out]
            return np.asarray(out)
        finally:
            self._queue.put(slot)

    @property
    def concurrent_slots_free(self) -> int:
        return self._queue.size()

    def __repr__(self):
        return (f"InferenceModel(concurrency="
                f"{self.supported_concurrent_num}, "
                f"loaded={self._predict_fn is not None}, "
                f"aot={self._compiled})")
