"""InferenceModel (L9): thread-safe serving wrapper.

Reference: `Z/pipeline/inference/InferenceModel.scala:29-120` — a
`LinkedBlockingQueue` of `supportedConcurrentNum` weight-sharing model
copies with loaders for BigDL/Caffe/TF/OpenVINO backends.

TPU-native redesign:
- the blocking pool is the native C++ queue (`native/serving_queue.cpp`),
  holding slot ids; each slot is a *compiled executable* reference —
  XLA-compiled programs are reentrant, so slots share one executable
  (the exact analog of the reference's weight-sharing clones,
  `FloatModel.scala:73-87`);
- OpenVINO's accelerated-inference role is played by XLA ahead-of-time
  compilation: `load_*` lowers + compiles the forward at load time for
  the declared input shapes;
- TF models load via a frozen `tf.function` bridged into XLA
  (`jax2tf.call_tf`) — the TFNet serving path without a JNI session.
"""

from __future__ import annotations

import json
import threading
import zipfile
from typing import Callable, Optional, Sequence, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common.nncontext import logger
from analytics_zoo_tpu.native import make_serving_queue

_ARTIFACT_VERSION = 1


def _tree_spec(skel) -> dict:
    """JSON-able structure spec of a pytree SKELETON (leaves are
    ints). The artifact stores this instead of pickled PyTreeDefs so
    the tree metadata adds no unpickling surface of its own. NOTE the
    executable blob itself still deserializes through jax's
    pickle-based loader — see the trust-model note on
    :meth:`InferenceModel.load_compiled`."""
    if isinstance(skel, tuple):
        return {"t": "tuple", "c": [_tree_spec(c) for c in skel]}
    if isinstance(skel, list):
        return {"t": "list", "c": [_tree_spec(c) for c in skel]}
    if isinstance(skel, dict):
        keys = sorted(skel)
        return {"t": "dict", "k": keys,
                "c": [_tree_spec(skel[k]) for k in keys]}
    if skel is None:
        return {"t": "none"}
    return {"t": "leaf"}


def _tree_from_spec(spec: dict):
    t = spec["t"]
    if t == "tuple":
        return tuple(_tree_from_spec(c) for c in spec["c"])
    if t == "list":
        return [_tree_from_spec(c) for c in spec["c"]]
    if t == "dict":
        return {k: _tree_from_spec(c)
                for k, c in zip(spec["k"], spec["c"])}
    if t == "none":
        return None
    return 0


class InferenceModel:
    def __init__(self, supported_concurrent_num: int = 1):
        self.supported_concurrent_num = int(supported_concurrent_num)
        self._queue = make_serving_queue()
        self._predict_fn: Optional[Callable] = None
        self._export_src: Optional[Tuple] = None
        self._compiled = False
        self._trace_fn: Optional[Callable] = None
        self._example_specs = None  # [(shape, np.dtype)] when known
        self._generation = 0
        self._lock = threading.Lock()
        self.quantized = None  # QuantizedModel when loaded with int8
        self._generator = None  # GenerationEngine via load_generator

    # -- loaders ------------------------------------------------------------
    def _install(self, predict_fn: Callable,
                 example_inputs: Optional[Sequence[np.ndarray]] = None,
                 export_state: Optional[Tuple] = None):
        import jax
        jfn = jax.jit(predict_fn)
        fn = jfn
        if example_inputs is not None:
            # AOT-compile for the declared shapes (the OpenVINO-IR role)
            fn = jfn.lower(*example_inputs).compile()
        # kept for export_compiled: ``(params_pytree, pure_fn)`` —
        # the pure form lets export re-commit the weights to ONE
        # device and stage a single-device artifact program,
        # independent of this process's mesh (a serving process is
        # one chip; a program lowered against mesh-committed params
        # would demand the exporter's device count from every loader)
        specs = None
        if example_inputs is not None:
            specs = [(tuple(np.shape(e)), np.asarray(e).dtype)
                     for e in example_inputs]
        self._swap_model(fn, compiled=example_inputs is not None,
                         export_src=(export_state, example_inputs),
                         trace_fn=jfn, example_specs=specs)

    def _swap_model(self, fn, compiled: bool, export_src,
                    trace_fn=None, example_specs=None):
        """Atomically install (fn, compiled-flag, fresh slot pool):
        predict() snapshots all three under the same lock, so a
        reload can never pair a new executable with a stale
        conversion flag. The queue is REPLACED, not drained —
        draining could not reclaim slots held by in-flight predicts,
        whose returns would then inflate the pool; a stale slot lands
        in the retired queue and is forgotten. (Predicts that took a
        slot from the retired queue finish against the old fn; for
        one reload window total concurrency may transiently exceed
        the contract by those stragglers.)"""
        q = make_serving_queue()
        for slot in range(self.supported_concurrent_num):
            q.put(slot)
        with self._lock:
            self._predict_fn = fn
            self._compiled = compiled
            self._export_src = export_src
            self._trace_fn = trace_fn
            self._example_specs = example_specs
            self._generation += 1
            self._queue = q

    def load(self, model_path: str,
             example_inputs: Optional[Sequence] = None,
             quantize: bool = False):
        """Load a saved ZooModel (`ZooModel.save_model` output) —
        the `doLoad` BigDL path. ``quantize=True`` serves int8 (the
        reference's quantized-inference claim, wp-bigdl.md:192-196;
        requires example_inputs for calibration)."""
        from analytics_zoo_tpu.models.common import ZooModel
        zm = ZooModel.load_model(model_path)
        return self.load_keras_net(zm.model,
                                   example_inputs=example_inputs,
                                   quantize=quantize)

    def load_keras_net(self, net, params=None,
                       example_inputs: Optional[Sequence] = None,
                       quantize: bool = False,
                       quantize_types: Optional[Sequence[str]] = None):
        """Serve an in-memory KerasNet; ``quantize=True`` swaps Dense
        kernels for int8 (MXU 8-bit path) calibrated on
        ``example_inputs``. ``quantize_types`` widens the layer set
        (e.g. ``("Dense", "Convolution2D")`` — conv int8 is measured
        slower than bf16 on v5e but 4x smaller; see
        `inference/quantize.py`)."""
        if params is None:
            est = net.estimator
            if est.params is None:
                est._ensure_initialized()
            params = est.params

        if quantize:
            if example_inputs is None:
                raise ValueError(
                    "quantize=True needs example_inputs for "
                    "activation-scale calibration")
            from analytics_zoo_tpu.pipeline.inference.quantize import \
                QuantizedModel
            kw = {} if quantize_types is None else \
                {"quantize_types": tuple(quantize_types)}
            qm = QuantizedModel(net, params,
                                np.asarray(example_inputs[0]), **kw)
            self.quantized = qm

            def predict_fn(*xs):
                return qm.forward(xs[0] if len(xs) == 1 else list(xs))
            export_state = None  # int8 tables live inside qm
        else:
            self.quantized = None

            def predict_fn(*xs):
                x = list(xs) if len(xs) > 1 else xs[0]
                return net.forward(params, x, training=False)

            def pure_fn(p, *xs):
                x = list(xs) if len(xs) > 1 else xs[0]
                return net.forward(p, x, training=False)
            export_state = (params, pure_fn)

        self._install(predict_fn,
                      None if example_inputs is None
                      else [np.asarray(e) for e in example_inputs],
                      export_state=export_state)
        return self

    def load_tf(self, saved_model_path: str,
                example_inputs: Optional[Sequence] = None,
                signature: str = "serving_default"):
        """TF SavedModel → XLA (the `doLoadTF` path,
        InferenceModel.scala:69, without the TFNet JNI session)."""
        from analytics_zoo_tpu.pipeline.api.net import TFNet
        net = TFNet.from_saved_model(saved_model_path,
                                     signature=signature)

        def predict_fn(*xs):
            return net(*xs)

        self._install(predict_fn,
                      None if example_inputs is None
                      else [np.asarray(e) for e in example_inputs])
        return self

    def load_openvino(self, model_path: str, weight_path=None,
                      **kwargs):
        """Deprecated delegating shim (reference
        `InferenceModel.scala:69-120` `doLoadOpenVINO`): the
        OpenVINO-IR role — an on-disk ahead-of-time compiled serving
        artifact any process can load — is played by
        :meth:`export_compiled` / :meth:`load_compiled` XLA bundles.
        ``model_path`` must point at an ``export_compiled`` artifact;
        ``weight_path`` is ignored (weights are embedded).

        TRUST MODEL: migrated call sites must know the error surface
        changed — an OpenVINO IR load fails safely on a bad file, but
        this shim delegates to :meth:`load_compiled`, whose
        executable blob deserializes through jax's pickle-based
        loader and runs with the loader's privileges. Load artifacts
        only from sources you trust."""
        import warnings
        warnings.warn(
            "load_openvino is deprecated on the TPU-native stack; "
            "pass an export_compiled() artifact (delegating to "
            "load_compiled — which deserializes the executable blob "
            "through jax's pickle-based loader: load artifacts only "
            "from sources you trust)", DeprecationWarning,
            stacklevel=2)
        return self.load_compiled(model_path)

    # -- serialized AOT artifact (the OpenVINO-IR role) ---------------------
    def export_compiled(self, path: str) -> str:
        """Write the AOT-compiled serving program to ``path`` (a zip
        bundle) that another process loads with :meth:`load_compiled`
        and serves WITHOUT recompiling — the on-disk-IR property of
        the reference's OpenVINO backend
        (`OpenVinoInferenceSupportive.scala:69-155`).

        The bundle carries two encodings:
        - ``executable.bin``: the serialized XLA executable (weights
          embedded as program constants) — loads with zero
          compilation on a machine/backend matching the exporter;
        - ``export.bin``: the portable ``jax.export`` StableHLO blob —
          the cross-machine fallback, compiled once at load time
          (still no Python model code or retracing needed).

        Requires a model loaded with ``example_inputs`` (AOT)."""
        from jax.experimental import serialize_executable as se

        if not self._compiled or self._export_src is None or \
                self._export_src[1] is None:
            raise RuntimeError(
                "export_compiled needs a model loaded with "
                "example_inputs (the AOT pre-compile path)")
        export_state, examples = self._export_src
        if export_state is None:
            raise NotImplementedError(
                "export_compiled supports load/load_keras_net models "
                "(quantized and call_tf-bridged programs embed state "
                "the exporter cannot re-stage single-device yet)")
        params, pure_fn = export_state
        # the ARTIFACT program is staged single-device: a serving
        # process is one chip, and a program lowered against this
        # process's mesh (training params are often replicated across
        # it) would demand the same device count from every loader.
        # Re-committing the weights to one device is what makes the
        # lowering single-device; the in-memory pool (_predict_fn)
        # keeps its mesh-aware form.
        dev = jax.devices()[0]
        p1 = jax.device_put(
            params, jax.sharding.SingleDeviceSharding(dev))

        def fn1(*xs):
            return pure_fn(p1, *xs)

        sjit = jax.jit(fn1)
        with jax.default_device(dev):
            payload, in_tree, out_tree = se.serialize(
                sjit.lower(*examples).compile())
        in_skel = jax.tree_util.tree_unflatten(
            in_tree, list(range(in_tree.num_leaves)))
        out_skel = jax.tree_util.tree_unflatten(
            out_tree, list(range(out_tree.num_leaves)))
        from jax import export as jexport
        # the portable blob is lowered for the exporter's platform
        # AND cpu, so a cpu serving box can still load a TPU-exported
        # artifact (the axon tunnel backend lowers as tpu)
        backend = jax.default_backend()
        plats = list(dict.fromkeys(
            ["tpu" if backend == "axon" else backend, "cpu"]))
        try:
            exported = jexport.export(sjit, platforms=plats)(*examples)
        except Exception:  # multi-platform lowering unsupported here
            plats = [plats[0]]  # the canonical (axon->tpu) name
            exported = jexport.export(sjit)(*examples)
        export_blob = exported.serialize()
        # batch-polymorphic variant (leading dim symbolic): lets a
        # loading process re-specialize the program for OTHER batch
        # sizes — what DynamicBatcher's bucket warming needs from a
        # load_compiled model. Optional: not every program lowers
        # under a symbolic batch dim.
        poly_blob = None
        try:
            (b,) = jexport.symbolic_shape("b")
            pargs = [jax.ShapeDtypeStruct(
                (b,) + tuple(np.shape(e))[1:],
                np.asarray(e).dtype) for e in examples]
            poly_blob = jexport.export(
                sjit, platforms=plats)(*pargs).serialize()
        except Exception as e:
            logger.info("batch-polymorphic export unavailable "
                        "(%s: %s); artifact serves its declared "
                        "batch only", type(e).__name__, e)
        meta = {
            "version": _ARTIFACT_VERSION,
            "platform": jax.default_backend(),
            "export_platforms": plats,
            "jax_version": jax.__version__,
            "n_devices": 1,
            "in_spec": _tree_spec(in_skel),
            "out_spec": _tree_spec(out_skel),
            "inputs": [{"shape": list(np.shape(e)),
                        "dtype": str(np.asarray(e).dtype)}
                       for e in examples],
        }
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("meta.json", json.dumps(meta))
            z.writestr("executable.bin", payload)
            z.writestr("export.bin", export_blob)
            if poly_blob is not None:
                z.writestr("export_poly.bin", poly_blob)
        logger.info("exported compiled serving artifact -> %s "
                    "(%d inputs, platform=%s)", path,
                    len(meta["inputs"]), meta["platform"])
        return path

    def load_compiled(self, path: str):
        """Load an :meth:`export_compiled` bundle and serve it. On a
        matching machine/backend the serialized executable loads
        directly — NO compilation, no tracing, no model code; on a
        different one the portable ``jax.export`` blob is compiled
        once for the declared shapes (lowered at export for the
        exporter's platform and cpu).

        TRUST MODEL: like any executable format (an OpenVINO IR, a
        shared library), a bundle runs with the loader's privileges —
        the executable blob deserializes through jax's pickle-based
        loader. Load artifacts only from sources you trust."""
        from jax.experimental import serialize_executable as se

        with zipfile.ZipFile(path, "r") as z:
            meta = json.loads(z.read("meta.json").decode())
            exec_blob = z.read("executable.bin")
            export_blob = z.read("export.bin")
            poly_blob = (z.read("export_poly.bin")
                         if "export_poly.bin" in z.namelist()
                         else None)
        if meta.get("version", 0) > _ARTIFACT_VERSION:
            raise ValueError(
                f"artifact version {meta.get('version')} is newer "
                f"than this runtime's {_ARTIFACT_VERSION}")
        in_tree = jax.tree_util.tree_structure(
            _tree_from_spec(meta["in_spec"]))
        out_tree = jax.tree_util.tree_structure(
            _tree_from_spec(meta["out_spec"]))
        n_dev = int(meta.get("n_devices", 1))
        trace_fn = None
        try:
            try:
                # execution_devices defaults to ALL of the backend's
                # devices — a single-device artifact must load onto
                # exactly the device count it was compiled for
                fn = se.deserialize_and_load(
                    exec_blob, in_tree, out_tree,
                    execution_devices=jax.devices()[:n_dev])
            except TypeError:
                # older jax (<=0.4.x): no execution_devices kwarg —
                # the payload itself carries the exporter's
                # single-device assignment
                fn = se.deserialize_and_load(
                    exec_blob, in_tree, out_tree)
            mode = "aot"
        except Exception as e:
            backend = jax.default_backend()
            cur = "tpu" if backend == "axon" else backend
            plats = meta.get("export_platforms", [meta["platform"]])
            if cur not in plats:
                raise ValueError(
                    f"artifact was exported for platform(s) {plats}; "
                    f"this process runs {backend} — re-export on a "
                    f"matching backend") from e
            logger.warning(
                "serialized executable not loadable here (%s: %s); "
                "compiling the portable export blob once",
                type(e).__name__, e)
            from jax import export as jexport
            exp = jexport.deserialize(export_blob)
            args = [jax.ShapeDtypeStruct(tuple(i["shape"]),
                                         np.dtype(i["dtype"]))
                    for i in meta["inputs"]]
            fn = jax.jit(exp.call).lower(*args).compile()
            mode = "export"
        if poly_blob is not None:
            # the batch-polymorphic program re-specializes for other
            # batch sizes — DynamicBatcher's bucket warming path
            try:
                from jax import export as jexport
                trace_fn = jax.jit(
                    jexport.deserialize(poly_blob).call)
            except Exception as e:
                logger.warning(
                    "polymorphic export blob unusable here (%s: %s);"
                    " serving the declared batch size only",
                    type(e).__name__, e)
        self.quantized = None     # any prior int8 load is replaced
        # export_src None: re-export needs a source model
        specs = [(tuple(i["shape"]), np.dtype(i["dtype"]))
                 for i in meta["inputs"]]
        self._swap_model(fn, compiled=True, export_src=None,
                         trace_fn=trace_fn, example_specs=specs)
        logger.info("loaded compiled serving artifact %s (mode=%s)",
                    path, mode)
        return self

    # -- predict ------------------------------------------------------------
    def predict(self, inputs, timeout_ms: int = -1):
        """Take a slot from the pool, run, return the slot (reference
        `doPredict` contract)."""
        # consistent snapshot (fn, conversion flag, queue): a reload
        # mid-predict must not mix generations (see _swap_model)
        with self._lock:
            predict_fn = self._predict_fn
            compiled = self._compiled
            queue = self._queue
        if predict_fn is None:
            raise RuntimeError("no model loaded")
        slot = queue.take(timeout_ms)
        if slot < 0:
            obs.counter("zoo_tpu_serving_errors_total",
                        help="serving errors by kind",
                        labels={"kind": "slot_timeout"}).inc()
            raise TimeoutError(
                f"no free model slot within {timeout_ms}ms "
                f"(concurrency={self.supported_concurrent_num})")
        try:
            xs = (inputs if isinstance(inputs, (list, tuple))
                  else [inputs])
            # device-resident inputs pass straight to a jit fn —
            # np.asarray would round-trip them through the host. The
            # AOT path (example_inputs) keeps the conversion: its
            # executable pins the example arrays' layout, which a
            # committed/sharded caller array need not match.
            xs = [x if isinstance(x, jax.Array)
                  and not compiled else np.asarray(x)
                  for x in xs]
            bdim = np.shape(xs[0])
            obs.histogram("zoo_tpu_serving_batch_size",
                          help="predict batch size (leading dim)",
                          buckets=obs.SIZE_BUCKETS).observe(
                bdim[0] if bdim else 1)
            with obs.span("serving/predict"):
                out = predict_fn(*xs)
                if isinstance(out, (list, tuple)):
                    return [np.asarray(o) for o in out]
                return np.asarray(out)
        finally:
            queue.put(slot)

    # -- generation (pipeline/inference/generation.py) ----------------------
    def load_generator(self, net, params=None, **engine_kwargs):
        """Attach an autoregressive decode engine for ``net`` (a
        transformer-style stack exposing ``init_kv_cache / prefill /
        decode_step / generate`` — `pipeline/api/keras/layers/
        transformer.py`). Orthogonal to the ``load_*`` predict path:
        a model can serve ``/predict`` and ``/generate`` at once, and
        loading a generator does not invalidate warmed predict
        buckets. ``engine_kwargs`` forward to
        :class:`~analytics_zoo_tpu.pipeline.inference.generation.
        GenerationEngine` (``max_slots``, ``max_context``,
        ``page_size``, ``top_k``, ``cache_dtype``,
        ``prefill_chunk``, ``spec_k`` — env-defaulted,
        docs/perf_flags.md). For speculative decoding pass
        ``drafter=`` (a smaller net sharing the vocabulary);
        ``drafter_params`` defaults to the drafter's own estimator
        params the same way ``params`` defaults to ``net``'s."""
        from analytics_zoo_tpu.pipeline.inference.generation import \
            GenerationEngine

        def _params_of(n, explicit):
            if explicit is not None:
                return explicit
            est = n.estimator
            if est.params is None:
                est._ensure_initialized()
            return est.params

        params = _params_of(net, params)
        drafter = engine_kwargs.get("drafter")
        if drafter is not None:
            engine_kwargs["drafter_params"] = _params_of(
                drafter, engine_kwargs.get("drafter_params"))
        self._generator = GenerationEngine(net, params,
                                           **engine_kwargs)
        return self

    @property
    def generator(self):
        """The attached GenerationEngine, or None — how the serving
        front-ends decide whether to mount ``/generate``."""
        return self._generator

    def generate(self, prompts, max_new_tokens: int = 32, *,
                 temperature: float = 0.0, eos_id=None):
        """Sequential per-request generation: one compiled whole-loop
        program per (batch, prompt-bucket, budget) shape — the
        baseline the continuous batcher is benchmarked against
        (`scripts/bench_generate.py`). ``prompts``: one token-id list
        or a list of them. Returns a list of 1-D arrays of newly
        generated ids."""
        if self._generator is None:
            raise RuntimeError(
                "no generator loaded; call load_generator(net) first")
        return self._generator.generate(
            prompts, max_new_tokens=max_new_tokens,
            temperature=temperature, eos_id=eos_id)

    # -- dynamic-batching hooks (pipeline/inference/batching.py) ------------
    @property
    def generation(self) -> int:
        """Bumped on every model (re)load — lets DynamicBatcher
        invalidate its per-bucket executable cache on reload."""
        return self._generation

    @property
    def can_relower(self) -> bool:
        """Whether the loaded model keeps a traceable form that can
        be AOT-lowered for NEW input shapes (bucket warming). False
        only for ``load_compiled`` artifacts without a
        batch-polymorphic export blob."""
        return self._trace_fn is not None

    @property
    def example_input_specs(self):
        """``[(shape, np.dtype), ...]`` of the declared example
        inputs (load-time ``example_inputs`` or a compiled artifact's
        manifest), or ``None`` when the model was loaded without
        shape declarations."""
        with self._lock:
            specs = self._example_specs
        return None if specs is None else list(specs)

    def lower_for(self, example_args: Sequence):
        """AOT-lower-and-compile the loaded forward for exactly the
        given arguments (arrays or ``jax.ShapeDtypeStruct``) and
        return the compiled executable — the primitive DynamicBatcher
        uses to warm its bucket ladder. The executable is NOT
        installed; :meth:`predict` is unaffected."""
        with self._lock:
            fn = self._trace_fn
        if fn is None:
            raise RuntimeError(
                "model cannot be re-lowered for new shapes (a "
                "load_compiled artifact without a batch-polymorphic "
                "export blob, or no model loaded)")
        return fn.lower(*example_args).compile()

    @property
    def concurrent_slots_free(self) -> int:
        return self._queue.size()

    def __repr__(self):
        return (f"InferenceModel(concurrency="
                f"{self.supported_concurrent_num}, "
                f"loaded={self._predict_fn is not None}, "
                f"aot={self._compiled})")
