"""Replicated serving fleet: a router over N model replicas.

The reference platform's headline serving capability was Cluster
Serving — distributed inference served by a *fleet*, not one process
(SURVEY §2.11, PAPER.md). This module is that front door for the TPU
port: a :class:`ReplicaPool` owning N model replicas (one per device,
or one per multi-device mesh slice for models too big for a chip —
the `parallel/mesh.py` inference path), and a :class:`FleetRouter`
dispatching requests across them::

    clients ──HTTP──► front-end (serving.py)
                          │ handle_predict
                          ▼
                     FleetRouter        least-outstanding-rows, or
                      │  │  │           consistent-hash affinity
              ┌───────┘  │  └───────┐
              ▼          ▼          ▼
          Replica r0  Replica r1  Replica r2     each: OWN
          DynamicBatcher + InferenceModel        bucket ladder,
          (devices[0])  (devices[1]) (dev[2])    OWN AOT warmup

Design notes:

* **Layering.** The router duck-types BOTH the model surface
  (``predict`` / ``example_input_specs`` / ``concurrent_slots_free``)
  and the batcher surface (``batchable`` / ``submit`` / ``stats`` /
  ``start`` / ``stop``), so the existing front-ends serve a fleet
  unchanged: ``InferenceServer(router, batcher=router)``. Each
  replica keeps its own :class:`DynamicBatcher` — per-queue EMA,
  per-queue ladder, per-queue warmup — the router only picks which
  queue a request joins.
* **Exactly-once for acked work.** ``submit`` returns a router-level
  future. A replica that dies mid-request fails *its own* future;
  the router then re-dispatches those rows to a sibling (bounded by
  ``ZOO_TPU_FLEET_MAX_RETRIES``, the dead replica excluded). Rows
  whose future already resolved are never re-executed — the router
  future resolves exactly once.
* **Lifecycle.** admitting → (failures ≥ ``ZOO_TPU_FLEET_EJECT_``
  ``AFTER``) → down, with exponential-backoff re-admission probes;
  or admitting → draining (stop admitting, flush in-flight, stop the
  batcher) → drained → restart (re-warm; a model reload bumps
  ``InferenceModel.generation`` so stale bucket executables drop).
* **Backpressure.** One full replica queue just steers traffic to a
  sibling. When EVERY admitting replica is full, the router raises
  :class:`FleetSaturatedError` carrying the *minimum* Retry-After
  EMA hint across the fleet — the shared ``handle_predict`` maps it
  to HTTP 503 + ``Retry-After`` like any queue-full.
* **Tracing.** Dispatch/retry spans join the ambient request trace
  (``X-Zoo-Trace-Id``); in-process replicas inherit it through the
  batcher's submit-time capture, HTTP replicas forward the header.

Env config (read at construction; kwargs override — see
docs/perf_flags.md):

``ZOO_TPU_FLEET_REPLICAS``              fleet size (default: one per
                                        device slice)
``ZOO_TPU_FLEET_DEVICES_PER_REPLICA``   devices per mesh slice (1)
``ZOO_TPU_FLEET_POLICY``                least_loaded | hash
``ZOO_TPU_FLEET_MAX_RETRIES``           sibling retries (2)
``ZOO_TPU_FLEET_EJECT_AFTER``           consecutive failures → down
``ZOO_TPU_FLEET_BACKOFF_S``             first re-admission delay (1)
``ZOO_TPU_FLEET_BACKOFF_MAX_S``         backoff ceiling (30)
``ZOO_TPU_FLEET_PROBE_S``               health-prober interval (2;
                                        <= 0 → manual ``tick()``)
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.common import diagnostics
from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common import tracing
from analytics_zoo_tpu.common.nncontext import logger
from analytics_zoo_tpu.pipeline.inference.batching import (
    ContinuousBatcher,
    DeadlineExpiredError,
    DynamicBatcher,
    QueueFullError,
)

# chaos hook: armed via ZOO_TPU_FAULTS or tests (docs/robustness.md);
# fires on every dispatch to an in-process replica with
# ctx {replica: name}, so a fault can target one replica by name —
# "kill" exercises ejection + sibling retry, "delay" a straggler,
# "corrupt" a replica returning garbage
_PREDICT_FAULT = faults.point("fleet/replica_predict")

__all__ = [
    "Replica",
    "HttpReplica",
    "ReplicaPool",
    "ReplicaContext",
    "FleetRouter",
    "FleetSaturatedError",
    "ReplicaUnavailableError",
    "make_fleet_server",
    "DisaggReplica",
    "HttpDisaggReplica",
    "DisaggRouter",
]

# replica lifecycle states (fleet_status()/debug surfaces)
STARTING = "starting"
ADMITTING = "admitting"
DRAINING = "draining"
DRAINED = "drained"
DOWN = "down"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class FleetSaturatedError(QueueFullError):
    """Every admitting replica's queue is at capacity. Subclasses
    :class:`QueueFullError` so the shared ``handle_predict`` maps it
    onto HTTP 503 + ``Retry-After`` unchanged; ``retry_after_s`` is
    the MINIMUM EMA drain hint across the fleet (the soonest any
    queue frees up)."""

    def __init__(self, replicas: int, retry_after_s: float):
        Exception.__init__(
            self,
            f"all {replicas} admitting replica queues are full; "
            f"retry in ~{retry_after_s:.2f}s")
        self.retry_after_s = retry_after_s
        self.replicas = replicas


class ReplicaUnavailableError(QueueFullError):
    """The fleet has no admitting replica (all down or draining).
    Also a 503 — capacity returns when backoff probes re-admit a
    replica, so ``retry_after_s`` carries the soonest probe."""

    def __init__(self, retry_after_s: float):
        Exception.__init__(
            self,
            f"no admitting replica in the fleet; retry in "
            f"~{retry_after_s:.2f}s")
        self.retry_after_s = retry_after_s


# -- metric handles (naming contract: docs/observability.md) ------------------

def _g_admitting():
    return obs.gauge("zoo_tpu_fleet_replicas_admitting",
                     help="replicas currently accepting traffic")


def _g_size():
    return obs.gauge("zoo_tpu_fleet_replicas_total",
                     help="replicas in the pool (any state)")


def _g_up(name: str):
    return obs.gauge("zoo_tpu_fleet_replica_up",
                     help="1 while the replica admits traffic",
                     labels={"replica": name})


def _g_outstanding(name: str):
    return obs.gauge("zoo_tpu_fleet_outstanding_rows",
                     help="rows dispatched to the replica and not "
                          "yet resolved",
                     labels={"replica": name})


def _c_dispatch(name: str):
    return obs.counter("zoo_tpu_fleet_dispatches_total",
                       help="requests dispatched, by replica",
                       labels={"replica": name})


def _c_requests():
    return obs.counter("zoo_tpu_fleet_requests_total",
                       help="requests entering the router")


def _c_failed():
    return obs.counter("zoo_tpu_fleet_requests_failed_total",
                       help="router requests that ultimately failed")


def _c_retries():
    return obs.counter("zoo_tpu_fleet_retries_total",
                       help="dispatches retried on a sibling replica")


def _c_saturated():
    return obs.counter("zoo_tpu_fleet_saturated_total",
                       help="requests rejected with every replica "
                            "queue full")


def _c_ejections(name: str):
    return obs.counter("zoo_tpu_fleet_ejections_total",
                       help="replica ejections (marked down)",
                       labels={"replica": name})


def _c_readmissions(name: str):
    return obs.counter("zoo_tpu_fleet_readmissions_total",
                       help="replicas re-admitted after backoff",
                       labels={"replica": name})


# per-version cohort metrics (the rollout layer's observability
# contract, docs/robustness.md): every replica completion is
# attributed to the model VERSION that served it, so a canary
# cohort's error/latency profile separates cleanly from the baseline

def _c_cohort_requests(version: str):
    return obs.counter("zoo_tpu_rollout_requests_total",
                       help="replica completions by model version "
                            "(canary cohort attribution)",
                       labels={"version": version})


def _c_cohort_errors(version: str):
    return obs.counter("zoo_tpu_rollout_errors_total",
                       help="replica failures by model version "
                            "(canary cohort attribution)",
                       labels={"version": version})


def _h_cohort_latency(version: str):
    return obs.histogram("zoo_tpu_rollout_latency_seconds",
                         help="dispatch-to-resolve latency by model "
                              "version",
                         labels={"version": version})


# per-REPLICA dispatch accounting (the skew detector's input,
# docs/observability.md): the router measures dispatch-to-resolve
# for every replica — in-process or HTTP — so the federation
# collector can window these uniformly across transports

def _h_replica_latency(name: str):
    return obs.histogram("zoo_tpu_fleet_replica_latency_seconds",
                         help="dispatch-to-resolve latency by "
                              "replica (skew detection input)",
                         labels={"replica": name})


def _c_replica_errors(name: str):
    return obs.counter("zoo_tpu_fleet_replica_errors_total",
                       help="dispatch failures attributed to a "
                            "replica (skew detection input)",
                       labels={"replica": name})


class ReplicaContext:
    """What a :class:`ReplicaPool` ``model_fn`` receives: the
    replica's index, name, and the device slice it owns."""

    def __init__(self, index: int, name: str, devices: Sequence):
        self.index = int(index)
        self.name = name
        self.devices = tuple(devices)

    def __repr__(self):
        return (f"ReplicaContext({self.name}, "
                f"devices={[str(d) for d in self.devices]})")


class _ReplicaBase:
    """Shared replica state machine + accounting. Subclasses provide
    transport (`Replica` in-process, `HttpReplica` remote)."""

    def __init__(self, name: str, clock: Callable[[], float]):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self.state = STARTING
        # what this replica serves: "predict" (the classic fleet),
        # or a disaggregated generation pool role ("prefill" /
        # "decode" / "both") — surfaced on /debug/fleet so operators
        # can see pool imbalance
        self.role = "predict"
        # model version this replica serves (cohort label; the
        # rollout controller rewrites it across a warm-swap)
        self.version = "v0"
        self.down_reason: Optional[str] = None
        self.outstanding_rows = 0
        self.consecutive_failures = 0
        self.failures_total = 0
        self.dispatches_total = 0
        self._backoff_base = _env_float("ZOO_TPU_FLEET_BACKOFF_S",
                                        1.0)
        self._backoff_max = _env_float("ZOO_TPU_FLEET_BACKOFF_MAX_S",
                                       30.0)
        self.backoff_s = self._backoff_base
        self.next_probe_at = 0.0  # clock() time of next revival try
        _g_outstanding(name).set(0)
        _g_up(name).set(0)

    # -- state ---------------------------------------------------------------
    def admitting(self) -> bool:
        with self._lock:
            return self.state == ADMITTING

    def _set_admitting(self):
        with self._lock:
            self.state = ADMITTING
            self.down_reason = None
            self.consecutive_failures = 0
            self.backoff_s = self._backoff_base
        _g_up(self.name).set(1)

    def mark_down(self, reason: str,
                  now: Optional[float] = None) -> bool:
        """admitting/draining → down. Schedules the first revival
        probe one backoff from now. Returns False when already
        down."""
        now = self._clock() if now is None else now
        with self._lock:
            if self.state == DOWN:
                return False
            self.state = DOWN
            self.down_reason = reason
            self.next_probe_at = now + self.backoff_s
        _g_up(self.name).set(0)
        _c_ejections(self.name).inc()
        diagnostics.anomaly("fleet_replica_down", replica=self.name,
                            reason=reason)
        logger.warning("fleet: replica %s marked down (%s)",
                       self.name, reason)
        return True

    def backoff_bump(self, now: float):
        """A revival probe failed: double the backoff (capped) and
        schedule the next probe."""
        with self._lock:
            self.backoff_s = min(self.backoff_s * 2.0,
                                 self._backoff_max)
            self.next_probe_at = now + self.backoff_s

    # -- accounting (router-driven) ------------------------------------------
    def note_dispatch(self, rows: int):
        with self._lock:
            self.outstanding_rows += rows
            self.dispatches_total += 1
            out = self.outstanding_rows
        _g_outstanding(self.name).set(out)
        _c_dispatch(self.name).inc()

    def note_done(self, rows: int):
        with self._lock:
            self.outstanding_rows = max(
                0, self.outstanding_rows - rows)
            out = self.outstanding_rows
        _g_outstanding(self.name).set(out)

    def note_success(self):
        with self._lock:
            self.consecutive_failures = 0

    def note_failure(self) -> int:
        """Count one dispatch failure; returns the consecutive-failure
        count (the router ejects past its threshold)."""
        with self._lock:
            self.consecutive_failures += 1
            self.failures_total += 1
            return self.consecutive_failures

    # -- introspection -------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            st = {
                "name": self.name,
                "state": self.state,
                "role": self.role,
                "version": self.version,
                "outstanding_rows": self.outstanding_rows,
                "consecutive_failures": self.consecutive_failures,
                "failures_total": self.failures_total,
                "dispatches_total": self.dispatches_total,
                "backoff_s": self.backoff_s,
            }
            if self.down_reason:
                st["down_reason"] = self.down_reason
        st["batcher"] = self.batcher_stats()
        return st

    # -- transport surface (subclass responsibility) -------------------------
    def start(self):
        raise NotImplementedError

    def stop(self):
        raise NotImplementedError

    def batchable(self, xs) -> bool:
        raise NotImplementedError

    def submit(self, xs) -> "Future":
        raise NotImplementedError

    def predict(self, inputs, timeout_ms: int = -1):
        raise NotImplementedError

    def probe(self) -> bool:
        raise NotImplementedError

    def retry_hint_s(self) -> float:
        return 0.05

    def batcher_stats(self) -> dict:
        return {"enabled": False}

    def slots_free(self) -> int:
        return 1

    def concurrency(self) -> int:
        return 1

    def input_specs(self):
        return None


class Replica(_ReplicaBase):
    """One in-process replica: a model (usually an
    :class:`InferenceModel` with params committed to this replica's
    device slice) plus its OWN :class:`DynamicBatcher` — own bounded
    queue, own bucket ladder, own AOT warmup, gauges labelled
    ``{replica=<name>}``."""

    def __init__(self, name: str, model, batcher="auto",
                 batcher_kwargs: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(name, clock)
        self.model = model
        if batcher == "auto":
            if os.environ.get("ZOO_TPU_SERVING_BATCH", "1") == "0":
                self.batcher = None
            else:
                kw = dict(batcher_kwargs or {})
                kw.setdefault("labels", {"replica": name})
                self.batcher = DynamicBatcher(model, **kw)
        else:
            self.batcher = batcher

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Replica":
        """Warm the bucket ladder and begin admitting. Idempotent."""
        if self.batcher is not None:
            self.batcher.start()
        self._set_admitting()
        return self

    def stop(self):
        if self.batcher is not None:
            self.batcher.stop()
        with self._lock:
            self.state = DOWN
            self.down_reason = "stopped"
        _g_up(self.name).set(0)

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful drain: stop admitting (the router skips
        non-admitting replicas), flush everything in flight (the
        batcher executes its queued entries before its dispatcher
        exits), then park in ``drained``. Returns True when fully
        flushed within ``timeout`` (wall clock — draining waits on
        real threads)."""
        with self._lock:
            if self.state == DOWN:
                return True
            self.state = DRAINING
        _g_up(self.name).set(0)
        deadline = time.monotonic() + timeout
        if self.batcher is not None:
            self.batcher.stop(timeout=timeout)
        while time.monotonic() < deadline:
            with self._lock:
                if self.outstanding_rows == 0:
                    break
            time.sleep(0.005)
        with self._lock:
            flushed = self.outstanding_rows == 0
            self.state = DRAINED
        obs.event("fleet/drained", replica=self.name,
                  flushed=flushed)
        return flushed

    def restart(self) -> "Replica":
        """Bring a drained replica back: restart the batcher (its
        bucket cache re-validates against ``model.generation``, so a
        reload in between serves fresh executables) and resume
        admitting."""
        if self.batcher is not None:
            self.batcher.start()
        self._set_admitting()
        return self

    # -- transport -----------------------------------------------------------
    def batchable(self, xs) -> bool:
        return self.batcher is not None and self.batcher.batchable(xs)

    def submit(self, xs) -> "Future":
        _PREDICT_FAULT.fire(replica=self.name)
        return self.batcher.submit(xs)

    def predict(self, inputs, timeout_ms: int = -1):
        _PREDICT_FAULT.fire(replica=self.name)
        if timeout_ms is not None and timeout_ms > 0:
            out = self.model.predict(inputs, timeout_ms=timeout_ms)
        else:
            out = self.model.predict(inputs)
        return _PREDICT_FAULT.corrupt(out, replica=self.name)

    def probe(self) -> bool:
        """One predict at the declared example shape through the
        per-request path (bypasses the batcher queue; AOT-compiled
        models only accept that exact shape) to prove the replica
        serves again before re-admission."""
        try:
            specs = getattr(self.model, "example_input_specs", None)
            if specs:
                xs = [np.zeros(tuple(shape), np.dtype(dt))
                      for shape, dt in specs]
                self.model.predict(xs if len(xs) > 1 else xs[0])
            return True
        except Exception as e:
            logger.info("fleet: probe failed on %s: %s",
                        self.name, e)
            return False

    def retry_hint_s(self) -> float:
        if self.batcher is not None:
            return self.batcher.retry_hint_s()
        return 0.05

    def batcher_stats(self) -> dict:
        if self.batcher is None:
            return {"enabled": False}
        return self.batcher.stats()

    def slots_free(self) -> int:
        return int(getattr(self.model, "concurrent_slots_free", 1))

    def concurrency(self) -> int:
        return int(getattr(self.model,
                           "supported_concurrent_num", 1))

    def input_specs(self):
        return getattr(self.model, "example_input_specs", None)


class HttpReplica(_ReplicaBase):
    """A replica living in another process behind the standard HTTP
    front-end (the Cluster-Serving shape: router node + worker
    nodes). ``submit`` POSTs ``/predict`` with the ambient trace id
    in ``X-Zoo-Trace-Id`` so one trace id spans router dispatch →
    remote queue/pad/execute; remote 503/504 map back onto
    :class:`QueueFullError` / :class:`DeadlineExpiredError` and ride
    the same retry/backpressure paths as in-process replicas.

    JSON carries no dtype, so remote replicas serve single-output
    float32 models; heterogeneous fleets should keep int-input
    models in-process."""

    def __init__(self, url: str, name: Optional[str] = None,
                 timeout_s: float = 30.0, workers: int = 4,
                 clock: Callable[[], float] = time.monotonic):
        self.url = url.rstrip("/")
        if name is None:
            name = self.url.split("//", 1)[-1].replace(
                "/", "_").replace(":", "_")
        super().__init__(name, clock)
        self.timeout_s = float(timeout_s)
        self._workers = int(workers)
        self._pool: Optional[ThreadPoolExecutor] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "HttpReplica":
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix=f"zoo-fleet-{self.name}")
        self._set_admitting()
        return self

    def stop(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        with self._lock:
            self.state = DOWN
            self.down_reason = "stopped"
        _g_up(self.name).set(0)

    def drain(self, timeout: float = 30.0) -> bool:
        with self._lock:
            if self.state == DOWN:
                return True
            self.state = DRAINING
        _g_up(self.name).set(0)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.outstanding_rows == 0:
                    break
            time.sleep(0.005)
        with self._lock:
            flushed = self.outstanding_rows == 0
            self.state = DRAINED
        return flushed

    def restart(self) -> "HttpReplica":
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix=f"zoo-fleet-{self.name}")
        self._set_admitting()
        return self

    # -- transport -----------------------------------------------------------
    def batchable(self, xs) -> bool:
        # the remote front-end re-batches for itself; anything
        # row-aligned can ride the future path
        if not xs or not all(isinstance(x, np.ndarray)
                             and x.ndim >= 1 for x in xs):
            return False
        n = xs[0].shape[0]
        return n >= 1 and all(x.shape[0] == n for x in xs)

    def submit(self, xs) -> "Future":
        ctx = tracing.current()  # forwarded as X-Zoo-Trace-Id
        return self._pool.submit(self._post_predict, list(xs), ctx)

    def predict(self, inputs, timeout_ms: int = -1):
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        return self._post_predict([np.asarray(x) for x in xs],
                                  tracing.current())

    def _post_predict(self, xs, ctx):
        import urllib.error
        import urllib.request
        if len(xs) == 1:
            inputs = xs[0].tolist()
        else:
            inputs = [{"data": x.tolist()} for x in xs]
        body = json.dumps({"inputs": inputs}).encode()
        req = urllib.request.Request(
            self.url + "/predict", data=body,
            headers={"Content-Type": "application/json"})
        if ctx is not None:
            req.add_header(tracing.TRACE_HEADER, ctx[0])
        t0 = time.time()
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = {}
            try:
                detail = json.loads(e.read()).get("error", {})
            except (ValueError, OSError):
                pass
            if e.code == 503:
                raise QueueFullError(
                    0, float(detail.get("retry_after_s", 1.0)))
            if e.code == 504:
                raise DeadlineExpiredError(
                    detail.get("message", "remote deadline expired"))
            raise RuntimeError(
                f"replica {self.name} HTTP {e.code}: "
                f"{detail.get('message', '')}")
        tracing.record_span(ctx, "fleet/remote_predict", t0,
                            time.time() - t0, replica=self.name)
        out = payload["outputs"]
        return np.asarray(out, np.float32)

    def probe(self) -> bool:
        import urllib.request
        try:
            with urllib.request.urlopen(
                    self.url + "/health", timeout=5.0) as resp:
                return json.loads(
                    resp.read()).get("status") == "ok"
        except Exception:
            return False

    def batcher_stats(self) -> dict:
        return {"enabled": False, "remote": self.url}

    def concurrency(self) -> int:
        return self._workers


class ReplicaPool:
    """Owns the fleet's replicas. Either wrap pre-built replicas
    (``ReplicaPool(replicas=[...])`` — mixed in-process/HTTP fleets
    are fine) or give a factory ``model_fn(ctx: ReplicaContext)``
    that builds one model per device slice; the pool then carves
    ``jax.devices()`` into ``n_replicas`` disjoint slices of
    ``devices_per_replica`` each (`parallel.replica_device_slices`)
    and wraps each model in a :class:`Replica`."""

    def __init__(self, model_fn: Optional[Callable] = None,
                 replicas: Optional[Sequence[_ReplicaBase]] = None,
                 n_replicas: Optional[int] = None,
                 devices_per_replica: Optional[int] = None,
                 devices: Optional[Sequence] = None,
                 batcher="auto",
                 batcher_kwargs: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        if replicas is not None:
            if model_fn is not None:
                raise ValueError(
                    "pass model_fn OR replicas, not both")
            self.replicas = list(replicas)
        else:
            if model_fn is None:
                raise ValueError("need model_fn or replicas")
            from analytics_zoo_tpu.parallel.mesh import \
                replica_device_slices
            if devices is None:
                import jax
                devices = jax.devices()
            k = devices_per_replica or _env_int(
                "ZOO_TPU_FLEET_DEVICES_PER_REPLICA", 1)
            n = n_replicas or _env_int("ZOO_TPU_FLEET_REPLICAS", 0) \
                or len(devices) // k
            slices = replica_device_slices(n, k, devices)
            self.replicas = []
            for i, sl in enumerate(slices):
                ctx = ReplicaContext(i, f"r{i}", sl)
                self.replicas.append(Replica(
                    ctx.name, model_fn(ctx), batcher=batcher,
                    batcher_kwargs=batcher_kwargs, clock=clock))
        if not self.replicas:
            raise ValueError("empty replica pool")
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")

    @classmethod
    def for_keras(cls, net, params=None,
                  example_inputs: Optional[Sequence] = None,
                  n_replicas: Optional[int] = None,
                  devices_per_replica: Optional[int] = None,
                  sharding: str = "auto",
                  devices: Optional[Sequence] = None,
                  concurrency: int = 1,
                  batcher="auto",
                  batcher_kwargs: Optional[dict] = None,
                  clock: Callable[[], float] = time.monotonic
                  ) -> "ReplicaPool":
        """N replicas of one in-memory KerasNet. Each replica's
        params are committed to its device slice
        (`parallel.place_inference_params`): a 1-device slice pins
        them to that device; a k-device slice builds a 1-D "model"
        mesh and applies the Megatron column split (``sharding="tp"``
        / ``"auto"``) or full replication (``"replicate"``). Because
        committed params steer jit placement, each replica's
        ``lower_for`` AOT-compiles its whole bucket ladder onto its
        own slice — N independent executables, no time-slicing."""
        from analytics_zoo_tpu.parallel.mesh import \
            place_inference_params
        from analytics_zoo_tpu.pipeline.inference.inference_model \
            import InferenceModel
        if params is None:
            try:
                est = net.estimator
                if est.params is None:
                    est._ensure_initialized()
                params = est.params
            except RuntimeError:
                # uncompiled net (inference-only): fresh init params
                params = net.init_params()

        def model_fn(ctx: ReplicaContext):
            placed = place_inference_params(params, ctx.devices,
                                            mode=sharding)
            im = InferenceModel(supported_concurrent_num=concurrency)
            im.load_keras_net(net, params=placed,
                              example_inputs=example_inputs)
            return im

        return cls(model_fn, n_replicas=n_replicas,
                   devices_per_replica=devices_per_replica,
                   devices=devices, batcher=batcher,
                   batcher_kwargs=batcher_kwargs, clock=clock)

    def start(self) -> "ReplicaPool":
        for r in self.replicas:
            r.start()
        _g_size().set(len(self.replicas))
        return self

    def stop(self):
        for r in self.replicas:
            try:
                r.stop()
            except Exception as e:
                logger.warning("fleet: stopping %s failed: %s",
                               r.name, e)

    def __len__(self):
        return len(self.replicas)

    def __repr__(self):
        states = {r.name: r.state for r in self.replicas}
        return f"ReplicaPool({states})"


class FleetRouter:
    """The fleet's front door. Duck-types the model AND batcher
    surfaces the HTTP front-ends expect, so
    ``make_inference_server(router)`` serves the whole fleet (the
    front-ends auto-use a router as its own batcher).

    Dispatch: ``policy="least_loaded"`` picks the admitting replica
    with the fewest outstanding rows (ties round-robin);
    ``policy="hash"`` routes by consistent hash over a virtual-node
    ring — same payload (or explicit ``key=``) lands on the same
    replica while it stays admitting (cache-warm affinity), walking
    the ring past down replicas."""

    def __init__(self, pool: ReplicaPool,
                 policy: Optional[str] = None,
                 max_retries: Optional[int] = None,
                 eject_after: Optional[int] = None,
                 probe_interval_s: Optional[float] = None,
                 vnodes: int = 64):
        self.pool = pool
        self.policy = policy or os.environ.get(
            "ZOO_TPU_FLEET_POLICY", "least_loaded")
        if self.policy not in ("least_loaded", "hash"):
            raise ValueError(
                f"unknown fleet policy {self.policy!r} "
                f"(least_loaded|hash)")
        self.max_retries = (max_retries if max_retries is not None
                            else _env_int("ZOO_TPU_FLEET_MAX_RETRIES",
                                          2))
        self.eject_after = (eject_after if eject_after is not None
                            else _env_int("ZOO_TPU_FLEET_EJECT_AFTER",
                                          3))
        self.probe_interval_s = (
            probe_interval_s if probe_interval_s is not None
            else _env_float("ZOO_TPU_FLEET_PROBE_S", 2.0))
        self._clock = pool.clock
        self._rr = 0  # least-loaded tie-breaker
        self._rr_lock = threading.Lock()
        self._ring = self._build_ring(vnodes)
        self._prober: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # canary traffic split, installed/cleared by the rollout
        # controller: {"version", "baseline", "pct"} or None
        self._canary: Optional[dict] = None
        self._cohort_rr = 0  # keyless-traffic bucket rotation
        self._rollout = None  # the active/last RolloutController
        # fleet telemetry plane (federation collector), created on
        # start(): TelemetryCollector or None
        self.telemetry = None

    # -- model-ish surface (serving.py duck-typing) --------------------------
    @property
    def example_input_specs(self):
        for r in self.pool.replicas:
            specs = r.input_specs()
            if specs:
                return specs
        return None

    @property
    def concurrent_slots_free(self) -> int:
        return sum(r.slots_free() for r in self.pool.replicas
                   if r.admitting())

    @property
    def supported_concurrent_num(self) -> int:
        return max(1, sum(r.concurrency()
                          for r in self.pool.replicas))

    def predict(self, inputs, timeout_ms: int = -1):
        """Per-request path (inputs the batcher cannot coalesce):
        synchronous dispatch with the same sibling-retry and
        failure-accounting semantics as :meth:`submit`."""
        _c_requests().inc()
        tried: set = set()
        last_exc: Optional[Exception] = None
        for attempt in range(self.max_retries + 1):
            r = self._pick(rows=1, key=None, exclude=tried)
            if r is None:
                break
            t0 = time.time()
            try:
                with obs.span("fleet/dispatch", replica=r.name,
                              attempt=attempt, path="predict"):
                    r.note_dispatch(1)
                    try:
                        out = r.predict(inputs,
                                        timeout_ms=timeout_ms)
                    finally:
                        r.note_done(1)
                r.note_success()
                _c_cohort_requests(r.version).inc()
                dt = time.time() - t0
                _h_cohort_latency(r.version).observe(dt)
                _h_replica_latency(r.name).observe(dt)
                return out
            except (QueueFullError, DeadlineExpiredError):
                raise  # backpressure/deadline: not a replica fault
            except Exception as e:
                last_exc = e
                tried.add(r.name)
                _c_cohort_requests(r.version).inc()
                _c_cohort_errors(r.version).inc()
                _c_replica_errors(r.name).inc()
                self._note_replica_failure(r, e)
                if attempt < self.max_retries:
                    _c_retries().inc()
        _c_failed().inc()
        if last_exc is not None:
            raise last_exc
        raise ReplicaUnavailableError(self._soonest_probe_s())

    # -- batcher-ish surface -------------------------------------------------
    def batchable(self, xs) -> bool:
        for r in self.pool.replicas:
            if r.admitting():
                return r.batchable(xs)
        return False

    def submit(self, xs, key: Optional[bytes] = None) -> "Future":
        """Dispatch one row-aligned request to a replica's batcher.
        Returns a ROUTER-level future: replica death mid-request
        re-dispatches the rows to a sibling (never a row whose
        future already resolved), bounded retries, then the failure
        surfaces. Fleet-wide saturation resolves the future with
        :class:`FleetSaturatedError` (HTTP 503 + min Retry-After)."""
        xs = [np.asarray(x) for x in xs]
        if not self.batchable(xs):
            raise ValueError(
                "inputs are not row-aligned (every input needs the "
                "same leading dimension >= 1)")
        _c_requests().inc()
        fut: "Future" = Future()
        if key is None and self.policy == "hash":
            key = self._affinity_key(xs)
        self._dispatch(xs, xs[0].shape[0], fut, key, attempt=0,
                       exclude=frozenset(), ctx=tracing.current())
        return fut

    def stats(self) -> dict:
        """Aggregate ``/health`` "batcher" block: fleet totals plus
        per-replica queue state."""
        per = {r.name: r.batcher_stats()
               for r in self.pool.replicas}
        return {
            "enabled": True,
            "fleet": True,
            "replicas_total": len(self.pool),
            "replicas_admitting": sum(
                1 for r in self.pool.replicas if r.admitting()),
            "queue_depth": sum(p.get("queue_depth", 0)
                               for p in per.values()),
            "queue_capacity": sum(p.get("queue_capacity", 0)
                                  for p in per.values()),
            "per_replica": per,
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetRouter":
        """Start every replica (each warms its own ladder), then the
        health prober (``ZOO_TPU_FLEET_PROBE_S <= 0`` → no thread;
        drive :meth:`tick` manually)."""
        self.pool.start()
        self._refresh_gauges()
        if self.probe_interval_s > 0 and self._prober is None:
            self._stop_evt.clear()
            self._prober = threading.Thread(
                target=self._probe_loop, name="zoo-fleet-prober",
                daemon=True)
            self._prober.start()
        if self.telemetry is None:
            # deferred import: federation pulls diagnostics/tracing,
            # fleet must stay importable without the telemetry plane
            from analytics_zoo_tpu.common import federation
            self.telemetry = federation.TelemetryCollector(self)
        self.telemetry.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._prober is not None:
            self._prober.join(timeout=5)
            self._prober = None
        if self.telemetry is not None:
            self.telemetry.stop()
        self.pool.stop()
        self._refresh_gauges()

    def _probe_loop(self):
        while not self._stop_evt.wait(self.probe_interval_s):
            try:
                self.tick()
            except Exception as e:  # prober must not die
                logger.warning("fleet prober: %s", e)

    def tick(self, now: Optional[float] = None) -> dict:
        """One health pass: try to revive replicas whose backoff
        expired (probe, then re-admit or double the backoff). Called
        by the prober thread, or manually from tests/smokes with an
        injected ``now``. Returns :meth:`fleet_status`."""
        now = self._clock() if now is None else now
        for r in self.pool.replicas:
            with r._lock:
                due = (r.state == DOWN
                       and r.down_reason != "stopped"
                       and r.next_probe_at <= now)
            if not due:
                continue
            if r.probe():
                try:
                    r.restart()
                except Exception as e:
                    logger.warning(
                        "fleet: restart of %s failed: %s",
                        r.name, e)
                    r.backoff_bump(now)
                    continue
                _c_readmissions(r.name).inc()
                obs.event("fleet/readmitted", replica=r.name)
                logger.info("fleet: replica %s re-admitted",
                            r.name)
            else:
                r.backoff_bump(now)
        self._refresh_gauges()
        rollout = self._rollout
        if rollout is not None and rollout.in_progress:
            try:
                rollout.tick(now=now)
            except Exception as e:  # the prober must not die
                logger.warning("fleet: rollout tick failed: %s", e)
        return self.fleet_status()

    def drain(self, name: str, timeout: float = 30.0) -> bool:
        """Gracefully drain one replica by name (stop admitting,
        flush in-flight, stop its batcher). Pair with
        ``restart_replica`` to complete a rolling reload."""
        r = self._replica(name)
        ok = r.drain(timeout=timeout)
        self._refresh_gauges()
        return ok

    def restart_replica(self, name: str):
        """Re-admit a drained replica (re-warms its ladder; a model
        reload in between is picked up via
        ``InferenceModel.generation``)."""
        r = self._replica(name)
        r.restart()
        self._refresh_gauges()
        return r

    def _replica(self, name: str) -> _ReplicaBase:
        for r in self.pool.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica named {name!r}")

    # -- versioned rollout ---------------------------------------------------
    def rollout(self, version, canary_pct: int = 25, **kwargs):
        """Warm-swap the fleet to ``version`` (a
        :class:`~analytics_zoo_tpu.pipeline.inference.registry.ModelVersion`
        or anything with ``name`` + ``load_into(model)``): drain one
        replica at a time behind the router (zero dropped acked
        requests — drained queues flush, the generation bump drops
        stale executables), then split ``canary_pct``% of traffic
        onto the new version and watch its cohort SLO. The canary
        either bakes clean and promotes to the rest of the fleet, or
        breaches and auto-rolls-back through the same drain path.
        Returns the :class:`~analytics_zoo_tpu.pipeline.
        inference.registry.RolloutController` (state machine at
        ``GET /debug/rollout``); ``kwargs`` forward
        to it (``bake_s``, ``max_canary_errors``, ...). The fleet
        prober drives its :meth:`tick`; with the prober disabled
        drive ``router.tick()`` manually (docs/robustness.md)."""
        from analytics_zoo_tpu.pipeline.inference.registry import \
            RolloutController
        active = self._rollout
        if active is not None and active.in_progress:
            raise RuntimeError(
                f"rollout of {active.version_name} still "
                f"{active.state}; finish or roll it back first")
        ctl = RolloutController(self, version,
                                canary_pct=canary_pct, **kwargs)
        self._rollout = ctl
        ctl.begin()
        return ctl

    def rollout_status(self) -> dict:
        """JSON-able rollout state — the ``GET /debug/rollout``
        payload (idle when no rollout ever ran)."""
        if self._rollout is None:
            return {"state": "idle", "canary": self._canary}
        st = self._rollout.status()
        st["canary"] = self._canary
        return st

    # -- dispatch ------------------------------------------------------------
    def _affinity_key(self, xs) -> bytes:
        """Deterministic content key for hash routing: shapes, dtypes
        and a bounded byte prefix of each input — identical payloads
        land on the same replica (cache-warm affinity)."""
        h = hashlib.blake2b(digest_size=8)
        for x in xs:
            h.update(str(x.shape).encode())
            h.update(str(x.dtype).encode())
            h.update(x.tobytes()[:1024])
        return h.digest()

    def _build_ring(self, vnodes: int):
        ring = []
        for r in self.pool.replicas:
            for v in range(vnodes):
                hv = int.from_bytes(
                    hashlib.blake2b(
                        f"{r.name}#{v}".encode(),
                        digest_size=8).digest(), "big")
                ring.append((hv, r))
        ring.sort(key=lambda t: t[0])
        self._ring_keys = [t[0] for t in ring]
        return ring

    def _cohort_version(self, key: Optional[bytes]) -> Optional[str]:
        """The model version this request's cohort should land on,
        or None when no canary split is active. Keyed traffic buckets
        deterministically off the affinity key (the same payload
        stays in the same cohort across its whole session — a request
        never flaps between versions); keyless traffic rotates
        ``pct``% round-robin."""
        canary = self._canary
        if not canary:
            return None
        if key is not None:
            hv = int.from_bytes(
                hashlib.blake2b(b"cohort:" + key,
                                digest_size=8).digest(), "big")
            bucket = hv % 100
        else:
            with self._rr_lock:
                self._cohort_rr = (self._cohort_rr + 1) % 100
                bucket = self._cohort_rr
        if bucket < canary["pct"]:
            return canary["version"]
        return canary["baseline"]

    def set_canary(self, version: str, baseline: str, pct: int):
        """Install a canary traffic split (rollout-controller API):
        ``pct``% of requests prefer replicas serving ``version``, the
        rest prefer ``baseline``. Preference, not a hard wall — when
        a cohort's replicas are all down/draining, its traffic spills
        to the other cohort (availability beats cohort purity)."""
        self._canary = {"version": str(version),
                        "baseline": str(baseline),
                        "pct": max(0, min(100, int(pct)))}
        obs.event("rollout/canary_split", version=version,
                  baseline=baseline, pct=self._canary["pct"])

    def clear_canary(self):
        self._canary = None

    def _pick_hash(self, key: bytes, exclude: set,
                   prefer_version: Optional[str] = None
                   ) -> Optional[_ReplicaBase]:
        if not self._ring:
            return None
        hv = int.from_bytes(
            hashlib.blake2b(key, digest_size=8).digest(), "big")
        start = bisect.bisect_left(self._ring_keys, hv)
        n = len(self._ring)
        fallback = None
        seen: set = set()
        for i in range(n):
            _, r = self._ring[(start + i) % n]
            if r.name in seen:
                continue
            seen.add(r.name)
            if r.name not in exclude and r.admitting():
                if (prefer_version is None
                        or r.version == prefer_version):
                    return r
                if fallback is None:
                    fallback = r  # wrong cohort, but admitting
        return fallback

    def _pick(self, rows: int, key: Optional[bytes],
              exclude: set) -> Optional[_ReplicaBase]:
        prefer = self._cohort_version(key)
        if key is not None:
            return self._pick_hash(key, exclude, prefer)
        cands = [r for r in self.pool.replicas
                 if r.admitting() and r.name not in exclude]
        if not cands:
            return None
        if prefer is not None:
            cohort = [r for r in cands if r.version == prefer]
            if cohort:  # spill to the other cohort only when empty
                cands = cohort
        lo = min(r.outstanding_rows for r in cands)
        ties = [r for r in cands if r.outstanding_rows == lo]
        with self._rr_lock:
            self._rr += 1
            return ties[self._rr % len(ties)]

    def _soonest_probe_s(self) -> float:
        """Retry hint when nothing admits: time to the next revival
        probe (floor 0.05s)."""
        now = self._clock()
        waits = [max(0.05, r.next_probe_at - now)
                 for r in self.pool.replicas if r.state == DOWN]
        return min(waits) if waits else 1.0

    def _dispatch(self, xs, rows, fut, key, attempt, exclude, ctx):
        """Pick a replica and hand it the rows; on synchronous
        queue-full try the next one; when every admitting replica is
        full resolve with the fleet-level 503 (min EMA hint)."""
        tried = set(exclude)
        busy_hints = []
        while True:
            r = self._pick(rows, key, tried)
            if r is None:
                if busy_hints:
                    _c_saturated().inc()
                    _c_failed().inc()
                    self._fail(fut, FleetSaturatedError(
                        len(busy_hints), min(busy_hints)))
                else:
                    _c_failed().inc()
                    self._fail(fut, ReplicaUnavailableError(
                        self._soonest_probe_s()))
                return
            t0 = time.time()
            try:
                inner = r.submit(xs)
            except QueueFullError as e:
                busy_hints.append(e.retry_after_s)
                tried.add(r.name)
                continue
            except Exception as e:  # broke at admission
                tried.add(r.name)
                # an admission fault is still an attempt the replica
                # failed: attribute it to its version cohort so a
                # sick canary trips the rollout burst/SLO watch even
                # when every failure happens before enqueue
                _c_cohort_requests(r.version).inc()
                _c_cohort_errors(r.version).inc()
                _c_replica_errors(r.name).inc()
                self._note_replica_failure(r, e)
                continue
            r.note_dispatch(rows)
            tracing.record_span(
                ctx, "fleet/dispatch", t0, time.time() - t0,
                replica=r.name, rows=rows, attempt=attempt)
            inner.add_done_callback(
                lambda f, r=r, t0=t0: self._on_replica_done(
                    r, f, xs, rows, fut, key, attempt, exclude,
                    ctx, t0))
            return

    def _on_replica_done(self, r, inner, xs, rows, fut, key,
                         attempt, exclude, ctx, t0=None):
        """Replica future resolved (dispatcher/executor thread).
        Success propagates; deadline expiry propagates (request-
        level, not a replica fault); queue-full retries a sibling
        without failure accounting; anything else counts against the
        replica (ejection past the threshold) and re-dispatches the
        rows on a sibling — the router future resolves exactly once,
        so acked work is never re-executed."""
        r.note_done(rows)
        exc = inner.exception()
        # cohort attribution: every attempt the replica actually
        # worked on counts for its version (queue-full never reached
        # the model, so it attributes to no cohort)
        if not isinstance(exc, QueueFullError):
            _c_cohort_requests(r.version).inc()
            if t0 is not None:
                dt = time.time() - t0
                _h_cohort_latency(r.version).observe(dt)
                _h_replica_latency(r.name).observe(dt)
            if exc is not None and not isinstance(
                    exc, DeadlineExpiredError):
                _c_cohort_errors(r.version).inc()
                _c_replica_errors(r.name).inc()
        if exc is None:
            r.note_success()
            self._resolve(fut, inner.result())
            return
        if isinstance(exc, DeadlineExpiredError):
            _c_failed().inc()
            self._fail(fut, exc)
            return
        is_busy = isinstance(exc, QueueFullError)
        if not is_busy:
            self._note_replica_failure(r, exc)
        if attempt >= self.max_retries:
            _c_failed().inc()
            self._fail(fut, exc)
            return
        _c_retries().inc()
        tracing.record_span(ctx, "fleet/retry", time.time(), 0.0,
                            replica=r.name, rows=rows,
                            attempt=attempt + 1,
                            error=type(exc).__name__)
        with tracing.activate(ctx):
            self._dispatch(xs, rows, fut, key, attempt + 1,
                           set(exclude) | {r.name}, ctx)

    def _note_replica_failure(self, r, exc):
        fails = r.note_failure()
        logger.warning("fleet: dispatch to %s failed (%s: %s), "
                       "consecutive=%d", r.name,
                       type(exc).__name__, exc, fails)
        if fails >= self.eject_after and r.admitting():
            r.mark_down(f"{type(exc).__name__}: {exc}",
                        now=self._clock())
            self._refresh_gauges()

    @staticmethod
    def _resolve(fut, value):
        try:
            fut.set_result(value)
        except Exception:
            pass  # already resolved (defensive; single-dispatch)

    @staticmethod
    def _fail(fut, exc):
        try:
            fut.set_exception(exc)
        except Exception:
            pass

    # -- introspection -------------------------------------------------------
    def _refresh_gauges(self):
        _g_admitting().set(sum(
            1 for r in self.pool.replicas if r.admitting()))
        _g_size().set(len(self.pool))

    def fleet_status(self) -> dict:
        """JSON-able fleet topology + lifecycle state — the
        ``GET /debug/fleet`` payload."""
        return {
            "policy": self.policy,
            "max_retries": self.max_retries,
            "eject_after": self.eject_after,
            "probe_interval_s": self.probe_interval_s,
            "replicas_admitting": sum(
                1 for r in self.pool.replicas if r.admitting()),
            "canary": self._canary,
            "replicas": [r.status() for r in self.pool.replicas],
        }

    def __repr__(self):
        return (f"FleetRouter(policy={self.policy}, "
                f"replicas={len(self.pool)})")


# -- disaggregated generation serving (prefill/decode pools) -----------------

def _c_handoff_retries():
    return obs.counter(
        "zoo_tpu_serving_gen_handoff_retries_total",
        help="handoffs retried after a pool replica failed "
             "mid-flight (the blob re-prefills on a sibling)")


class DisaggReplica(_ReplicaBase):
    """One in-process generation replica of a disaggregated pool: a
    role-specific :class:`GenerationEngine` (``role="prefill"`` or
    ``"decode"``) plus its OWN :class:`ContinuousBatcher`. The
    prefill surface returns handoff blobs; the decode surface
    consumes them (`docs/serving.md` has the topology)."""

    def __init__(self, name: str, engine,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(name, clock)
        self.engine = engine
        self.role = getattr(engine, "role", "both")
        self.batcher = ContinuousBatcher(engine)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "DisaggReplica":
        self.batcher.start()
        self._set_admitting()
        return self

    def stop(self):
        self.batcher.stop()
        with self._lock:
            self.state = DOWN
            self.down_reason = "stopped"
        _g_up(self.name).set(0)

    def drain(self, timeout: float = 30.0) -> bool:
        with self._lock:
            if self.state == DOWN:
                return True
            self.state = DRAINING
        _g_up(self.name).set(0)
        flushed = self.batcher.drain(timeout=timeout)
        with self._lock:
            self.state = DRAINED
        return flushed

    def restart(self) -> "DisaggReplica":
        self.batcher.start()
        self._set_admitting()
        return self

    def probe(self) -> bool:
        return True  # in-process: alive iff the loop thread is

    # -- generation transport ------------------------------------------------
    def prefill(self, prompt_ids, max_new: int,
                temperature: float) -> "Future":
        """Future resolving to a handoff blob (host dict)."""
        return self.batcher.submit_prefill(
            prompt_ids, max_new_tokens=max_new,
            temperature=temperature)

    def decode(self, blob: dict, max_new: int, eos_id) -> "Future":
        """Future resolving to the full new-token stream."""
        return self.batcher.submit_handoff(
            blob, max_new_tokens=max_new, eos_id=eos_id)

    # -- introspection -------------------------------------------------------
    def free_pages(self) -> int:
        return int(self.engine.free_pages)

    def total_pages(self) -> int:
        return int(self.engine.allocator.max_pages)

    def batcher_stats(self) -> dict:
        return self.batcher.stats()

    def status(self) -> dict:
        st = super().status()
        st["pages_free"] = self.free_pages()
        st["pages_total"] = self.total_pages()
        return st


class HttpDisaggReplica(_ReplicaBase):
    """A disaggregated-pool replica in another process behind the
    standard HTTP front-end: ``prefill`` POSTs ``/generate/prefill``
    (the handoff blob returns base64-encoded —
    `ops/kv_cache.handoff_to_wire`), ``decode`` POSTs
    ``/generate/handoff``. The ambient trace id rides
    ``X-Zoo-Trace-Id`` on both legs, so one trace spans admission →
    prefill replica → page hop → decode replica. Page headroom for
    routing comes from the remote ``/health`` generator block
    (briefly cached — headroom staleness only costs balance, never
    correctness)."""

    def __init__(self, url: str, role: str,
                 name: Optional[str] = None,
                 timeout_s: float = 60.0, workers: int = 8,
                 clock: Callable[[], float] = time.monotonic):
        self.url = url.rstrip("/")
        if name is None:
            name = self.url.split("//", 1)[-1].replace(
                "/", "_").replace(":", "_")
        super().__init__(name, clock)
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"bad pool role {role!r}")
        self.role = role
        self.timeout_s = float(timeout_s)
        self._workers = int(workers)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pages_cache = (0.0, 0, 0)  # (stamp, free, total)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "HttpDisaggReplica":
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix=f"zoo-disagg-{self.name}")
        self._set_admitting()
        return self

    def stop(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        with self._lock:
            self.state = DOWN
            self.down_reason = "stopped"
        _g_up(self.name).set(0)

    def restart(self) -> "HttpDisaggReplica":
        return self.start()

    # -- transport -----------------------------------------------------------
    def _post(self, path: str, payload: dict, ctx):
        import urllib.error
        import urllib.request
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.url + path, data=body,
            headers={"Content-Type": "application/json"})
        if ctx is not None:
            req.add_header(tracing.TRACE_HEADER, ctx[0])
        t0 = time.time()
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = {}
            try:
                detail = json.loads(e.read()).get("error", {})
            except (ValueError, OSError):
                pass
            if e.code == 503:
                raise QueueFullError(
                    0, float(detail.get("retry_after_s", 1.0)))
            if e.code == 400:
                raise ValueError(detail.get("message", "bad request"))
            raise RuntimeError(
                f"replica {self.name} HTTP {e.code}: "
                f"{detail.get('message', '')}")
        tracing.record_span(ctx, "fleet/remote_generate", t0,
                            time.time() - t0, replica=self.name,
                            path=path)
        return out

    def prefill(self, prompt_ids, max_new: int,
                temperature: float) -> "Future":
        from analytics_zoo_tpu.ops.kv_cache import handoff_from_wire
        ctx = tracing.current()

        def run():
            out = self._post("/generate/prefill", {
                "prompt": [int(t) for t in prompt_ids],
                "max_new_tokens": int(max_new),
                "temperature": float(temperature)}, ctx)
            return handoff_from_wire(out["handoff"])

        return self._pool.submit(run)

    def decode(self, blob: dict, max_new: int, eos_id) -> "Future":
        from analytics_zoo_tpu.ops.kv_cache import handoff_to_wire
        ctx = tracing.current()

        def run():
            out = self._post("/generate/handoff", {
                "handoff": handoff_to_wire(blob),
                "max_new_tokens": int(max_new),
                "eos_id": eos_id}, ctx)
            return np.asarray(out["tokens"], np.int32)

        return self._pool.submit(run)

    def probe(self) -> bool:
        import urllib.request
        try:
            with urllib.request.urlopen(
                    self.url + "/health", timeout=5.0) as resp:
                return json.loads(
                    resp.read()).get("status") == "ok"
        except Exception:
            return False

    # -- introspection -------------------------------------------------------
    def _pages(self) -> "tuple[int, int]":
        import urllib.request
        now = time.monotonic()
        stamp, free, total = self._pages_cache
        if now - stamp < 0.5:
            return free, total
        try:
            with urllib.request.urlopen(
                    self.url + "/health", timeout=5.0) as resp:
                gen = json.loads(resp.read()).get("generator") or {}
            free = int(gen.get("free_pages", 0))
            total = int(gen.get("total_pages", 0))
        except Exception:
            free, total = 0, 0  # unknown: route elsewhere first
        self._pages_cache = (now, free, total)
        return free, total

    def free_pages(self) -> int:
        return self._pages()[0]

    def total_pages(self) -> int:
        return self._pages()[1]

    def batcher_stats(self) -> dict:
        return {"enabled": False, "remote": self.url}

    def status(self) -> dict:
        st = super().status()
        free, total = self._pages()
        st["pages_free"] = free
        st["pages_total"] = total
        return st


class DisaggRouter:
    """``/generate`` front door for a disaggregated fleet (DistServe/
    Splitwise prefill–decode separation): admission goes to the
    least-loaded **prefill** replica, which runs the prompt to its
    first token and exports a KV-page handoff blob; the router ships
    the blob — in-process dict or base64 pages over HTTP — to the
    **decode** replica with the most free pages, whose future
    resolves the full token stream. Compute-bound prefill and
    bandwidth-bound decode each scale on their own bottleneck
    (capacity = pages).

    Duck-types the gen-batcher surface (``submit`` / ``stats`` /
    ``start`` / ``stop``), so the HTTP front-ends mount it as
    ``gen_batcher`` unchanged; :func:`serving._resolve_gen_batcher`
    builds one automatically when ``ZOO_TPU_DISAGG`` is set.

    **Exactly-once.** The router-level future resolves once. A
    replica dying mid-handoff fails only its leg: the blob is
    dropped (its pages were already reclaimed at export) and the
    request re-prefills from the original prompt on a surviving
    replica — greedy decoding is deterministic, so a retried stream
    is byte-identical and acked tokens are never lost or reordered.
    """

    def __init__(self, prefill_replicas, decode_replicas, *,
                 max_retries: Optional[int] = None,
                 request_timeout_s: Optional[float] = None,
                 eject_after: int = 1):
        self.prefill = list(prefill_replicas)
        self.decode = list(decode_replicas)
        if not self.prefill or not self.decode:
            raise ValueError(
                "DisaggRouter needs >= 1 prefill and >= 1 decode "
                "replica")
        self.max_retries = (
            max_retries if max_retries is not None
            else _env_int("ZOO_TPU_FLEET_MAX_RETRIES", 2))
        self.request_timeout_s = (
            request_timeout_s if request_timeout_s is not None
            else _env_float("ZOO_TPU_DISAGG_TIMEOUT_S", 120.0))
        self.eject_after = max(1, int(eject_after))
        self._clock = time.monotonic
        self._pool: Optional[ThreadPoolExecutor] = None

    @classmethod
    def for_engine(cls, engine,
                   n_prefill: Optional[int] = None,
                   n_decode: Optional[int] = None,
                   **kwargs) -> "DisaggRouter":
        """Carve an in-process disaggregated fleet out of one
        template engine: ``n_prefill`` role-"prefill" engines and
        ``n_decode`` role-"decode" engines sharing the template's
        net/params and cache geometry (pool sizes default to
        ``ZOO_TPU_DISAGG_PREFILL_REPLICAS`` /
        ``ZOO_TPU_DISAGG_DECODE_REPLICAS``, both 1). The template
        itself is not used — each pool engine owns its own cache."""
        from analytics_zoo_tpu.pipeline.inference.generation import \
            GenerationEngine
        if getattr(engine, "spec_k", 0) > 0:
            raise ValueError(
                "speculative decoding is incompatible with "
                "disaggregated pools (unset ZOO_TPU_SPEC_K or "
                "ZOO_TPU_DISAGG)")
        if n_prefill is None:
            n_prefill = _env_int("ZOO_TPU_DISAGG_PREFILL_REPLICAS",
                                 1)
        if n_decode is None:
            n_decode = _env_int("ZOO_TPU_DISAGG_DECODE_REPLICAS", 1)

        def make(role, i):
            eng = GenerationEngine(
                engine.net, engine.params,
                max_slots=engine.max_slots,
                max_context=engine.max_context,
                page_size=engine.page_size,
                top_k=engine.top_k,
                cache_dtype=engine.cache_dtype,
                prefill_chunk=(engine.prefill_chunk
                               if role == "prefill" else 0),
                role=role)
            return DisaggReplica(f"{role}{i}", eng)

        return cls([make("prefill", i) for i in range(n_prefill)],
                   [make("decode", i) for i in range(n_decode)],
                   **kwargs)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "DisaggRouter":
        for r in self.prefill + self.decode:
            r.start()
        if self._pool is None:
            # each in-flight request parks one worker on a pool
            # future; size well past total decode slots so the
            # router never queues ahead of the pools' own admission
            workers = 8 * (len(self.prefill) + len(self.decode))
            self._pool = ThreadPoolExecutor(
                max_workers=max(32, workers),
                thread_name_prefix="zoo-disagg-router")
        _g_size().set(len(self.prefill) + len(self.decode))
        self._refresh_gauges()
        return self

    def stop(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        for r in self.prefill + self.decode:
            try:
                r.stop()
            except Exception as e:
                logger.warning("disagg: stopping %s failed: %s",
                               r.name, e)
        self._refresh_gauges()

    def _refresh_gauges(self):
        _g_admitting().set(sum(
            1 for r in self.prefill + self.decode
            if r.admitting()))

    # -- request path --------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, eos_id=None) -> "Future":
        """Gen-batcher surface: future resolves to the 1-D int32
        array of newly generated tokens, byte-identical (greedy) to
        a monolithic engine's stream."""
        ids = [int(t) for t in prompt_ids]
        _c_requests().inc()
        fut: "Future" = Future()
        ctx = tracing.current()
        self._pool.submit(self._run_request, ids,
                          int(max_new_tokens), float(temperature),
                          eos_id, fut, ctx)
        return fut

    def _pick_prefill(self, exclude: set):
        cands = [r for r in self.prefill
                 if r.admitting() and r.name not in exclude]
        if not cands:
            return None
        return min(cands, key=lambda r: r.outstanding_rows)

    def _pick_decode(self, exclude: set):
        cands = [r for r in self.decode
                 if r.admitting() and r.name not in exclude]
        if not cands:
            return None
        # page headroom is the decode pool's capacity currency
        return max(cands, key=lambda r: r.free_pages())

    def _note_failure(self, r, exc):
        fails = r.note_failure()
        _c_replica_errors(r.name).inc()
        logger.warning("disagg: %s leg on %s failed (%s: %s)",
                       r.role, r.name, type(exc).__name__, exc)
        if fails >= self.eject_after and r.admitting():
            r.mark_down(f"{type(exc).__name__}: {exc}",
                        now=self._clock())
            self._refresh_gauges()

    def _run_request(self, ids, max_new, temperature, eos_id, fut,
                     ctx):
        with tracing.activate(ctx):
            try:
                toks = self._generate_once(ids, max_new,
                                           temperature, eos_id,
                                           ctx)
            except Exception as exc:
                _c_failed().inc()
                FleetRouter._fail(fut, exc)
                return
        FleetRouter._resolve(fut, toks)

    def _generate_once(self, ids, max_new, temperature, eos_id,
                       ctx):
        bad_p: set = set()
        bad_d: set = set()
        busy_hints: "list[float]" = []
        last_exc: Optional[Exception] = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                _c_retries().inc()
                _c_handoff_retries().inc()
            # leg 1: prefill to first token + handoff blob
            p = self._pick_prefill(bad_p)
            if p is None:
                break
            t0 = time.time()
            try:
                with obs.span("fleet/prefill_dispatch",
                              replica=p.name, attempt=attempt):
                    p.note_dispatch(1)
                    try:
                        blob = p.prefill(
                            ids, max_new, temperature).result(
                            self.request_timeout_s)
                    finally:
                        p.note_done(1)
                p.note_success()
                _h_replica_latency(p.name).observe(
                    time.time() - t0)
            except QueueFullError as e:
                busy_hints.append(e.retry_after_s)
                bad_p.add(p.name)  # full, not dead: just skip it
                continue
            except ValueError:
                raise  # client error: no retry can fix the request
            except Exception as e:
                last_exc = e
                bad_p.add(p.name)
                self._note_failure(p, e)
                continue
            first = int(blob["last_token"])
            if ((eos_id is not None and first == eos_id)
                    or max_new <= 1):
                # done at prefill: no pages to ship, no decode leg
                return np.asarray([first], np.int32)
            # leg 2: ship the pages, resume decode
            d = self._pick_decode(bad_d)
            if d is None:
                break
            t0 = time.time()
            try:
                with obs.span("fleet/handoff", replica=d.name,
                              attempt=attempt,
                              seq_len=blob["seq_len"]):
                    d.note_dispatch(1)
                    try:
                        toks = d.decode(
                            blob, max_new, eos_id).result(
                            self.request_timeout_s)
                    finally:
                        d.note_done(1)
                d.note_success()
                _h_replica_latency(d.name).observe(
                    time.time() - t0)
                return np.asarray(toks, np.int32)
            except QueueFullError as e:
                busy_hints.append(e.retry_after_s)
                bad_d.add(d.name)
                continue  # blob dropped; re-prefill on a sibling
            except ValueError:
                raise
            except Exception as e:
                # mid-handoff death: the blob dies with the leg
                # (prefill-side pages were reclaimed at export, so
                # nothing leaks) and the request re-prefills from
                # the original prompt — acked tokens only ever come
                # from a future that resolved, exactly once
                last_exc = e
                bad_d.add(d.name)
                self._note_failure(d, e)
                continue
        _c_failed().inc()
        if last_exc is not None:
            raise last_exc
        if busy_hints:
            _c_saturated().inc()
            raise FleetSaturatedError(len(busy_hints),
                                      min(busy_hints))
        raise ReplicaUnavailableError(1.0)

    # -- drain / introspection ----------------------------------------------
    def drain(self, timeout: float = 30.0) -> bool:
        ok = True
        for r in self.prefill + self.decode:
            if hasattr(r, "drain"):
                ok = r.drain(timeout=timeout) and ok
        self._refresh_gauges()
        return ok

    def _pool_block(self, replicas) -> dict:
        return {
            "replicas": len(replicas),
            "admitting": sum(1 for r in replicas
                             if r.admitting()),
            "pages_free": sum(r.free_pages() for r in replicas),
            "pages_total": sum(r.total_pages() for r in replicas),
        }

    def stats(self) -> dict:
        """``/health`` "generator" block: per-pool page headroom +
        per-replica batcher state."""
        out = {
            "enabled": True,
            "disagg": True,
            "pools": {
                "prefill": self._pool_block(self.prefill),
                "decode": self._pool_block(self.decode),
            },
            "per_replica": {
                r.name: r.batcher_stats()
                for r in self.prefill + self.decode},
        }
        depth = sum(
            p.get("queue_depth", 0)
            for p in out["per_replica"].values()
            if isinstance(p, dict))
        out["queue_depth"] = depth
        return out

    def fleet_status(self) -> dict:
        """``GET /debug/fleet`` payload for a disaggregated fleet:
        role-tagged replicas + per-pool page headroom."""
        return {
            "disagg": True,
            "max_retries": self.max_retries,
            "replicas_admitting": sum(
                1 for r in self.prefill + self.decode
                if r.admitting()),
            "pools": {
                "prefill": self._pool_block(self.prefill),
                "decode": self._pool_block(self.decode),
            },
            "replicas": [r.status()
                         for r in self.prefill + self.decode],
        }

    def __repr__(self):
        return (f"DisaggRouter(prefill={len(self.prefill)}, "
                f"decode={len(self.decode)})")


def make_fleet_server(pool_or_router, port: int = 0,
                      prefer_native: bool = True):
    """Serve a fleet behind the standard front-ends: wraps a
    :class:`ReplicaPool` in a :class:`FleetRouter` (pass a router to
    choose policy/retries) and mounts it as both the model and the
    batcher — ``/predict``, ``/health``, ``/metrics``,
    ``/debug/fleet`` and friends all work (docs/serving.md)."""
    from analytics_zoo_tpu.pipeline.inference.serving import \
        make_inference_server
    router = pool_or_router
    if isinstance(router, ReplicaPool):
        router = FleetRouter(router)
    return make_inference_server(router, port=port,
                                 prefer_native=prefer_native,
                                 batcher=router)
